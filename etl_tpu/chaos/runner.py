"""Scenario runner: arm faults, drive the workload, crash, restart,
verify.

One `run_scenario(scenario, seed)` call:

  1. seeds `random.Random(seed)` — the ONLY randomness source — and
     builds the fake walsender database, a recording store, and a
     tracing MemoryDestination behind the fault-injecting wrapper;
  2. arms every `FaultSpec` (failpoint errors, hard crashes, scripted
     destination faults, wire severs) and records each firing into the
     per-site injection trace;
  3. runs the workload: initial copy → CDC transactions → drain. A
     CRASH firing hard-kills the pipeline (every task cancelled, no
     drain — process-death semantics) and restarts a fresh `Pipeline`
     from the same store/destination, resuming the remaining workload;
  4. checks the recovery invariants (chaos/invariants.py) and reports
     chaos metrics (telemetry/metrics.py).

Same (scenario, seed) → same workload bytes and same injection trace:
the run is replayable from the CLI (`python -m etl_tpu.chaos`).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from ..config import (BatchConfig, BatchEngine, PipelineConfig, RetryConfig,
                      SupervisionConfig)
from ..destinations import (FaultAction, FaultInjectingDestination, FaultKind
                            as DestFaultKind, MemoryDestination)
from ..models import ColumnSchema, Oid, TableName, TableSchema
from ..models.lsn import Lsn
from ..models.errors import EtlError
from ..models.table_state import TableStateType
from ..postgres.fake import FakeDatabase, FakeSource
from ..postgres.slots import apply_slot_name
from ..store import NotifyingStore
from ..telemetry.metrics import (ETL_CHAOS_INJECTED_FAULTS_TOTAL,
                                 ETL_CHAOS_RECOVERY_DURATION_SECONDS,
                                 ETL_CHAOS_SCENARIOS_TOTAL, registry)
from . import failpoints
from .invariants import (InvariantReport, LeakProbe, check_invariants,
                         view_matches)
from .scenario import FaultKind, FaultSpec, Scenario

BASE_TABLE_ID = 16384
_DEST_OPS = ("write_events", "write_table_rows", "truncate_table",
             "drop_table")


# -- program-cache restart scenarios (ISSUE 12) -------------------------------

#: row buckets seeded AND prewarmed for program-cache scenarios — one
#: tuple so the seed can never drift from what the restarted pipeline
#: warms (covers every bucket the scenarios' flushes can stage into:
#: txs × rows_per_tx stays well under 4096)
_PC_PREWARM_BUCKETS = (256, 1024, 4096)


def _clear_program_memory_caches() -> None:
    """Process-death semantics for the decode-program state a real crash
    would free with the process: the in-process program cache and the
    background-compile bookkeeping. The program-cache scenarios clear
    these at setup (so seeding provably writes to DISK) and at every
    hard restart (so the restarted pipeline can only be warm via the
    disk layer — exactly what a new process would see)."""
    from ..ops import engine as engine_mod

    with engine_mod._SHARED_FN_LOCK:
        engine_mod._SHARED_FN_CACHE.clear()
    with engine_mod._BG_COMPILE_LOCK:
        engine_mod._BG_COMPILE_KEYS.clear()
        engine_mod._BG_COMPILE_FAILED.clear()


def _corrupt_program_cache(cache_dir: str) -> None:
    """Overwrite every serialized executable with garbage (the
    power-loss / torn-disk case the degrade contract covers)."""
    import os

    for root, _dirs, files in os.walk(cache_dir):
        for f in files:
            if f.endswith(".prog"):
                with open(os.path.join(root, f), "wb") as fh:
                    fh.write(b"not a serialized executable")


def _program_cache_counters() -> dict:
    from ..telemetry.metrics import (ETL_COMPILE_CACHE_HITS_TOTAL,
                                     ETL_COMPILE_CACHE_MISSES_TOTAL,
                                     ETL_PROGRAMS_COMPILED_TOTAL)

    return {
        "compiled": registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL),
        "disk_hits": registry.get_counter(ETL_COMPILE_CACHE_HITS_TOTAL,
                                          {"layer": "disk"}),
        "invalid": registry.get_counter(ETL_COMPILE_CACHE_MISSES_TOTAL,
                                        {"reason": "invalid"}),
    }


class SimulatedCrash(Exception):
    """Raised at a CRASH site; the watcher hard-kills the pipeline before
    any in-process retry can proceed."""


class RecordingStore(NotifyingStore):
    """NotifyingStore that records the stored durable-progress trajectory
    per key (the monotonic-lsn invariant's evidence)."""

    def __init__(self) -> None:
        super().__init__()
        self.progress_log: dict[str, list[Lsn]] = {}

    async def update_durable_progress(self, key, lsn) -> bool:
        stored = await super().update_durable_progress(key, lsn)
        if stored:
            self.progress_log.setdefault(key, []).append(lsn)
        return stored


class TracingDestination(MemoryDestination):
    """MemoryDestination that remembers WHERE in the event timeline each
    destination drop happened, so the invariant checker can exclude
    events of abandoned (dropped-and-recopied) copy attempts."""

    def __init__(self) -> None:
        super().__init__()
        self.drop_seq_by_table: dict = {}
        self.held_ack_count = 0  # set by the runner after shutdown

    async def drop_table(self, table_id, schema=None) -> None:
        self.drop_seq_by_table[table_id] = len(self.events)
        await super().drop_table(table_id, schema)


@dataclass
class RestartRecord:
    kind: str  # "crash" | "clean"
    resume_lsn: int
    at_tx: int
    recovery_s: float = 0.0

    def describe(self) -> dict:
        return {"kind": self.kind, "resume_lsn": self.resume_lsn,
                "at_tx": self.at_tx,
                "recovery_s": round(self.recovery_s, 4)}


@dataclass
class ChaosRun:
    scenario: Scenario
    seed: int
    trace: dict[str, list[dict]] = field(default_factory=dict)
    restarts: list[RestartRecord] = field(default_factory=list)
    report: InvariantReport = field(default_factory=InvariantReport)
    fault_firings: int = 0  # every injection, for the trace
    # only firings that can cause re-delivery (worker retry re-streams):
    # the bounded-dup budget — OOM fallbacks, HOLDs, and crashes (already
    # counted via restarts) must NOT loosen the exactly-once assertion
    redelivery_firings: int = 0
    # supervision: health-state transitions observed across every
    # pipeline incarnation, and watchdog cancel-and-restart escalations
    # (each one re-streams a window, so each adds to the dup budget)
    health_track: list[str] = field(default_factory=list)
    supervision_restarts: int = 0
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "workload": self.scenario.workload or "default",
            "seed": self.seed,
            "ok": self.ok,
            "trace": {site: list(fires)
                      for site, fires in sorted(self.trace.items())},
            "restarts": [r.describe() for r in self.restarts],
            "fault_firings": self.fault_firings,
            "redelivery_firings": self.redelivery_firings,
            "health_track": list(self.health_track),
            "supervision_restarts": self.supervision_restarts,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


def _make_workload(scenario: Scenario, rng: random.Random):
    """The scenario's traffic source: a named workload profile
    (etl_tpu/workloads — update/delete/TOAST/truncate/DDL/partitioned
    shapes) when `scenario.workload` is set, else the default mixed-insert
    workload below. Both expose the same interface (build_db / run_tx /
    table_ids / expected / tx_index / delivered) and draw from the
    scenario's single seeded RNG, so the injection interleaving replays
    bit-identically either way."""
    if scenario.workload:
        from ..workloads import make_chaos_workload

        return make_chaos_workload(scenario.workload, rng)
    return _Workload(scenario, rng)


class _Workload:
    """Deterministic workload state: per-table expected rows + next pk."""

    def __init__(self, scenario: Scenario, rng: random.Random):
        self.scenario = scenario
        self.rng = rng
        self.table_ids = [BASE_TABLE_ID + i for i in range(scenario.tables)]
        self.expected: dict[int, dict[int, tuple]] = \
            {tid: {} for tid in self.table_ids}
        self._next_pk: dict[int, int] = {tid: 1 for tid in self.table_ids}
        self.tx_index = 0

    def build_db(self) -> FakeDatabase:
        db = FakeDatabase()
        for i, tid in enumerate(self.table_ids):
            rows = []
            for _ in range(self.scenario.rows_per_table):
                pk = self._next_pk[tid]
                self._next_pk[tid] += 1
                v = self.rng.randrange(0, 1000)
                note = f"seed-{self.rng.randrange(10**6)}"
                rows.append([str(pk), str(v), note])
                self.expected[tid][pk] = (pk, v, note)
            db.create_table(TableSchema(
                tid, TableName("public", f"chaos_t{i}"),
                (ColumnSchema("id", Oid.INT8, nullable=False,
                              primary_key_ordinal=1),
                 ColumnSchema("v", Oid.INT4),
                 ColumnSchema("note", Oid.TEXT))), rows=rows)
        db.create_publication("pub", list(self.table_ids))
        return db

    async def run_tx(self, db: FakeDatabase) -> None:
        """One CDC transaction: inserts, sometimes an update or delete."""
        rng = self.rng
        tid = self.table_ids[rng.randrange(len(self.table_ids))]
        exp = self.expected[tid]
        async with db.transaction() as tx:
            for _ in range(self.scenario.rows_per_tx):
                roll = rng.random()
                existing = sorted(exp)
                if roll < 0.15 and existing:  # delete
                    pk = existing[rng.randrange(len(existing))]
                    tx.delete(tid, [str(pk), None, None])
                    del exp[pk]
                elif roll < 0.40 and existing:  # update
                    pk = existing[rng.randrange(len(existing))]
                    v = rng.randrange(0, 1000)
                    note = f"upd-{rng.randrange(10**6)}"
                    tx.update(tid, [str(pk), None, None],
                              [str(pk), str(v), note])
                    exp[pk] = (pk, v, note)
                else:  # insert
                    pk = self._next_pk[tid]
                    self._next_pk[tid] += 1
                    v = rng.randrange(0, 1000)
                    note = f"ins-{rng.randrange(10**6)}"
                    tx.insert(tid, [str(pk), str(v), note])
                    exp[pk] = (pk, v, note)
        self.tx_index += 1

    def delivered(self, dest: TracingDestination) -> bool:
        return view_matches(dest, self.table_ids, self.expected)


class _CrashState:
    """Crash flag settable from ANY thread: failpoint sites on the decode
    pipeline's worker thread (pipeline.*) trip it via
    call_soon_threadsafe, sites on the event loop set it directly."""

    def __init__(self) -> None:
        self.event = asyncio.Event()
        self._loop = asyncio.get_running_loop()

    def trip(self) -> None:
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            self.event.set()
        else:
            self._loop.call_soon_threadsafe(self.event.set)


async def _race_crash(crash: _CrashState, coro) -> None:
    """Run `coro` unless/until the crash trips; on crash, cancel it and
    raise SimulatedCrash to the caller's restart loop."""
    task = asyncio.ensure_future(coro)
    crash_task = asyncio.ensure_future(crash.event.wait())
    try:
        done, _ = await asyncio.wait({task, crash_task},
                                     return_when=asyncio.FIRST_COMPLETED)
        if crash_task in done and crash.event.is_set():
            if task.done():
                # both finished in the same round: retrieve the task's
                # outcome so a real failure is not silently dropped as
                # "exception was never retrieved" noise
                task.exception()
            raise SimulatedCrash()
        return task.result()
    finally:
        for t in (task, crash_task):
            if not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass  # cancel-then-drain of our own helper tasks


async def _hard_kill(pipeline) -> None:
    """Process-death semantics: cancel every pipeline task with no drain
    and no destination shutdown. In-process resources that a real crash
    would free with the process (decode-pipeline threads, the memory
    monitor's sampler, the supervision sweep) are closed via the tasks'
    finally blocks."""
    if pipeline.supervisor is not None:
        await pipeline.supervisor.stop()
    tasks = []
    if pipeline._apply_task is not None:
        tasks.append(pipeline._apply_task)
    pool = pipeline.pool
    if pool is not None:
        tasks += [h.task for h in pool._workers.values()
                  if h.task is not None]
        tasks += list(pool._retry_tasks.values())
        pool._retry_tasks.clear()
    for t in tasks:
        if not t.done():
            t.cancel()
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    if pipeline.memory_monitor is not None:
        await pipeline.memory_monitor.stop()


async def _wait_until(predicate, timeout: float, what: str,
                      interval: float = 0.02) -> None:
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() >= deadline:
            raise TimeoutError(what)
        await asyncio.sleep(interval)


async def run_scenario(scenario: Scenario, seed: int,
                       timeout_s: float = 60.0) -> ChaosRun:
    """Run one scenario to completion and verify invariants. Always
    disarms every failpoint on the way out."""
    failpoints.disarm_all()
    run = ChaosRun(scenario=scenario, seed=seed)
    t_start = time.monotonic()
    try:
        await asyncio.wait_for(_run_scenario_inner(scenario, seed, run),
                               timeout_s)
    except (TimeoutError, asyncio.TimeoutError) as e:
        run.report.fail(f"scenario did not complete: {e or 'timeout'}")
    except Exception as e:
        # an unexpected error is a FAILED run, not a pass with an empty
        # report — the metrics and run.ok must say so
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.disarm_all()
        run.duration_s = time.monotonic() - t_start
        registry.counter_inc(
            ETL_CHAOS_SCENARIOS_TOTAL,
            labels={"result": "pass" if run.ok else "fail"})
    return run


async def _run_scenario_inner(scenario: Scenario, seed: int,
                              run: ChaosRun) -> None:
    rng = random.Random(seed)
    leak_probe = LeakProbe.capture()
    workload = _make_workload(scenario, rng)
    db = workload.build_db()
    pc_dir = None
    pc_base = None
    pc_restart_base = None
    if scenario.program_cache:
        # seed a private cache dir with this workload's host programs
        # (AOT-compiled + serialized), then make the in-process caches
        # look like a fresh process — from here on, warmth can only come
        # from disk (_PC_PREWARM_BUCKETS keeps the warm assertion from
        # ever flaking on flush sizing).
        import tempfile

        from ..models import ReplicatedTableSchema
        from ..ops import program_store

        pc_dir = tempfile.mkdtemp(prefix="etl-chaos-progcache-")
        program_store.configure(pc_dir)
        _clear_program_memory_caches()
        schemas = [ReplicatedTableSchema.with_all_columns(
            db.tables[tid].schema) for tid in workload.table_ids]
        await asyncio.to_thread(program_store.warm_host_programs,
                                schemas, _PC_PREWARM_BUCKETS, True)
        _clear_program_memory_caches()
        pc_base = _program_cache_counters()
    store = RecordingStore()
    inner = TracingDestination()
    dest = FaultInjectingDestination(inner)
    crash = _CrashState()
    held_releases: list[tuple[asyncio.Event, int | None]] = []

    def record_fire(spec: FaultSpec, action: str) -> None:
        fires = run.trace.setdefault(spec.site, [])
        fires.append({"fire": len(fires) + 1, "action": action,
                      "error_kind": spec.error_kind.name})
        run.fault_firings += 1
        if spec.kind in (FaultKind.ERROR, FaultKind.DEST_REJECT,
                         FaultKind.DEST_FAIL_AFTER_APPLY, FaultKind.SEVER) \
                and spec.site != failpoints.ENGINE_DEVICE_OOM:
            # faults the worker recovers from by re-streaming; crashes
            # are accounted via restarts, OOM fallbacks and HOLDs never
            # re-deliver. STALL firings fund NOTHING here — a stall
            # causes re-delivery only through its recovery mechanism,
            # and both mechanisms are counted where they fire (a
            # supervision restart via on_supervision_event, a
            # destination-op timeout via the counter delta at the end) —
            # funding the firing too would double the budget and loosen
            # the exactly-once assertion.
            run.redelivery_firings += 1
        registry.counter_inc(ETL_CHAOS_INJECTED_FAULTS_TOTAL,
                             labels={"site": spec.site})

    def arm_failpoint(spec: FaultSpec) -> None:
        state = {"hits": 0, "fired": 0}

        def action() -> None:
            state["hits"] += 1
            if state["hits"] <= spec.after_hits \
                    or state["fired"] >= spec.times:
                return
            state["fired"] += 1
            if spec.kind is FaultKind.CRASH:
                record_fire(spec, "crash")
                crash.trip()
                raise SimulatedCrash(f"simulated crash at {spec.site}")
            record_fire(spec, "error")
            raise EtlError(spec.error_kind,
                           f"chaos injection at {spec.site}")

        failpoints.arm(spec.site, action)

    # firings are recorded when the wrapper actually CONSUMES a scripted
    # fault, not at scripting time — the trace must never claim an
    # injection that didn't happen, and the bounded-dup budget must not
    # be inflated by scripts the workload never reached. The per-op spec
    # FIFO mirrors the wrapper's own FIFO action queue exactly.
    scripted_specs: dict[str, list[FaultSpec]] = {}
    _orig_next_fault = dest._next_fault

    def _observing_next_fault(op: str):
        fault = _orig_next_fault(op)
        if fault is not None:
            pending = scripted_specs.get(op)
            if pending:
                spec = pending.pop(0)
                record_fire(spec, spec.kind.value)
        return fault

    dest._next_fault = _observing_next_fault

    def script_dest_fault(spec: FaultSpec) -> None:
        if spec.kind is FaultKind.DEST_REJECT:
            kind = DestFaultKind.REJECT
        elif spec.kind is FaultKind.DEST_FAIL_AFTER_APPLY:
            kind = DestFaultKind.FAIL_AFTER_APPLY
        else:
            kind = DestFaultKind.HOLD
        for _ in range(spec.times):
            if kind is DestFaultKind.HOLD:
                release = asyncio.Event()
                held_releases.append((release, spec.hold_release_after_tx))
                dest.script(spec.site, FaultAction(kind,
                                                   release_event=release))
            else:
                dest.script(spec.site, FaultAction(kind))
            scripted_specs.setdefault(spec.site, []).append(spec)

    def arm_stall_spec(spec: FaultSpec) -> None:
        failpoints.arm_stall(
            spec.site, duration_s=spec.stall_s, times=spec.times,
            after_hits=spec.after_hits,
            on_fire=lambda spec=spec: record_fire(spec, "stall"))

    # arm everything without a tx trigger now; tx-triggered specs arm in
    # the workload loop below
    deferred: list[FaultSpec] = []
    for spec in scenario.faults:
        if spec.kind in (FaultKind.ERROR, FaultKind.CRASH):
            arm_failpoint(spec)
        elif spec.kind is FaultKind.STALL:
            arm_stall_spec(spec)
        elif spec.at_tx is None:
            if spec.kind is FaultKind.SEVER:
                deferred.append(spec)  # severing needs open streams
            else:
                script_dest_fault(spec)
        else:
            deferred.append(spec)

    copy_started = asyncio.Event()
    if scenario.tx_during_copy:
        # non-destructive observer on the during-copy site (scenarios
        # combining tx_during_copy with a fault at that same site would
        # clobber each other's arming — none do)
        failpoints.arm(failpoints.DURING_COPY, copy_started.set)

    if scenario.fast_watchdog:
        # stall scenarios: sweeps every 50 ms, sub-second stall deadline,
        # ~2 s hang deadline — detection + recovery must land inside the
        # scenario budget. wal_sender 1 s keeps an idle apply loop
        # beating every 600 ms, safely under the hang deadline.
        # stall deadline must clear the first-decode XLA compile
        # (~0.5-1 s on the CPU backend) or a legitimately slow first
        # fetch reads as a stall
        sup_cfg = SupervisionConfig(
            check_interval_s=0.05, stall_deadline_s=1.3,
            hang_deadline_s=2.2, restart_backoff_s=0.3,
            device_degrade_threshold=3, device_degrade_cooldown_s=1.0,
            breaker_failure_threshold=5, breaker_cooldown_s=0.4)
        dest_timeout_s = 1.5
        wal_sender_ms = 1_000
    else:
        # fault scenarios: supervision stays LIVE (its false-positive
        # rate under normal recovery churn is itself under test) but the
        # deadlines sit far above any legitimate pause in these runs
        sup_cfg = SupervisionConfig(
            check_interval_s=0.25, stall_deadline_s=10.0,
            hang_deadline_s=25.0, restart_backoff_s=1.0)
        dest_timeout_s = 30.0
        wal_sender_ms = 60_000
    config = PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=BatchConfig(max_size_bytes=64 * 1024, max_fill_ms=25,
                          batch_engine=BatchEngine(scenario.engine),
                          # program-cache scenarios: the restarted
                          # pipeline prewarms the stored schemas from
                          # the seeded dir at start — the tentpole flow
                          # under test
                          program_cache_dir=pc_dir,
                          prewarm_row_buckets=_PC_PREWARM_BUCKETS
                          if pc_dir else None),
        apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        supervision=sup_cfg,
        destination_op_timeout_s=dest_timeout_s,
        wal_sender_timeout_ms=wal_sender_ms,
        lag_sample_interval_s=0)

    def on_supervision_event(ev) -> None:
        if ev.kind not in ("restart", "degrade", "breaker"):
            return  # stall/hang detections precede a restart — count once
        fires = run.trace.setdefault(f"supervision.{ev.kind}", [])
        fires.append({"fire": len(fires) + 1, "component": ev.component})
        if ev.kind == "restart":
            # a cancel-and-restart re-streams the cancelled window: it
            # funds the bounded-dup budget exactly like a worker retry
            run.supervision_restarts += 1
            run.redelivery_firings += 1

    def make_pipeline():
        from ..runtime import Pipeline

        p = Pipeline(config=config, store=store, destination=dest,
                     source_factory=lambda: FakeSource(db))
        if p.supervisor is not None:
            p.supervisor.add_listener(on_supervision_event)
            p.supervisor.health.add_listener(
                lambda old, new, why: run.health_track.append(new.value))
        return p

    async def release_due_holds(tx_index: int | None) -> None:
        for release, due in list(held_releases):
            if due is None or tx_index is None or tx_index >= due:
                release.set()
                held_releases.remove((release, due))
                await asyncio.sleep(0)  # let the release task run

    async def drive() -> None:
        """The workload phases; raises SimulatedCrash when a crash site
        fires and the caller restarts."""
        if scenario.tx_during_copy and workload.tx_index == 0:
            await _race_crash(crash, copy_started.wait())
            await _race_crash(crash, workload.run_tx(db))
        await _race_crash(crash, _wait_until(
            lambda: all(
                (st := store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in workload.table_ids), 30.0, "tables never ready"))
        while workload.tx_index < scenario.txs:
            for spec in list(deferred):
                if (spec.at_tx or 0) <= workload.tx_index:
                    deferred.remove(spec)
                    if spec.kind is FaultKind.SEVER:
                        record_fire(spec, "sever")
                        await db.sever_streams()
                    else:
                        script_dest_fault(spec)
            await _race_crash(crash, workload.run_tx(db))
            await release_due_holds(workload.tx_index)
        await release_due_holds(None)
        await _race_crash(crash, _wait_until(
            lambda: workload.delivered(inner), 30.0,
            "workload never fully delivered"))

    def _dest_timeouts_total() -> float:
        from ..telemetry.metrics import (ETL_DESTINATION_OP_TIMEOUTS_TOTAL,
                                         registry)

        return sum(registry.get_counter(ETL_DESTINATION_OP_TIMEOUTS_TOTAL,
                                        {"op": op})
                   for op in ("startup", "write_events", "write_table_rows",
                              "drop_table", "truncate_table", "flush"))

    timeouts_before = _dest_timeouts_total()
    pipeline = make_pipeline()
    try:
        await pipeline.start()
        max_restarts = scenario.expect_restarts + 2
        t_phase = time.monotonic()
        while True:
            try:
                await drive()
                break
            except SimulatedCrash:
                crash.event.clear()
                await _hard_kill(pipeline)
                if scenario.program_cache:
                    # a real crash loses all jit state with the process;
                    # the corrupt variant additionally trashes the disk
                    # layer so the restart exercises the degrade path
                    _clear_program_memory_caches()
                    if scenario.program_cache == "corrupt":
                        await asyncio.to_thread(_corrupt_program_cache,
                                                pc_dir)
                    pc_restart_base = _program_cache_counters()
                resume = await store.get_durable_progress(
                    apply_slot_name(1))
                rec = RestartRecord(kind="crash",
                                    resume_lsn=int(resume or Lsn.ZERO),
                                    at_tx=workload.tx_index)
                run.restarts.append(rec)
                if len(run.restarts) > max_restarts:
                    run.report.fail(
                        f"crash loop: {len(run.restarts)} restarts "
                        f"exceeded the scenario budget {max_restarts}")
                    return
                t_phase = time.monotonic()
                pipeline = make_pipeline()
                await pipeline.start()
        if run.restarts:
            recovery = time.monotonic() - t_phase
            run.restarts[-1].recovery_s = recovery
            registry.histogram_observe(
                ETL_CHAOS_RECOVERY_DURATION_SECONDS, recovery)

        if scenario.clean_restart:
            await pipeline.shutdown_and_wait()
            resume = await store.get_durable_progress(apply_slot_name(1))
            run.restarts.append(RestartRecord(
                kind="clean", resume_lsn=int(resume or Lsn.ZERO),
                at_tx=workload.tx_index))
            t_phase = time.monotonic()
            pipeline = make_pipeline()
            await pipeline.start()
            end = workload.tx_index + scenario.txs_after_restart
            while workload.tx_index < end:
                await _race_crash(crash, workload.run_tx(db))
            await _race_crash(crash, _wait_until(
                lambda: workload.delivered(inner), 30.0,
                "post-restart workload never delivered"))
            run.restarts[-1].recovery_s = time.monotonic() - t_phase

        if scenario.program_cache and pc_base is not None:
            now = _program_cache_counters()
            if scenario.program_cache == "warm":
                fresh = now["compiled"] - pc_base["compiled"]
                if fresh != 0:
                    run.report.fail(
                        f"warm program cache: {fresh:g} fresh XLA builds "
                        "after seeding — restart did not serve from "
                        "cached programs")
                if pc_restart_base is not None \
                        and now["disk_hits"] <= pc_restart_base["disk_hits"]:
                    run.report.fail(
                        "warm program cache: the restarted pipeline never "
                        "loaded a program from disk")
            else:  # corrupt
                if now["invalid"] <= pc_base["invalid"]:
                    run.report.fail(
                        "corrupt program cache: no invalid-miss recorded "
                        "— the corrupted files were never probed (the "
                        "degrade path did not run)")

        if scenario.expect_health_recovery and pipeline.supervisor is not None:
            # the acceptance arc: /health's state machine must have gone
            # healthy → degraded during the stall and settled back to
            # healthy once the watchdog recovered the component
            from ..supervision import HealthState

            if "degraded" not in run.health_track:
                run.report.fail(
                    "health: state machine never left healthy during a "
                    "stall scenario (watchdog detected nothing)")

            def _settled() -> bool:
                pipeline.supervisor.sweep_once()
                return pipeline.supervisor.health.state \
                    is HealthState.HEALTHY

            try:
                await _wait_until(_settled, 8.0, "health stuck degraded")
            except TimeoutError:
                run.report.fail(
                    f"health: did not settle back to healthy after "
                    f"recovery: {pipeline.supervisor.health.snapshot()}")

        await pipeline.shutdown_and_wait()
    finally:
        # a failed scenario (timeout cancellation, unexpected error) must
        # not leak a live pipeline into the next scenario/test: hard-kill
        # whatever is still running and close the destination; release
        # any still-armed (or mid-stall) chaos stalls so no thread stays
        # parked, and lift a supervision-forced host-oracle degrade so it
        # cannot leak into the next scenario/test. After a clean shutdown
        # every call is an idempotent no-op.
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        await _hard_kill(pipeline)
        await dest.shutdown()
        if scenario.program_cache:
            # the corrupt variant may have rebuilds in flight on
            # background threads — wait them out (bounded) so the store
            # is quiescent before it is deconfigured and the dir removed
            import shutil

            from ..ops import program_store

            try:
                await _wait_until(
                    lambda: engine.background_compiles_inflight() == 0,
                    30.0, "program-cache background compiles lingering")
            except TimeoutError:
                pass  # non-fatal: save() re-reads active_dir per write
            program_store.configure(None)
            shutil.rmtree(pc_dir, ignore_errors=True)
    # unresolved = still pending now (shutdown missed them) PLUS any the
    # wrapper had to force-fail because no release ever came (shutdown
    # clears _held_acks, so counting the list alone would always be 0)
    inner.held_ack_count = dest.forced_held_acks + sum(
        1 for f in dest._held_acks if not f.done())
    # decode-pipeline worker threads exit asynchronously after close();
    # give them a moment before the leak probe counts survivors
    from .invariants import _pipeline_thread_count

    await _wait_until(
        lambda: _pipeline_thread_count() <= leak_probe.pipeline_threads,
        2.0, "pipeline threads lingering")
    # a released thread-stall (decode fetch) finishes its fetch — and
    # releases its staging arena — a beat after the release; give it the
    # same grace as the worker threads before the leak probe counts
    from ..ops.staging import ARENA_POOL

    await _wait_until(
        lambda: ARENA_POOL.outstanding <= leak_probe.arenas_outstanding,
        3.0, "staging arenas lingering after stall release")

    # each destination-op timeout classified one call as failed and sent
    # the worker back through a re-stream: it funds the dup budget like
    # any other recovery (counted by mechanism, not by injected firing)
    run.redelivery_firings += int(_dest_timeouts_total() - timeouts_before)

    check_invariants(
        expected=workload.expected, dest=inner, store=store,
        restarts=run.restarts, fault_firings=run.redelivery_firings,
        leak_probe=leak_probe, report=run.report)
