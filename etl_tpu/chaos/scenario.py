"""Declarative, seeded fault schedules.

A `Scenario` is a small data object: a workload shape (tables, initial
rows, CDC transactions) plus a tuple of `FaultSpec`s. The runner arms
every spec before the pipeline starts; a spec names WHERE (a failpoint
site, a destination op, or the wire), WHAT (error kind / scripted
destination fault / hard crash), WHEN (skip the first `after_hits` hits;
wire faults trigger after workload transaction `at_tx`), and HOW OFTEN
(`times`). Everything else — row values, which table each transaction
touches — is drawn from `random.Random(seed)`, so one (scenario, seed)
pair replays the identical workload and the identical injection trace.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..models.errors import ErrorKind


class FaultKind(enum.Enum):
    """What an armed spec does when its trigger predicate passes."""

    ERROR = "error"  # raise EtlError(error_kind) at a failpoint site
    CRASH = "crash"  # hard process-style crash: every pipeline task is
    # cancelled with no drain; the runner restarts from durable state
    DEST_REJECT = "dest_reject"  # scripted destination fault (memory.py
    DEST_FAIL_AFTER_APPLY = "dest_fail_after_apply"  # FaultInjecting-
    DEST_HOLD = "dest_hold"  # Destination): fail before / after apply,
    # or ack Accepted and turn durable only when the runner releases
    SEVER = "sever"  # postgres wire: drop every open walsender stream
    STALL = "stall"  # hang at a failpoint site for `stall_s` (or until
    # released) instead of raising: the silent-sickness mode the
    # supervision watchdog / destination op timeout must detect and
    # recover — the component never errors on its own


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault. `site` is a failpoint name (chaos/failpoints.py)
    for ERROR/CRASH, a destination op name (write_events /
    write_table_rows / truncate_table / drop_table) for DEST_*, and
    ignored for SEVER."""

    site: str
    kind: FaultKind = FaultKind.ERROR
    error_kind: ErrorKind = ErrorKind.SOURCE_IO
    times: int = 1
    after_hits: int = 0  # trigger predicate: skip the first N hits
    at_tx: int | None = None  # SEVER / DEST_*: arm after this workload tx
    hold_release_after_tx: int | None = None  # DEST_HOLD: release point
    # STALL: how long the site hangs. Async sites are cancelled the
    # moment the watchdog restarts their worker, so a generous value only
    # proves nothing else broke the hang; thread sites (decode fetch)
    # block a real thread for the full duration — keep those short
    stall_s: float = 8.0

    def describe(self) -> dict:
        return {
            "site": self.site,
            "kind": self.kind.value,
            "error_kind": self.error_kind.name,
            "times": self.times,
            "after_hits": self.after_hits,
            "at_tx": self.at_tx,
        }


@dataclass(frozen=True)
class Scenario:
    """A reproducible chaos schedule over the standard workload:
    `tables` tables copied with `rows_per_table` seed rows, then `txs`
    CDC transactions of `rows_per_tx` inserts/updates each, then
    drain + (optional clean restart) + invariant check."""

    name: str
    description: str
    faults: tuple[FaultSpec, ...] = ()
    tables: int = 1
    rows_per_table: int = 3
    txs: int = 6
    rows_per_tx: int = 4
    # named workload profile (etl_tpu/workloads) driving the traffic
    # instead of the default mixed-insert workload; the profile then owns
    # the table shape (tables/rows_per_table/rows_per_tx above are
    # ignored) while `txs` still counts generator steps. One (scenario,
    # workload, seed) triple replays bit-identically.
    workload: str | None = None
    # crash handling: how many hard restarts the runner should survive
    # (must be >= number of CRASH spec firings; compound crash-during-
    # recovery scenarios re-arm a crash after the first restart)
    expect_restarts: int = 0
    # commit one workload transaction WHILE the initial copy runs (the
    # runner observes the during-copy site non-destructively): guarantees
    # a catchup window between the copy snapshot and the catchup target,
    # so the before-streaming path actually executes
    tx_during_copy: bool = False
    # satellite (restart matrix): after the workload completes, shut the
    # pipeline down cleanly and restart it, then run `txs_after_restart`
    # more transactions before the invariant check
    clean_restart: bool = False
    txs_after_restart: int = 2
    engine: str = "tpu"  # BatchConfig.batch_engine
    # stall scenarios: tighten the watchdog (50 ms sweeps, sub-second
    # stall deadline, ~2 s hang deadline, 1.5 s destination op timeout,
    # 1 s wal_sender_timeout so an idle loop still beats often) so
    # detection + recovery land inside the scenario budget
    fast_watchdog: bool = False
    # assert the health state machine visited DEGRADED during the run
    # and settled back to HEALTHY before shutdown
    expect_health_recovery: bool = False
    # program-cache restart modes (ops/program_store.py):
    #   "warm"    — seed a cache dir with this workload's programs, clear
    #               the in-process program caches at every hard restart
    #               (process-death semantics for jit state), and assert
    #               the restarted pipeline served its first batch from
    #               DISK-cached programs: compile-counter delta == 0
    #               across the whole post-seed run, disk hits > 0.
    #   "corrupt" — same setup, but every cache file is overwritten with
    #               garbage at the restart: the load must degrade to a
    #               clean rebuild (invariants hold, at least one
    #               invalid-miss recorded), never a crash.
    program_cache: str | None = None

    def describe(self) -> dict:
        return {
            "name": self.name,
            "description": self.description,
            "workload": self.workload or "default",
            "tables": self.tables,
            "rows_per_table": self.rows_per_table,
            "txs": self.txs,
            "rows_per_tx": self.rows_per_tx,
            "expect_restarts": self.expect_restarts,
            "clean_restart": self.clean_restart,
            "engine": self.engine,
            "program_cache": self.program_cache,
            "faults": [f.describe() for f in self.faults],
        }
