"""Exactly-once chaos: a hard-kill matrix over the transactional
commit seam (docs/destinations.md).

The at-least-once scenarios (--ack-window, the corpus) prove bounded
duplication — budget = 1 + restarts. This matrix proves the STRICT
invariant the transactional seam buys: against a sink that records the
acked WAL coordinate range atomically with the data
(`TransactionalMemoryDestination`, the in-memory analogue of BigQuery
MERGE keys / ClickHouse dedup tokens / Iceberg snapshot properties /
Snowpipe offsets), a hard kill ANYWHERE leaves duplication == 0 — every
row delivered exactly once — alongside zero-loss and a monotone sink
high-water mark.

Three kill windows, each its own seeded sub-run:

  mid_write     — acks turn durable a fixed delay late
                  (DelayedAckDestination); the kill lands with >= 2
                  committed-but-unacked writes: the sink holds data +
                  range the progress store never heard about.
  pre_progress  — a stall armed at STORE_PROGRESS_COMMIT wedges the
                  durable-progress write AFTER the flush acked; the kill
                  lands inside the classic write-vs-progress gap.
  mid_recovery  — the FIRST restart is itself hard-killed while the
                  sink's recovery query (`recover_high_water`) is in
                  flight (scripted delay + one transient fault exercises
                  the satellite-1 retry path); the second restart must
                  still converge.

After each kill the restarted pipeline recovers the sink's high-water
mark (`ApplyWorker._recover_sink_high_water`), bootstraps the progress
store past what the sink already holds, and re-streams at most the
unacked suffix — whose rows the sink's coordinate dedup absorbs.

`python -m etl_tpu.chaos --exactly-once [--seed N]` replays the matrix;
the workload bytes are seed-deterministic and every kill is
event-triggered, so the end state replays bit-identically per seed.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from ..config import (BatchConfig, BatchEngine, PipelineConfig, RetryConfig,
                      SupervisionConfig)
from ..destinations import DelayedAckDestination, TransactionalMemoryDestination
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.table_state import TableStateType
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name
from . import failpoints
from .invariants import InvariantReport, LeakProbe, check_invariants
from .runner import RecordingStore, RestartRecord, _hard_kill, _wait_until, \
    _Workload
from .scenario import Scenario

KILL_WINDOWS = ("mid_write", "pre_progress", "mid_recovery")


class TracingTransactionalDestination(TransactionalMemoryDestination):
    """TransactionalMemoryDestination + the drop bookkeeping the
    invariant checker expects from chaos sinks."""

    def __init__(self) -> None:
        super().__init__()
        self.drop_seq_by_table: dict = {}
        self.held_ack_count = 0

    async def drop_table(self, table_id, schema=None) -> None:
        self.drop_seq_by_table[table_id] = len(self.events)
        await super().drop_table(table_id, schema)


@dataclass
class ExactlyOnceRun:
    seed: int
    report: InvariantReport = field(default_factory=InvariantReport)
    windows: list[dict] = field(default_factory=list)
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": "exactly_once_kill_matrix",
            "seed": self.seed,
            "ok": self.ok,
            "windows": list(self.windows),
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


def _config(write_window: int = 4) -> PipelineConfig:
    return PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=BatchConfig(max_size_bytes=2048, max_fill_ms=25,
                          batch_engine=BatchEngine("tpu"),
                          write_window=write_window),
        apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        supervision=SupervisionConfig(
            check_interval_s=0.25, stall_deadline_s=10.0,
            hang_deadline_s=25.0, restart_backoff_s=1.0),
        wal_sender_timeout_ms=60_000,
        lag_sample_interval_s=0)


async def _run_window(window: str, seed: int, report: InvariantReport,
                      txs: int = 8, rows_per_tx: int = 5) -> dict:
    """One kill window against a fresh workload + transactional sink.
    Returns the window's describe() fragment; failures land on the
    shared report prefixed with the window name."""
    failpoints.disarm_all()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name=f"exactly_once_{window}",
                     description=f"hard kill at {window}",
                     txs=txs, rows_per_tx=rows_per_tx)
    workload = _Workload(shape, random.Random(seed))
    db = workload.build_db()
    store = RecordingStore()
    inner = TracingTransactionalDestination()
    ack_delay_s = 0.25 if window == "mid_write" else 0.0
    dest = DelayedAckDestination(inner, ack_delay_s) \
        if window == "mid_write" else inner
    config = _config()
    restarts: list[RestartRecord] = []
    doc: dict = {"window": window, "seed": seed}

    def make_pipeline():
        from ..runtime import Pipeline

        return Pipeline(config=config, store=store, destination=dest,
                        source_factory=lambda: FakeSource(db))

    pipeline = make_pipeline()
    try:
        await pipeline.start()
        await _wait_until(
            lambda: all(
                (st := store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in workload.table_ids),
            30.0, "tables never ready")
        half = txs // 2
        while workload.tx_index < half:
            await workload.run_tx(db)

        if window == "mid_write":
            # the kill must land with >= 2 committed-but-unacked writes:
            # the sink already holds their data + coordinate ranges
            await _wait_until(lambda: dest.pending >= 2, 20.0,
                              "never held 2 delayed acks in flight")
            doc["acks_in_flight_at_kill"] = dest.pending
        elif window == "pre_progress":
            # wedge the NEXT durable-progress store write and kill
            # inside the stall: flush acked, progress never committed
            spec = failpoints.arm_stall(failpoints.STORE_PROGRESS_COMMIT,
                                        duration_s=30.0, times=1)
            while workload.tx_index < half + 1:
                await workload.run_tx(db)
            await _wait_until(lambda: spec.fired >= 1, 20.0,
                              "progress-store stall never fired")
        doc["sink_end_at_kill"] = int(inner.committed_end_lsn)
        doc["sink_high_at_kill"] = list(inner.high_water)
        await _hard_kill(pipeline)
        failpoints.release_stalls()
        failpoints.disarm_all()
        resume = await store.get_durable_progress(apply_slot_name(1))
        restarts.append(RestartRecord(
            kind="crash", resume_lsn=int(resume or Lsn.ZERO),
            at_tx=workload.tx_index))

        if window == "mid_recovery":
            # restart whose sink recovery query is slow + transiently
            # failing, then kill it MID-RECOVERY; the second restart
            # must still converge (satellite-1 retry path exercised)
            inner.recover_delay_s = 0.6
            inner.recover_faults.append(EtlError(
                ErrorKind.TIMEOUT, "scripted recovery-query fault"))
            calls_before = inner.recover_calls
            pipeline = make_pipeline()
            await pipeline.start()
            await _wait_until(
                lambda: inner.recover_calls > calls_before, 20.0,
                "sink recovery query never ran on restart")
            await _hard_kill(pipeline)
            inner.recover_delay_s = 0.0
            resume = await store.get_durable_progress(apply_slot_name(1))
            restarts.append(RestartRecord(
                kind="crash", resume_lsn=int(resume or Lsn.ZERO),
                at_tx=workload.tx_index))

        t_restart = time.monotonic()
        pipeline = make_pipeline()
        await pipeline.start()
        while workload.tx_index < txs:
            await workload.run_tx(db)
        await _wait_until(lambda: workload.delivered(inner), 30.0,
                          "workload never fully delivered after restart")
        restarts[-1].recovery_s = time.monotonic() - t_restart
        await pipeline.shutdown_and_wait()
    except Exception as e:
        report.fail(f"{window}: scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        failpoints.disarm_all()
        from ..ops import engine

        engine.clear_forced_oracle()
        await _hard_kill(pipeline)
        await dest.shutdown()

    from .invariants import _pipeline_thread_count

    try:
        await _wait_until(
            lambda: _pipeline_thread_count() <= leak_probe.pipeline_threads,
            3.0, "pipeline threads lingering")
    except TimeoutError as e:
        report.fail(f"{window}: {e}")

    # the standard invariants (zero-loss, monotonic durable LSN,
    # no-leaks) — with dup budget temporarily at-least-once so the sub-
    # report carries max_duplication for the STRICT check below
    sub = check_invariants(
        expected=workload.expected, dest=inner, store=store,
        restarts=restarts, fault_firings=0, leak_probe=leak_probe)
    for f in sub.violations:
        report.fail(f"{window}: {f}")

    # -- the exactly-once invariants ------------------------------------------
    # the kill must have landed inside a REAL write-vs-progress gap: the
    # sink held committed coordinate ranges the progress store never
    # named (otherwise the window exercised nothing)
    if window in ("mid_write", "pre_progress") and restarts:
        if doc["sink_end_at_kill"] <= restarts[0].resume_lsn:
            report.fail(
                f"{window}: kill landed outside the gap — sink commit "
                f"end {doc['sink_end_at_kill']} not ahead of durable "
                f"progress {restarts[0].resume_lsn}")
    max_dup = sub.stats.get("max_duplication", 0)
    if max_dup > 1:
        report.fail(
            f"{window}: exactly-once violated — a row delivered "
            f"{max_dup}x through the transactional sink (dup budget 0)")
    for a, b in zip(inner.high_water_log, inner.high_water_log[1:]):
        if b < a:
            report.fail(f"{window}: sink high-water regressed {a} -> {b}")
    if inner.recover_calls < len(restarts):
        report.fail(
            f"{window}: sink recovery query ran {inner.recover_calls}x "
            f"for {len(restarts)} restart(s) — a restart resumed blind")
    if inner.uncoordinated_writes:
        report.fail(
            f"{window}: {inner.uncoordinated_writes} CDC write(s) "
            f"bypassed the transactional seam")

    doc.update({
        "restarts": [r.describe() for r in restarts],
        "max_duplication": max_dup,
        "dedup_skipped_rows": inner.dedup_skipped_rows,
        "recover_calls": inner.recover_calls,
        "high_water": list(inner.high_water),
        "high_water_log_len": len(inner.high_water_log),
        "delivered_events": sub.stats.get("delivered_events", 0),
        "expected_rows": sub.stats.get("expected_rows", 0),
    })
    return doc


async def run_exactly_once_crash(seed: int = 11) -> ExactlyOnceRun:
    """The full kill matrix: every window in KILL_WINDOWS, each against
    a fresh seeded workload (seed + window index keeps the sub-runs
    independent AND deterministic)."""
    run = ExactlyOnceRun(seed=seed)
    t_start = time.monotonic()
    for i, window in enumerate(KILL_WINDOWS):
        run.windows.append(
            await _run_window(window, seed + i, run.report))
    run.duration_s = time.monotonic() - t_start
    return run
