"""Sharded chaos: kill one of K shard replicators mid-stream.

The sharded topology's one piece of shared state is the store (shard
assignment + table states + per-slot durable progress), so the scenario
shape mirrors production faithfully: ONE fake source database, ONE
publication, ONE shared store — and K shard-scoped Pipelines, each with
its own destination and its own `_s{shard}` slot, exactly the resource
split of K pods (multi-process semantics via the runner's `_hard_kill`:
every task cancelled, no drain, no destination shutdown).

The run proves, deterministically per seed:

  1. killing one shard leaves the SURVIVORS untouched — their entire
     remaining workload delivers during the outage window (a cross-shard
     coupling bug — shared store contention, a leaked ownership fence,
     admission tickets stranded by the dead pod — would stall them);
  2. the victim restarts from durable state and reconverges: the
     per-shard invariant check (zero loss, bounded dups funded by
     exactly one restart, monotonic per-slot durable LSN) passes for
     EVERY shard over its own slice of the committed truth;
  3. the union across shards equals the full committed source truth
     (`gen.expected`): no table fell between shards, none is owned
     twice — the cross-shard union check;
  4. no shard's destination ever saw another shard's tables (delivery
     isolation), and tasks/threads/arena leases return to baseline.

`python -m etl_tpu.chaos --sharded [K] [--seed N]` replays it.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from ..analysis.annotations import shard_scoped
from ..config import (BatchConfig, BatchEngine, PipelineConfig, RetryConfig,
                      SupervisionConfig)
from ..models.event import DeleteEvent, InsertEvent, UpdateEvent
from ..models.lsn import Lsn
from ..models.table_state import TableStateType
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name
from ..sharding import ShardMap
from . import failpoints
from .invariants import (InvariantReport, LeakProbe, check_invariants,
                         view_matches)
from .runner import (RecordingStore, RestartRecord, TracingDestination,
                     _hard_kill, _wait_until, _Workload)
from .scenario import Scenario

#: workload shape: enough tables that every shard owns at least one at
#: K=2 (5/3 split) and K=3 (5/2/1) under the fixed HRW map; the run
#: still guards against a degenerate (empty-shard) map before any fault
#: fires, so a larger K fails loudly instead of proving nothing
SHARDED_TABLES = 8


@dataclass
class ShardedChaosRun:
    seed: int
    shards: int
    victim: int
    report: InvariantReport = field(default_factory=InvariantReport)
    restarts: list[RestartRecord] = field(default_factory=list)
    tables_per_shard: dict = field(default_factory=dict)
    survivor_txs_during_outage: int = 0
    union_matches: bool = False
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": "sharded_pod_kill",
            "seed": self.seed,
            "shards": self.shards,
            "victim": self.victim,
            "ok": self.ok,
            "tables_per_shard": {str(s): n for s, n in
                                 sorted(self.tables_per_shard.items())},
            "restarts": [r.describe() for r in self.restarts],
            "survivor_txs_during_outage": self.survivor_txs_during_outage,
            "union_matches": self.union_matches,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


class _UnionDest:
    """The cross-shard union view: every shard's copied baselines and
    delivered events merged into one reconstructable surface (delivery
    order preserved per shard; WAL rank does the global ordering, the
    same collapse rule the invariant checker replays by)."""

    def __init__(self, dests):
        self.events = []
        self.event_seqs = []
        self.table_rows = {}
        self.drop_seq_by_table = {}
        seq = 0
        for d in dests:
            offset = seq
            for tid, rows in d.table_rows.items():
                self.table_rows.setdefault(tid, []).extend(rows)
            for tid, drop_seq in getattr(d, "drop_seq_by_table",
                                         {}).items():
                self.drop_seq_by_table[tid] = offset + drop_seq
            for e in d.events:
                self.events.append(e)
                self.event_seqs.append(seq)
                seq += 1


def _shard_pipeline_config(shard: int, shards: int) -> PipelineConfig:
    # supervision LIVE but lenient (the chaos runner's fault-scenario
    # stance): deadlines far above any legitimate pause here, so the dup
    # budget needs no supervision-restart accounting
    return PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=BatchConfig(max_size_bytes=64 * 1024, max_fill_ms=25,
                          batch_engine=BatchEngine("tpu")),
        apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        supervision=SupervisionConfig(
            check_interval_s=0.25, stall_deadline_s=10.0,
            hang_deadline_s=25.0, restart_backoff_s=1.0),
        wal_sender_timeout_ms=60_000,
        lag_sample_interval_s=0,
        shard=shard, shard_count=shards)


@shard_scoped
async def _wait_shard_ready(scoped_store, owned, timeout_s: float,
                            what: str) -> None:
    """One shard's readiness: every owned table READY in ITS view."""

    async def ready() -> bool:
        states = await scoped_store.owned_table_states()
        return all((st := states.get(tid)) is not None
                   and st.type is TableStateType.READY for tid in owned)

    deadline = time.monotonic() + timeout_s
    while not await ready():
        if time.monotonic() >= deadline:
            raise TimeoutError(what)
        await asyncio.sleep(0.02)


def _delivered(dest, owned, expected) -> bool:
    return view_matches(dest, owned,
                        {tid: expected[tid] for tid in owned})


async def run_sharded_scenario(seed: int = 7, shards: int = 2,
                               txs: int = 8, rows_per_tx: int = 60,
                               victim: int | None = None
                               ) -> ShardedChaosRun:
    """K shard replicators over one publication; the victim shard is
    hard-killed after half the transactions and restarted from durable
    state. Defaults pick the LAST shard as the victim (it always owns
    tables under the fixed map — asserted before any fault fires)."""
    failpoints.disarm_all()
    run = ShardedChaosRun(seed=seed, shards=shards,
                          victim=shards - 1 if victim is None else victim)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name="sharded", description="sharded workload",
                     tables=SHARDED_TABLES, rows_per_table=3,
                     txs=txs, rows_per_tx=rows_per_tx)
    workload = _Workload(shape, random.Random(seed))
    db = workload.build_db()
    store = RecordingStore()
    smap = ShardMap(shards)
    part = smap.partition(workload.table_ids)
    run.tables_per_shard = {s: len(t) for s, t in part.items()}
    dests = {s: TracingDestination() for s in range(shards)}
    pipes: dict[int, object] = {}

    def make_pipeline(shard: int):
        from ..runtime import Pipeline

        p = Pipeline(config=_shard_pipeline_config(shard, shards),
                     store=store, destination=dests[shard],
                     source_factory=lambda: FakeSource(db))
        pipes[shard] = p
        return p

    async def wait_all_ready() -> None:
        await asyncio.gather(*(
            _wait_shard_ready(pipes[s].store, part[s], 30.0,
                              f"shard {s}: tables never ready")
            for s in pipes))

    try:
        if any(not tabs for tabs in part.values()):
            run.report.fail(f"degenerate shard map: empty shard in "
                            f"{run.tables_per_shard} — grow the table set")
            return run
        for s in range(shards):
            await make_pipeline(s).start()
        await wait_all_ready()
        half = txs // 2
        while workload.tx_index < half:
            await workload.run_tx(db)

        # hard-kill the victim: process-death semantics, nothing drained
        await _hard_kill(pipes[run.victim])
        resume = await store.get_durable_progress(
            apply_slot_name(1, run.victim))
        run.restarts.append(RestartRecord(
            kind="crash", resume_lsn=int(resume or Lsn.ZERO),
            at_tx=workload.tx_index))

        # the survivors must stay whole DURING the outage: the rest of
        # the workload commits and every surviving shard delivers its
        # full slice while the victim is down
        before = workload.tx_index
        while workload.tx_index < txs:
            await workload.run_tx(db)
        run.survivor_txs_during_outage = workload.tx_index - before
        for s in range(shards):
            if s == run.victim:
                continue
            await _wait_until(
                lambda s=s: _delivered(dests[s], part[s],
                                       workload.expected),
                30.0, f"survivor shard {s} stalled during the victim's "
                      f"outage")

        # restart the victim from durable state; it must reconverge
        t_restart = time.monotonic()
        await make_pipeline(run.victim).start()
        await _wait_shard_ready(pipes[run.victim].store, part[run.victim],
                                30.0, "victim tables not ready after "
                                      "restart")
        await _wait_until(
            lambda: _delivered(dests[run.victim], part[run.victim],
                               workload.expected),
            30.0, "victim never reconverged after restart")
        run.restarts[-1].recovery_s = time.monotonic() - t_restart

        for s in range(shards):
            await pipes[s].shutdown_and_wait()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        for p in pipes.values():
            await _hard_kill(p)
        for d in dests.values():
            await d.shutdown()
        run.duration_s = time.monotonic() - t_start

    # decode-pipeline worker threads exit asynchronously after close()
    from .invariants import _pipeline_thread_count

    try:
        await _wait_until(
            lambda: _pipeline_thread_count() <= leak_probe.pipeline_threads,
            3.0, "pipeline threads lingering")
    except TimeoutError as e:
        run.report.fail(str(e))

    # delivery isolation: a shard's destination must never have seen a
    # row event of a table the map assigns elsewhere
    for s, dest in dests.items():
        owned = set(part[s])
        for e in dest.events:
            if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)) \
                    and e.schema.id not in owned:
                run.report.fail(
                    f"cross-shard leak: shard {s} delivered an event of "
                    f"table {e.schema.id} (owner: "
                    f"{smap.shard_of(e.schema.id)})")
                break

    # per-shard invariants over each shard's OWN slice of the committed
    # truth — the victim's crash funds one restart of dup budget, the
    # survivors get none
    for s in range(shards):
        restarts = run.restarts if s == run.victim else []
        check_invariants(
            expected={tid: workload.expected[tid] for tid in part[s]},
            dest=dests[s], store=store, restarts=restarts,
            fault_firings=0, leak_probe=leak_probe, report=run.report)

    # the cross-shard union: merged shard views must equal the FULL
    # committed source truth — no table lost between shards
    run.union_matches = view_matches(_UnionDest(list(dests.values())),
                                     workload.table_ids, workload.expected)
    if not run.union_matches:
        run.report.fail("cross-shard union: merged shard destinations do "
                        "not reconstruct the committed source truth")
    return run
