"""Failpoints: named crash/error injection sites across every layer.

Grown from runtime/failpoints.py (the seven reference-parity sites,
crates/etl/src/failpoints.rs:14-54) into the chaos subsystem's injection
surface: decode-pipeline stages, copy partition boundaries, assembler
seals, destination write/flush, store state/schema/progress commits, and
a simulated device-OOM hook the decode pipeline degrades through.

Design constraints:

  - the registry stays a no-op dict lookup when nothing is armed — the
    hot loop (per-row CDC pushes, per-chunk COPY writes) pays one `if not
    dict` check;
  - sites may be hit from the decode pipeline's WORKER THREAD as well as
    the event loop, so the global registry is guarded by a lock and
    actions must be thread-safe;
  - per-pipeline scoping: `scope("name")` binds a contextvar that
    asyncio tasks inherit, so two pipelines under test in one process can
    arm the same site without cross-firing (satellite: parallel tests).
    Worker-thread hits do not see contextvars of the arming task — sites
    that fire on the pack/dispatch thread (pipeline.*) should be armed
    globally in single-pipeline tests.

`runtime/failpoints.py` re-exports this module, so existing call sites
and tests keep importing from the runtime package unchanged.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Callable, Iterator

from ..models.errors import ErrorKind, EtlError

# --- the reference's named sites (failpoints.rs:14-21) ----------------------

BEFORE_SLOT_CREATION = "table_sync.before_slot_creation"
DURING_COPY = "table_sync.during_copy"
AFTER_FINISHED_COPY = "table_sync.after_finished_copy"
BEFORE_STREAMING = "table_sync.before_streaming"
ON_STATUS_UPDATE = "apply.on_status_update"
ON_PROGRESS_STORE = "apply.on_progress_store"
ON_SCHEMA_CLEANUP = "apply.on_schema_cleanup"

REFERENCE_SITES = (
    BEFORE_SLOT_CREATION, DURING_COPY, AFTER_FINISHED_COPY,
    BEFORE_STREAMING, ON_STATUS_UPDATE, ON_PROGRESS_STORE,
    ON_SCHEMA_CLEANUP,
)

# --- chaos-subsystem sites ---------------------------------------------------

# decode pipeline stages (ops/pipeline.py _process/_fetch)
PIPELINE_PACK = "pipeline.pack"
PIPELINE_DISPATCH = "pipeline.dispatch"
PIPELINE_FETCH = "pipeline.fetch"
# simulated device OOM: the pipeline catches DEVICE_UNAVAILABLE /
# MEMORY_PRESSURE_ABORT raised here and degrades the batch to the host
# oracle instead of failing the stream (ops/pipeline.py)
ENGINE_DEVICE_OOM = "engine.device_oom"
# copy partition boundaries (runtime/copy.py)
COPY_PARTITION_START = "copy.partition_start"
COPY_PARTITION_END = "copy.partition_end"
# assembler run seal (runtime/assembler.py)
ASSEMBLER_SEAL = "assembler.seal"
# destination ack layer (destinations/base.py): WRITE fires when a
# destination constructs its ack (the write applied — an error here is
# the lost-response ambiguity), FLUSH fires on wait_durable
DESTINATION_WRITE = "destination.write"
DESTINATION_FLUSH = "destination.flush"
# store commit layer (store/memory.py, store/sql.py)
STORE_STATE_COMMIT = "store.state_commit"
STORE_SCHEMA_COMMIT = "store.schema_commit"
STORE_PROGRESS_COMMIT = "store.progress_commit"

CHAOS_SITES = (
    PIPELINE_PACK, PIPELINE_DISPATCH, PIPELINE_FETCH, ENGINE_DEVICE_OOM,
    COPY_PARTITION_START, COPY_PARTITION_END, ASSEMBLER_SEAL,
    DESTINATION_WRITE, DESTINATION_FLUSH,
    STORE_STATE_COMMIT, STORE_SCHEMA_COMMIT, STORE_PROGRESS_COMMIT,
)

ALL_SITES = REFERENCE_SITES + CHAOS_SITES

# --- registry ----------------------------------------------------------------

_lock = threading.Lock()
_armed: dict[str, Callable[[], None]] = {}
# scope name -> site -> action; consulted only when the contextvar is set
_scoped: dict[str, dict[str, Callable[[], None]]] = {}
_scope_var: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("etl_failpoint_scope", default=None)


@contextlib.contextmanager
def scope(name: str) -> Iterator[str]:
    """Bind a failpoint scope for the calling task (and every task it
    spawns). Scoped armings fire only inside their scope."""
    token = _scope_var.set(name)
    try:
        yield name
    finally:
        _scope_var.reset(token)
        with _lock:
            _scoped.pop(name, None)


def arm(name: str, action: Callable[[], None],
        scope_name: str | None = None) -> None:
    """Arm a failpoint with an action (usually raising)."""
    with _lock:
        if scope_name is None:
            _armed[name] = action
        else:
            _scoped.setdefault(scope_name, {})[name] = action


def arm_error(name: str, kind: ErrorKind = ErrorKind.SOURCE_IO,
              times: int = 1, detail: str = "",
              scope_name: str | None = None) -> None:
    """Arm to raise an EtlError of `kind` the next `times` hits."""
    remaining = [times]

    def action() -> None:
        if remaining[0] > 0:
            remaining[0] -= 1
            raise EtlError(kind, detail or f"failpoint {name}")
        disarm(name, scope_name)

    arm(name, action, scope_name)


def disarm(name: str, scope_name: str | None = None) -> None:
    with _lock:
        if scope_name is None:
            _armed.pop(name, None)
        else:
            scoped = _scoped.get(scope_name)
            if scoped is not None:
                scoped.pop(name, None)


def disarm_all() -> None:
    with _lock:
        _armed.clear()
        _scoped.clear()


def armed_sites() -> list[str]:
    """Globally armed site names (introspection for tests/CLI)."""
    with _lock:
        return sorted(_armed)


def fail_point(name: str) -> None:
    """Hit a failpoint (no-op unless armed). Hot-path cost when disarmed:
    two falsy dict checks, no lock."""
    if not _armed and not _scoped:
        return
    action = None
    if _scoped:
        scope_name = _scope_var.get()
        if scope_name is not None:
            with _lock:
                scoped = _scoped.get(scope_name)
                action = scoped.get(name) if scoped else None
    if action is None:
        with _lock:
            action = _armed.get(name)
    if action is not None:
        action()
