"""Failpoints: named crash/error injection sites across every layer.

Grown from runtime/failpoints.py (the seven reference-parity sites,
crates/etl/src/failpoints.rs:14-54) into the chaos subsystem's injection
surface: decode-pipeline stages, copy partition boundaries, assembler
seals, destination write/flush, store state/schema/progress commits, and
a simulated device-OOM hook the decode pipeline degrades through.

Design constraints:

  - the registry stays a no-op dict lookup when nothing is armed — the
    hot loop (per-row CDC pushes, per-chunk COPY writes) pays one `if not
    dict` check;
  - sites may be hit from the decode pipeline's WORKER THREAD as well as
    the event loop, so the global registry is guarded by a lock and
    actions must be thread-safe;
  - per-pipeline scoping: `scope("name")` binds a contextvar that
    asyncio tasks inherit, so two pipelines under test in one process can
    arm the same site without cross-firing (satellite: parallel tests).
    Worker-thread hits do not see contextvars of the arming task — sites
    that fire on the pack/dispatch thread (pipeline.*) should be armed
    globally in single-pipeline tests.

`runtime/failpoints.py` re-exports this module, so existing call sites
and tests keep importing from the runtime package unchanged.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Callable, Iterator

from ..models.errors import ErrorKind, EtlError

# --- the reference's named sites (failpoints.rs:14-21) ----------------------

BEFORE_SLOT_CREATION = "table_sync.before_slot_creation"
DURING_COPY = "table_sync.during_copy"
AFTER_FINISHED_COPY = "table_sync.after_finished_copy"
BEFORE_STREAMING = "table_sync.before_streaming"
ON_STATUS_UPDATE = "apply.on_status_update"
ON_PROGRESS_STORE = "apply.on_progress_store"
ON_SCHEMA_CLEANUP = "apply.on_schema_cleanup"

REFERENCE_SITES = (
    BEFORE_SLOT_CREATION, DURING_COPY, AFTER_FINISHED_COPY,
    BEFORE_STREAMING, ON_STATUS_UPDATE, ON_PROGRESS_STORE,
    ON_SCHEMA_CLEANUP,
)

# --- chaos-subsystem sites ---------------------------------------------------

# decode pipeline stages (ops/pipeline.py _process/_fetch)
PIPELINE_PACK = "pipeline.pack"
PIPELINE_DISPATCH = "pipeline.dispatch"
PIPELINE_FETCH = "pipeline.fetch"
# simulated device OOM: the pipeline catches DEVICE_UNAVAILABLE /
# MEMORY_PRESSURE_ABORT raised here and degrades the batch to the host
# oracle instead of failing the stream (ops/pipeline.py)
ENGINE_DEVICE_OOM = "engine.device_oom"
# copy partition boundaries (runtime/copy.py)
COPY_PARTITION_START = "copy.partition_start"
COPY_PARTITION_END = "copy.partition_end"
# assembler run seal (runtime/assembler.py)
ASSEMBLER_SEAL = "assembler.seal"
# apply-loop frame handling (runtime/apply_loop.py): a stall here wedges
# the loop itself — the watchdog's hang detection is the only way out
APPLY_FRAME_READ = "apply.frame_read"
# destination ack layer (destinations/base.py): WRITE fires when a
# destination constructs its ack (the write applied — an error here is
# the lost-response ambiguity), FLUSH fires on wait_durable
DESTINATION_WRITE = "destination.write"
DESTINATION_FLUSH = "destination.flush"
# store commit layer (store/memory.py, store/sql.py)
STORE_STATE_COMMIT = "store.state_commit"
STORE_SCHEMA_COMMIT = "store.schema_commit"
STORE_PROGRESS_COMMIT = "store.progress_commit"
# shard-assignment commits (store/memory.py, store/sql.py): the
# coordinator's two-phase rebalance persists through here — a fault is
# the crash-mid-rebalance window (docs/sharding.md)
STORE_SHARD_COMMIT = "store.shard_commit"

# autoscale decision-journal commits (store/memory.py, store/sql.py):
# the controller persists each scale decision here BEFORE actuating —
# a fault is the crash-before-actuation window the resume protocol
# covers (etl_tpu/autoscale/controller.py)
STORE_AUTOSCALE_COMMIT = "store.autoscale_commit"

# fleet spec/journal commits (store/memory.py, store/sql.py): the fleet
# reconciler persists each actuation decision here BEFORE driving the
# orchestrator — a fault is the crash-before-actuation window the
# successor's resume protocol covers (etl_tpu/fleet/reconciler.py)
STORE_FLEET_COMMIT = "store.fleet_commit"

# dead-letter appends (store/memory.py, store/sql.py): the isolation
# protocol persists poison rows here BEFORE acking their flush durable —
# a fault is the crash-between-bisect-and-dead-letter window the
# idempotent (keyed upsert) append covers (docs/dead-letter.md)
STORE_DLQ_COMMIT = "store.dlq_commit"

# poison-pill bisection (runtime/poison.py): fires once per bisection
# probe write — a crash here is the hard-kill-mid-bisection window the
# --dlq chaos scenario proves recoverable within the dup budget
POISON_BISECT = "poison.bisect"

CHAOS_SITES = (
    PIPELINE_PACK, PIPELINE_DISPATCH, PIPELINE_FETCH, ENGINE_DEVICE_OOM,
    COPY_PARTITION_START, COPY_PARTITION_END, ASSEMBLER_SEAL,
    APPLY_FRAME_READ,
    DESTINATION_WRITE, DESTINATION_FLUSH,
    STORE_STATE_COMMIT, STORE_SCHEMA_COMMIT, STORE_PROGRESS_COMMIT,
    STORE_SHARD_COMMIT, STORE_AUTOSCALE_COMMIT, STORE_FLEET_COMMIT,
    STORE_DLQ_COMMIT,
    POISON_BISECT,
)

#: sites that can stall asynchronously (an armed stall is consumed by the
#: site's `await stall_point(...)`); PIPELINE_FETCH stalls synchronously
#: on whichever THREAD drives the fetch (copy partitions fetch via
#: asyncio.to_thread, so the block lands off the event loop)
ASYNC_STALL_SITES = (
    APPLY_FRAME_READ, DESTINATION_WRITE, DESTINATION_FLUSH,
    COPY_PARTITION_START, COPY_PARTITION_END,
    STORE_STATE_COMMIT, STORE_SCHEMA_COMMIT, STORE_PROGRESS_COMMIT,
    STORE_SHARD_COMMIT, STORE_AUTOSCALE_COMMIT, STORE_FLEET_COMMIT,
    STORE_DLQ_COMMIT,
    POISON_BISECT,
)

ALL_SITES = REFERENCE_SITES + CHAOS_SITES

# --- registry ----------------------------------------------------------------

_lock = threading.Lock()
_armed: dict[str, Callable[[], None]] = {}
# scope name -> site -> action; consulted only when the contextvar is set
_scoped: dict[str, dict[str, Callable[[], None]]] = {}
_scope_var: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("etl_failpoint_scope", default=None)


@contextlib.contextmanager
def scope(name: str) -> Iterator[str]:
    """Bind a failpoint scope for the calling task (and every task it
    spawns). Scoped armings fire only inside their scope."""
    token = _scope_var.set(name)
    try:
        yield name
    finally:
        _scope_var.reset(token)
        with _lock:
            _scoped.pop(name, None)


def arm(name: str, action: Callable[[], None],
        scope_name: str | None = None) -> None:
    """Arm a failpoint with an action (usually raising)."""
    with _lock:
        if scope_name is None:
            _armed[name] = action
        else:
            _scoped.setdefault(scope_name, {})[name] = action


def arm_error(name: str, kind: ErrorKind = ErrorKind.SOURCE_IO,
              times: int = 1, detail: str = "",
              scope_name: str | None = None) -> None:
    """Arm to raise an EtlError of `kind` the next `times` hits."""
    remaining = [times]

    def action() -> None:
        if remaining[0] > 0:
            remaining[0] -= 1
            raise EtlError(kind, detail or f"failpoint {name}")
        disarm(name, scope_name)

    arm(name, action, scope_name)


def disarm(name: str, scope_name: str | None = None) -> None:
    with _lock:
        if scope_name is None:
            _armed.pop(name, None)
        else:
            scoped = _scoped.get(scope_name)
            if scoped is not None:
                scoped.pop(name, None)


def disarm_all() -> None:
    release_stalls()
    with _lock:
        _armed.clear()
        _scoped.clear()


def armed_sites() -> list[str]:
    """Globally armed site names (introspection for tests/CLI)."""
    with _lock:
        return sorted(_armed)


def fail_point(name: str) -> None:
    """Hit a failpoint (no-op unless armed). Hot-path cost when disarmed:
    three falsy dict checks, no lock. Armed STALLS fire here only when
    the caller is OFF the event loop (worker threads, asyncio.to_thread
    fetches) — a synchronous block on the loop would freeze the
    supervisor that is supposed to detect it, so loop-side sites consume
    stalls through `await stall_point(...)` instead."""
    if _stalls and not _on_event_loop():
        s = _consume_stall(name)
        if s is not None:
            s.release.wait(s.duration_s)
    if not _armed and not _scoped:
        return
    action = None
    if _scoped:
        scope_name = _scope_var.get()
        if scope_name is not None:
            with _lock:
                scoped = _scoped.get(scope_name)
                action = scoped.get(name) if scoped else None
    if action is None:
        with _lock:
            action = _armed.get(name)
    if action is not None:
        action()


# --- stall mode --------------------------------------------------------------


class _StallSpec:
    """One armed stall: hang for `duration_s` or until released."""

    __slots__ = ("name", "duration_s", "release", "times", "after_hits",
                 "hits", "fired", "on_fire")

    def __init__(self, name: str, duration_s: float, times: int,
                 after_hits: int, on_fire: Callable[[], None] | None):
        self.name = name
        self.duration_s = duration_s
        self.release = threading.Event()
        self.times = times
        self.after_hits = after_hits
        self.hits = 0
        self.fired = 0
        self.on_fire = on_fire


_stalls: dict[str, _StallSpec] = {}
# every spec ever armed since the last release: a consumed spec leaves
# `_stalls` but may still be blocking a thread on its release event
_all_stall_specs: list[_StallSpec] = []


def _on_event_loop() -> bool:
    import asyncio

    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


def arm_stall(name: str, duration_s: float = 5.0, times: int = 1,
              after_hits: int = 0,
              on_fire: Callable[[], None] | None = None) -> "_StallSpec":
    """Arm a stall at `name`: the site hangs for `duration_s` (or until
    `release_stalls()` / `disarm_all()`) instead of raising. Async sites
    (`ASYNC_STALL_SITES`) stall cancellably via `stall_point`; thread
    sites block in `fail_point`. Returns the spec so tests can release
    it directly."""
    spec = _StallSpec(name, duration_s, times, after_hits, on_fire)
    with _lock:
        _stalls[name] = spec
        _all_stall_specs.append(spec)
    return spec


def _consume_stall(name: str) -> "_StallSpec | None":
    """One stall firing attempt: counts the hit, honors after_hits/times,
    self-disarms when exhausted."""
    with _lock:
        spec = _stalls.get(name)
        if spec is None:
            return None
        spec.hits += 1
        if spec.hits <= spec.after_hits:
            return None
        if spec.fired >= spec.times:
            _stalls.pop(name, None)
            return None
        spec.fired += 1
        if spec.fired >= spec.times:
            _stalls.pop(name, None)
    if spec.on_fire is not None:
        spec.on_fire()
    return spec


def stalls_armed() -> bool:
    """Per-frame hot paths guard their `await stall_point(...)` behind
    this (one dict truthiness check) so the disarmed cost stays a sync
    call, not a coroutine allocation per frame — the same contract as
    fail_point's no-op lookup."""
    return bool(_stalls)


async def stall_point(name: str) -> None:
    """Async stall site: hang (cancellably) while armed. Cost when
    nothing is armed: one falsy dict check (hot paths pre-guard with
    `stalls_armed()` to skip even the coroutine). Polling (20 ms)
    rather than an executor wait so supervisor cancellation interrupts
    the stall immediately without stranding an executor thread."""
    if not _stalls:
        return
    s = _consume_stall(name)
    if s is None:
        return
    import asyncio
    import time

    deadline = time.monotonic() + s.duration_s
    while not s.release.is_set() and time.monotonic() < deadline:
        await asyncio.sleep(0.02)


def release_stalls() -> None:
    """Unblock every stalled site, armed or mid-stall (consumed specs
    keep blocking their thread until released) — scenario teardown must
    never leave a thread parked on a chaos stall."""
    with _lock:
        specs = list(_all_stall_specs)
        _stalls.clear()
        _all_stall_specs.clear()
    for s in specs:
        s.release.set()
