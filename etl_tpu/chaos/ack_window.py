"""Ack-window chaos: hard-kill with K ≥ 2 destination acks in flight.

The bounded write window (runtime/ack_window.py) widens the classic
write-vs-progress-store crash window: at the kill instant up to
`write_window` batches have been SUBMITTED to the destination while none
of their acks has resolved — durable progress covers only the contiguous
acked prefix, so the restart must re-stream every in-flight batch. The
scenario proves, with a destination whose acks turn durable a fixed
delay late (destinations/delay.py — the deterministic way to hold
multiple acks open):

  1. the kill lands while ≥ 2 acks are verifiably in flight (the
     delayed destination's pending counter is the evidence — window=1
     could never reach 2);
  2. zero-loss: every committed row is present after recovery;
  3. bounded-dup: re-delivered batches stay within budget = 1 + restarts
     — i.e. the window-full of unacked batches re-streams ONCE;
  4. monotonic durable LSN across the kill (the contiguous-prefix rule
     means the store never named an unacked batch's commit);
  5. no leaked tasks/threads/arena leases.

`python -m etl_tpu.chaos --ack-window [--seed N]` replays it: the
workload bytes are seed-deterministic and the kill is event-triggered
(pending ≥ 2), so the delivered end state replays identically.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from ..config import (BatchConfig, BatchEngine, PipelineConfig, RetryConfig,
                      SupervisionConfig)
from ..destinations import DelayedAckDestination
from ..models.lsn import Lsn
from ..models.table_state import TableStateType
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name
from . import failpoints
from .invariants import InvariantReport, LeakProbe, check_invariants
from .runner import (RecordingStore, RestartRecord, TracingDestination,
                     _hard_kill, _wait_until, _Workload)
from .scenario import Scenario


@dataclass
class AckWindowCrashRun:
    seed: int
    report: InvariantReport = field(default_factory=InvariantReport)
    restarts: list[RestartRecord] = field(default_factory=list)
    acks_in_flight_at_kill: int = 0
    max_acks_pending: int = 0
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": "ack_window_crash_k_inflight",
            "seed": self.seed,
            "ok": self.ok,
            "restarts": [r.describe() for r in self.restarts],
            "acks_in_flight_at_kill": self.acks_in_flight_at_kill,
            "max_acks_pending": self.max_acks_pending,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


async def run_ack_window_crash(seed: int = 7, txs: int = 8,
                               rows_per_tx: int = 6,
                               ack_delay_s: float = 0.25,
                               write_window: int = 4) -> AckWindowCrashRun:
    """Drive CDC until the write window verifiably holds ≥ 2 pending
    acks, hard-kill the pipeline with process-death semantics, restart
    from durable state, finish the workload, and check every recovery
    invariant. Small batches (2 KiB) + per-commit dispatch + a 250 ms
    ack delay stack the window deterministically within the first
    transactions."""
    failpoints.disarm_all()
    run = AckWindowCrashRun(seed=seed)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name="ack_window", description="K-in-flight crash",
                     txs=txs, rows_per_tx=rows_per_tx)
    workload = _Workload(shape, random.Random(seed))
    db = workload.build_db()
    store = RecordingStore()
    inner = TracingDestination()
    dest = DelayedAckDestination(inner, ack_delay_s)
    config = PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=BatchConfig(max_size_bytes=2048, max_fill_ms=25,
                          batch_engine=BatchEngine("tpu"),
                          write_window=write_window),
        apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        supervision=SupervisionConfig(
            check_interval_s=0.25, stall_deadline_s=10.0,
            hang_deadline_s=25.0, restart_backoff_s=1.0),
        wal_sender_timeout_ms=60_000,
        lag_sample_interval_s=0)

    def make_pipeline():
        from ..runtime import Pipeline

        return Pipeline(config=config, store=store, destination=dest,
                        source_factory=lambda: FakeSource(db))

    pipeline = make_pipeline()
    try:
        await pipeline.start()
        await _wait_until(
            lambda: all(
                (st := store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in workload.table_ids),
            30.0, "tables never ready")
        # commit transactions until ≥ 2 acks are in flight at once; each
        # commit's fast-path flush dispatches while earlier acks pend
        half = txs // 2
        while workload.tx_index < half:
            await workload.run_tx(db)
        await _wait_until(lambda: dest.pending >= 2, 20.0,
                          "the write window never held 2 acks in flight")
        run.acks_in_flight_at_kill = dest.pending

        # hard kill with K acks in flight: every pipeline task cancelled,
        # no drain — the unacked batches' durability never reached the
        # progress store (contiguous-prefix rule), so restart re-streams
        # them (at-least-once, budget = the window)
        await _hard_kill(pipeline)
        resume = await store.get_durable_progress(apply_slot_name(1))
        run.restarts.append(RestartRecord(
            kind="crash", resume_lsn=int(resume or Lsn.ZERO),
            at_tx=workload.tx_index))

        t_restart = time.monotonic()
        pipeline = make_pipeline()
        await pipeline.start()
        while workload.tx_index < txs:
            await workload.run_tx(db)
        await _wait_until(lambda: workload.delivered(inner), 30.0,
                          "workload never fully delivered after restart")
        run.restarts[-1].recovery_s = time.monotonic() - t_restart
        await pipeline.shutdown_and_wait()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        await _hard_kill(pipeline)
        await dest.shutdown()
        run.duration_s = time.monotonic() - t_start
    run.max_acks_pending = dest.max_pending

    if run.acks_in_flight_at_kill < 2:
        run.report.fail(
            f"kill landed with only {run.acks_in_flight_at_kill} ack(s) "
            f"in flight — the scenario did not exercise the window")

    from .invariants import _pipeline_thread_count

    try:
        await _wait_until(
            lambda: _pipeline_thread_count() <= leak_probe.pipeline_threads,
            3.0, "pipeline threads lingering")
    except TimeoutError as e:
        run.report.fail(str(e))

    # budget = 1 + 1 restart: the window-full of unacked batches may
    # deliver exactly twice, nothing may deliver three times
    check_invariants(
        expected=workload.expected, dest=inner, store=store,
        restarts=run.restarts, fault_firings=0, leak_probe=leak_probe,
        report=run.report)
    return run
