"""Recovery invariants checked after every chaos scenario run.

After the workload drains and the (possibly restarted) pipeline shuts
down, the checker reconstructs the destination's final view and asserts:

  zero-loss       — every committed source row is present (with its final
                    values) after recovery; deletes are absent;
  bounded-dup     — at-least-once duplicates are accounted: a row event
                    may appear more than once only within the re-streamed
                    window budget (restarts + injected fault firings);
                    a fault-free run must be exactly-once;
  monotonic-lsn   — the stored durable-progress trajectory of every
                    progress key never regresses;
  store-consistency — every table ends READY with a stored schema and
                    destination metadata; no table is parked Errored;
  no-leaks        — asyncio tasks, decode-pipeline worker threads, and
                    staging-arena leases return to their pre-run baseline;
                    the fault-injecting destination holds no unresolved
                    acks.

The checker REPORTS rather than raises: the runner embeds the report in
its JSON so the CLI can print every violation of a failing scenario at
once.
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass, field

from ..models.event import (DeleteEvent, InsertEvent, TruncateEvent,
                            UpdateEvent)
from ..models.table_state import TableStateType


@dataclass
class LeakProbe:
    """Pre-run baseline for the leak invariant."""

    tasks: int = 0
    pipeline_threads: int = 0
    arenas_outstanding: int = 0

    @classmethod
    def capture(cls) -> "LeakProbe":
        from ..ops.staging import ARENA_POOL

        try:
            tasks = len(asyncio.all_tasks())
        except RuntimeError:  # no running loop (CLI teardown)
            tasks = 0
        return cls(
            tasks=tasks,
            pipeline_threads=_pipeline_thread_count(),
            arenas_outstanding=ARENA_POOL.outstanding)


def _pipeline_thread_count() -> int:
    return sum(1 for t in threading.enumerate()
               if t.name.startswith("etl-") and t.name.endswith("-pipeline")
               and t.is_alive())


@dataclass
class InvariantReport:
    ok: bool = True
    violations: list[str] = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    def fail(self, message: str) -> None:
        self.ok = False
        self.violations.append(message)

    def describe(self) -> dict:
        return {"ok": self.ok, "violations": list(self.violations),
                "stats": dict(self.stats)}


def _row_pk(row) -> object:
    return row.values[0]


def reconstruct_final_view(dest, table_ids) -> dict:
    """{table_id: {pk: tuple(values)}} from copied rows + delivered
    events, replayed in WAL order.

    Events delivered before a table's LAST destination drop belong to an
    abandoned copy attempt (the drop-and-recopy crash-consistency path)
    and are excluded. The survivors are sorted by their WAL rank
    (commit_lsn, tx_ordinal) — at-least-once re-delivery then collapses
    naturally, because applying the same ranked event twice is idempotent
    — and applied as a destination would apply them:

      insert/update — upsert by pk; an update carrying an old image whose
                      identity differs from the new row (a PK-changing
                      update) also removes the OLD pk (the delete+upsert
                      split key-aware destinations perform); a new value
                      that is TOAST-unchanged patches column-wise,
                      keeping the stored value (the PATCH path);
      delete        — remove the pk (the old image under replica identity
                      DEFAULT carries only identity columns — the pk is
                      all that is consulted);
      truncate      — clear every listed table, including its copied
                      baseline rows (the barrier the coalesced columnar
                      write path must order correctly).
    """
    from ..models.cell import TOAST_UNCHANGED

    view: dict = {}
    last_drop = getattr(dest, "drop_seq_by_table", {})
    event_seqs = getattr(dest, "event_seqs", None)
    wanted = set(table_ids)
    for tid in table_ids:
        view[tid] = {_row_pk(r): tuple(r.values)
                     for r in dest.table_rows.get(tid, [])}
    # (rank, delivery order, table, event) for every surviving event that
    # touches a wanted table; truncates fan out to each listed table
    ordered: list = []
    for i, e in enumerate(dest.events):
        seq = event_seqs[i] if event_seqs is not None else i
        if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)):
            tid = e.schema.id
            if tid not in wanted or seq < last_drop.get(tid, -1):
                continue
            ordered.append(((int(e.commit_lsn), e.tx_ordinal), i, tid, e))
        elif isinstance(e, TruncateEvent):
            for sch in e.schemas:
                if sch.id not in wanted \
                        or seq < last_drop.get(sch.id, -1):
                    continue
                ordered.append(((int(e.commit_lsn), e.tx_ordinal), i,
                                sch.id, e))
    ordered.sort(key=lambda t: (t[0], t[1]))
    for _, _, tid, e in ordered:
        table = view[tid]
        if isinstance(e, TruncateEvent):
            table.clear()
        elif isinstance(e, DeleteEvent):
            table.pop(_row_pk(e.old_row), None)
        else:
            pk = _row_pk(e.row)
            prev = table.get(pk)
            if isinstance(e, UpdateEvent) and e.old_row is not None:
                old_pk = _row_pk(e.old_row)
                if old_pk != pk:
                    # a PK-changing update: the stored row (and so the
                    # TOAST patch source) lives under the OLD key
                    popped = table.pop(old_pk, None)
                    if popped is not None:
                        prev = popped
            values = tuple(
                (prev[k] if prev is not None and k < len(prev) else v)
                if v is TOAST_UNCHANGED else v
                for k, v in enumerate(e.row.values))
            table[pk] = values
    return view


def view_matches(dest, table_ids, expected: dict) -> bool:
    """True when the destination's reconstructed final view equals the
    committed source truth — the shared quiescence/verification test used
    by both the chaos runner and the workload bench harness, so the
    collapse rules above can never silently diverge between them."""
    view = reconstruct_final_view(dest, table_ids)
    for tid, rows in expected.items():
        got = view.get(tid, {})
        if set(got) != set(rows):
            return False
        if any(got[pk] != vals for pk, vals in rows.items()):
            return False
    return True


def check_invariants(*, expected: dict, dest, store,
                     restarts: list, fault_firings: int,
                     leak_probe: LeakProbe,
                     report: InvariantReport | None = None
                     ) -> InvariantReport:
    """Run every invariant; `expected` is {table_id: {pk: tuple(values)}}
    of committed source state, `restarts` the runner's restart records,
    `fault_firings` the number of injected fault firings (the
    redelivery budget), `leak_probe` the pre-run baseline."""
    r = report if report is not None else InvariantReport()

    # -- zero-loss ----------------------------------------------------------
    view = reconstruct_final_view(dest, list(expected))
    lost = dup_rows = 0
    for tid, rows in expected.items():
        got = view.get(tid, {})
        for pk, values in rows.items():
            if pk not in got:
                lost += 1
                r.fail(f"zero-loss: table {tid} row pk={pk!r} missing "
                       f"after recovery")
            elif got[pk] != values:
                r.fail(f"zero-loss: table {tid} pk={pk!r} final values "
                       f"{got[pk]!r} != committed {values!r}")
        for pk in got:
            if pk not in rows:
                r.fail(f"zero-loss: table {tid} pk={pk!r} present at the "
                       f"destination but deleted/never-committed at the "
                       f"source")

    # -- bounded duplication -------------------------------------------------
    budget = 1 + len(restarts) + fault_firings
    counts: dict = {}
    for e in dest.events:
        if not isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)):
            continue
        row = e.old_row if isinstance(e, DeleteEvent) else e.row
        key = (e.schema.id, int(e.commit_lsn), e.tx_ordinal,
               type(e).__name__, _row_pk(row))
        counts[key] = counts.get(key, 0) + 1
    max_dup = max(counts.values(), default=0)
    for key, n in counts.items():
        if n > budget:
            dup_rows += 1
            r.fail(f"bounded-dup: event {key} delivered {n}x, budget "
                   f"{budget} (1 + {len(restarts)} restarts + "
                   f"{fault_firings} fault firings)")

    # -- monotonic durable progress ------------------------------------------
    progress_log = getattr(store, "progress_log", {})
    for key, lsns in progress_log.items():
        for a, b in zip(lsns, lsns[1:]):
            if b < a:
                r.fail(f"monotonic-lsn: progress key {key!r} regressed "
                       f"{a} -> {b}")

    # -- store / table-state consistency -------------------------------------
    states = getattr(store, "_states", {})
    for tid in expected:
        st = states.get(tid)
        if st is None or st.type is not TableStateType.READY:
            r.fail(f"store-consistency: table {tid} final state "
                   f"{st.type.value if st else 'missing'}, expected ready")
        if not store_has_schema(store, tid):
            r.fail(f"store-consistency: table {tid} has no stored schema")
        if getattr(store, "_dest_meta", {}).get(tid) is None:
            r.fail(f"store-consistency: table {tid} has no destination "
                   f"metadata")

    # -- no leaked tasks / threads / arenas / held acks ----------------------
    from ..ops.staging import ARENA_POOL

    try:
        tasks_now = len(asyncio.all_tasks())
    except RuntimeError:
        tasks_now = 0
    if tasks_now > leak_probe.tasks:
        r.fail(f"no-leaks: {tasks_now - leak_probe.tasks} asyncio task(s) "
               f"leaked past shutdown")
    threads_now = _pipeline_thread_count()
    if threads_now > leak_probe.pipeline_threads:
        r.fail(f"no-leaks: {threads_now - leak_probe.pipeline_threads} "
               f"decode-pipeline worker thread(s) leaked")
    if ARENA_POOL.outstanding > leak_probe.arenas_outstanding:
        r.fail(f"no-leaks: {ARENA_POOL.outstanding - leak_probe.arenas_outstanding} "
               f"staging arena(s) leased but never released")
    held = getattr(dest, "held_ack_count", None)
    if held:
        r.fail(f"no-leaks: destination still holds {held} unresolved "
               f"ack(s)")

    r.stats.update({
        "tables": len(expected),
        "lost_rows": lost,
        "duplicate_keys_over_budget": dup_rows,
        "expected_rows": sum(len(v) for v in expected.values()),
        "delivered_events": sum(
            1 for e in dest.events
            if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent))),
        "max_duplication": max_dup,
        "duplication_budget": budget,
        "restarts": len(restarts),
        "fault_firings": fault_firings,
    })
    return r


def store_has_schema(store, tid) -> bool:
    schemas = getattr(store, "_schemas", None)
    if schemas is None:
        return True  # non-memory store: not introspectable here
    return bool(schemas.get(tid))
