"""Autoscale chaos: closed-loop elasticity under traffic, and a
controller hard-kill mid-rebalance.

Two scenarios, both deterministic per seed (same workload bytes, same
decision trace — the controller is ticked at FIXED points in the
scenario script and the policy bands sit orders of magnitude away from
the operating points, so timing jitter cannot flip a decision):

  autoscale_surge_drain — the ISSUE 13 acceptance arc end-to-end:
      a K=2 fleet idles under light traffic (controller holds), a
      seeded backlog surge drives the policy over its up band and the
      controller actuates a two-phase rebalance to K=3 WHILE traffic
      flows, the backlog drains, the first post-drain tick must HOLD
      (cooldown), and once the cooldown expires the controller scales
      back to K=2. Zero-loss/bounded-dup invariants hold over the
      union of all three destinations across BOTH transitions.

  autoscale_controller_crash — the controller is hard-killed between
      journal persist and the epoch flip (mid-quiesce), leaving a
      pending journal entry and an in-flight rebalancing record. A
      fresh controller's resume() re-drives the SAME transition with
      the persisted fence, the fleet rolls, invariants hold, no slot
      is leaked (exactly K+1 apply slots exist after the flip), and a
      second resume() is a no-op.

`python -m etl_tpu.chaos --autoscale [--seed N]` replays both.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from ..autoscale import (ACTION_DOWN, ACTION_HOLD, ACTION_UP,
                         AutoscaleController, AutoscalePolicy,
                         AutoscalePolicyConfig, StoreSignalSource)
from ..models.lsn import Lsn
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name, parse_slot_name
from ..sharding import ShardCoordinator, ShardMap
from . import failpoints
from .invariants import (InvariantReport, LeakProbe, check_invariants,
                         view_matches)
from .runner import (RecordingStore, RestartRecord, TracingDestination,
                     _hard_kill, _wait_until, _Workload)
from .scenario import Scenario
from .sharded import SHARDED_TABLES, _UnionDest, _shard_pipeline_config, \
    _wait_shard_ready

#: chaos policy: bands far from both operating points (a ~200 KiB burst
#: vs a 16 KiB up band; a drained backlog of ~0 vs a 4 KiB down band),
#: so the SAME decision fires at the SAME scripted tick every seed
_POLICY = AutoscalePolicyConfig(
    min_shards=2, max_shards=3,
    drain_slo_s=1.0,
    up_backlog_bytes=16 * 1024,
    down_backlog_bytes=4 * 1024,
    up_ticks=2, down_ticks=1,
    cooldown_ticks=3,
    window_frames=8)


@dataclass
class AutoscaleChaosRun:
    scenario: str
    seed: int
    report: InvariantReport = field(default_factory=InvariantReport)
    restarts: list = field(default_factory=list)
    decision_trace: list = field(default_factory=list)
    k_track: list = field(default_factory=list)  # applied K after each tick
    journal: dict = field(default_factory=dict)
    union_matches: bool = False
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "ok": self.ok,
            "decision_trace": list(self.decision_trace),
            "k_track": list(self.k_track),
            "journal": dict(self.journal),
            "restarts": [r.describe() for r in self.restarts],
            "union_matches": self.union_matches,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


class _Fleet:
    """K in-process shard Pipelines over one shared store/source — the
    chaos stand-in for an orchestrator roll. `roll(k)` is what
    Orchestrator.scale_pipeline does to real pods: stop the old fleet,
    start one scoped replicator per shard of the new topology."""

    def __init__(self, db, store, dests, run: AutoscaleChaosRun):
        self.db = db
        self.store = store
        self.dests = dests
        self.run = run
        self.pipes: dict[int, object] = {}
        self.k = 0

    async def start(self, k: int) -> None:
        from ..runtime import Pipeline

        for shard in range(k):
            p = Pipeline(config=_shard_pipeline_config(shard, k),
                         store=self.store,
                         destination=self.dests[shard],
                         source_factory=lambda: FakeSource(self.db))
            await p.start()
            self.pipes[shard] = p
        self.k = k

    async def stop(self) -> None:
        for shard in sorted(self.pipes):
            p = self.pipes[shard]
            if p._apply_task is not None:
                await p.shutdown_and_wait()
            resume = await self.store.get_durable_progress(
                apply_slot_name(1, shard))
            self.run.restarts.append(RestartRecord(
                kind="clean", resume_lsn=int(resume or Lsn.ZERO),
                at_tx=0))
        self.pipes.clear()

    async def roll(self, k: int) -> None:
        await self.stop()
        await self.start(k)

    async def wait_ready(self, part: dict) -> None:
        await asyncio.gather(*(
            _wait_shard_ready(self.pipes[s].store, part[s], 30.0,
                              f"shard {s}: tables never ready")
            for s in self.pipes))

    async def wait_delivered(self, part: dict, expected: dict,
                             what: str) -> None:
        for s in self.pipes:
            await _wait_until(
                lambda s=s: view_matches(
                    self.dests[s], part[s],
                    {tid: expected[tid] for tid in part[s]}),
                30.0, f"{what}: shard {s} never delivered its slice")

    async def wait_union(self, table_ids, expected: dict,
                         what: str) -> None:
        """Post-rebalance convergence: rows committed BEFORE a fence
        live at the table's OLD owner's destination, so per-shard slice
        checks cannot pass after a move — the union of every
        destination against the committed truth is the oracle (the PR 9
        handoff test's stance)."""
        await _wait_until(
            lambda: view_matches(
                _UnionDest([self.dests[s] for s in sorted(self.dests)]),
                table_ids, expected),
            30.0, f"{what}: union never converged")


def _make_controller(store, db, fleet: "_Fleet | None",
                     run: AutoscaleChaosRun) -> AutoscaleController:
    holder = {"k": 2}

    async def on_scale(from_k: int, to_k: int, result) -> None:
        holder["k"] = to_k
        if fleet is not None:
            await fleet.roll(to_k)

    coordinator = ShardCoordinator(store, 1, lambda: FakeSource(db),
                                   quiesce_timeout_s=30.0,
                                   poll_interval_s=0.02)
    controller = AutoscaleController(
        store=store, pipeline_id=1,
        collector=StoreSignalSource(
            store, 1, lambda: FakeSource(db),
            shard_count_reader=lambda: holder["k"]),
        coordinator=coordinator,
        policy=AutoscalePolicy(_POLICY),
        scale_listener=on_scale)
    controller._holder = holder  # the chaos script reads applied K
    return controller


async def _tick(controller: AutoscaleController, tick_no: int,
                run: AutoscaleChaosRun):
    decision = await controller.tick(float(tick_no))
    run.decision_trace.append(
        {"tick": decision.tick, "action": decision.action,
         "k": f"{decision.current_k}->{decision.target_k}"})
    run.k_track.append(controller._holder["k"])
    return decision


async def _surge(workload: _Workload, db, txs: int) -> None:
    """Commit a burst without waiting for drain: the backlog the policy
    must react to. Tight loop — the apply loops get only the awaits
    inside commit, so most of the burst is still undrained after."""
    for _ in range(txs):
        await workload.run_tx(db)


async def _drive_through(task: asyncio.Task, workload: _Workload, db,
                         txs: int, what: str) -> None:
    """Keep traffic flowing WHILE an actuation runs: a fixed tx count
    (determinism), then wait the actuation out. The commits push every
    shard's durable progress past the fence — the quiesce completes
    because the system keeps working, not because the world stopped."""
    for _ in range(txs):
        await workload.run_tx(db)
        await asyncio.sleep(0.05)
    try:
        await asyncio.wait_for(task, 30.0)
    except Exception as e:
        raise RuntimeError(f"{what} failed") from e


async def _wait_backlog_drained(controller: AutoscaleController,
                                limit_bytes: int) -> None:
    """Gate the post-drain ticks on the SIGNAL the policy reads (not on
    destination contents): sampled aggregate backlog under the limit.
    Probe frames are NOT recorded into the controller's timeline."""

    async def drained() -> bool:
        frame = await controller.collector.sample(-1.0)
        controller.collector._tick -= 1  # probe, not a timeline tick
        return frame.aggregate_backlog_bytes <= limit_bytes

    deadline = time.monotonic() + 30.0
    while not await drained():
        if time.monotonic() >= deadline:
            raise TimeoutError("backlog never drained under "
                               f"{limit_bytes} bytes")
        await asyncio.sleep(0.05)


async def run_autoscale_surge_drain(seed: int = 7) -> AutoscaleChaosRun:
    """The end-to-end elasticity arc (module docstring)."""
    failpoints.disarm_all()
    run = AutoscaleChaosRun(scenario="autoscale_surge_drain", seed=seed)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name="autoscale", description="surge/drain",
                     tables=SHARDED_TABLES, rows_per_table=3,
                     txs=64, rows_per_tx=120)
    workload = _Workload(shape, random.Random(seed))
    db = workload.build_db()
    store = RecordingStore()
    dests = {s: TracingDestination() for s in range(3)}
    fleet = _Fleet(db, store, dests, run)
    controller = _make_controller(store, db, fleet, run)
    part2 = ShardMap(2).partition(workload.table_ids)
    part3 = ShardMap(3).partition(workload.table_ids)
    try:
        if any(not t for t in part2.values()) \
                or any(not t for t in part3.values()):
            run.report.fail("degenerate shard map: empty shard at K=2 or "
                            "K=3 — grow the table set")
            return run
        await fleet.start(2)
        await fleet.wait_ready(part2)
        tick = 0

        # quiet baseline: two ticks, both must hold at K=2
        for _ in range(2):
            await _surge(workload, db, 1)
            await fleet.wait_delivered(part2, workload.expected,
                                       "baseline")
            d = await _tick(controller, tick, run)
            tick += 1
            if d.action != ACTION_HOLD:
                run.report.fail(f"baseline tick {d.tick} decided "
                                f"{d.action}, expected hold")

        # the surge: a burst far over the up band, committed without
        # waiting for drain; two ticks build the sustained up votes and
        # the second one ACTUATES K=2->3 while traffic keeps flowing
        await _surge(workload, db, 16)
        d = await _tick(controller, tick, run)
        tick += 1
        if d.action != ACTION_HOLD:
            run.report.fail(f"tick {d.tick}: scale-up before the "
                            f"sustained-votes threshold")
        up_task = asyncio.ensure_future(_tick(controller, tick, run))
        tick += 1
        await _drive_through(up_task, workload, db, 6, "scale-up tick")
        d = up_task.result()
        if d.action != ACTION_UP or d.target_k != 3:
            run.report.fail(f"surge tick {d.tick} decided {d.action} "
                            f"(target {d.target_k}), expected 2->3")
        assignment = await store.get_shard_assignment()
        if assignment.shard_count != 3 or assignment.epoch != 1:
            run.report.fail(f"assignment after scale-up: {assignment}")
        await fleet.wait_ready(part3)

        # drain: the fleet catches up completely (backlog samples to
        # ZERO — the fake's WAL position is the last commit end, so a
        # fully-flushed fleet has durable == wal end exactly); the next
        # two ticks land inside the cooldown window and must hold even
        # though the down votes are already there
        await fleet.wait_union(workload.table_ids, workload.expected,
                               "drain")
        await _wait_backlog_drained(controller, 0)
        for _ in range(_POLICY.cooldown_ticks - 1):
            d = await _tick(controller, tick, run)
            tick += 1
            if d.action != ACTION_HOLD or "cooldown" not in d.reason:
                run.report.fail(
                    f"tick {d.tick}: expected a cooldown hold after the "
                    f"scale-up, got {d.action} ({d.reason})")

        # cooldown expires -> sustained quiet under the down band ->
        # scale back to K=2 (the retiring shard is already durable at
        # the fence, so the quiesce completes without extra traffic)
        down = await _tick(controller, tick, run)
        tick += 1
        if down.action != ACTION_DOWN or down.target_k != 2:
            run.report.fail(f"tick {down.tick}: expected scale-down "
                            f"3->2, got {down.action} ({down.reason})")
        else:
            assignment = await store.get_shard_assignment()
            if assignment.shard_count != 2 or assignment.epoch != 2:
                run.report.fail(
                    f"assignment after scale-down: {assignment}")
            await fleet.wait_ready(part2)

        # finish the workload at K=2 and converge
        while workload.tx_index < shape.txs:
            await workload.run_tx(db)
        await fleet.wait_union(workload.table_ids, workload.expected,
                               "final")
        run.journal = (await store.get_autoscale_journal()) or {}
        await fleet.stop()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        for p in fleet.pipes.values():
            await _hard_kill(p)
        for dst in dests.values():
            await dst.shutdown()
        run.duration_s = time.monotonic() - t_start

    _finish(run, workload, dests, store, leak_probe)
    # the journal must agree with the trace: exactly one up + one down,
    # both applied (a pending entry here would mean a leaked decision)
    entries = run.journal.get("entries", [])
    applied = [(e["action"], e["from_k"], e["to_k"]) for e in entries
               if e.get("status") == "applied"]
    if applied != [("scale_up", 2, 3), ("scale_down", 3, 2)]:
        run.report.fail(f"journal does not record the up/down pair as "
                        f"applied: {entries}")
    # the bit-identity evidence: the tick script is fixed and the policy
    # bands sit orders of magnitude from the operating points, so the
    # decision trace is the same exact sequence every run of a seed
    actions = [d["action"] for d in run.decision_trace]
    want = (["hold"] * 3 + ["scale_up"]
            + ["hold"] * (_POLICY.cooldown_ticks - 1) + ["scale_down"])
    if actions != want:
        run.report.fail(f"decision trace diverged: {actions} != {want}")
    return run


async def run_autoscale_controller_crash(seed: int = 7
                                         ) -> AutoscaleChaosRun:
    """Hard-kill the controller mid-actuation; a successor resumes via
    the persisted journal (module docstring)."""
    failpoints.disarm_all()
    run = AutoscaleChaosRun(scenario="autoscale_controller_crash",
                            seed=seed)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name="autoscale-crash", description="crash",
                     tables=SHARDED_TABLES, rows_per_table=3,
                     txs=48, rows_per_tx=120)
    workload = _Workload(shape, random.Random(seed))
    db = workload.build_db()
    store = RecordingStore()
    dests = {s: TracingDestination() for s in range(3)}
    fleet = _Fleet(db, store, dests, run)
    part2 = ShardMap(2).partition(workload.table_ids)
    part3 = ShardMap(3).partition(workload.table_ids)
    controller = _make_controller(store, db, fleet, run)
    try:
        await fleet.start(2)
        await fleet.wait_ready(part2)
        await _surge(workload, db, 2)
        await fleet.wait_delivered(part2, workload.expected, "baseline")
        d = await _tick(controller, 0, run)
        if d.action != ACTION_HOLD:
            run.report.fail(f"baseline decided {d.action}")

        # surge, build votes, then let the actuating tick start its
        # two-phase rebalance — and hard-kill it mid-quiesce, AFTER the
        # in-flight record persisted (the burst is undrained, so the
        # quiesce cannot have completed)
        await _surge(workload, db, 16)
        await _tick(controller, 1, run)  # first vote (hold)
        kill_task = asyncio.ensure_future(_tick(controller, 2, run))
        deadline = time.monotonic() + 15.0
        while True:
            assignment = await store.get_shard_assignment()
            if assignment is not None and assignment.rebalancing:
                break
            if kill_task.done():
                raise RuntimeError(
                    "actuation finished before the kill window — "
                    "quiesce completed against an undrained burst?")
            if time.monotonic() >= deadline:
                raise TimeoutError("rebalancing record never persisted")
            await asyncio.sleep(0.01)
        kill_task.cancel()  # the controller process dies here
        try:
            await kill_task
        except (asyncio.CancelledError, Exception):
            pass
        run.restarts.append(RestartRecord(
            kind="crash", resume_lsn=0, at_tx=workload.tx_index))

        journal = (await store.get_autoscale_journal()) or {}
        pending = [e for e in journal.get("entries", [])
                   if e.get("status") == "pending"]
        if len(pending) != 1 or pending[0]["to_k"] != 3:
            run.report.fail(f"expected exactly one pending K=2->3 "
                            f"journal entry after the kill: {journal}")

        # a fresh controller (the restarted process) resumes: the SAME
        # transition completes with the persisted fence while traffic
        # flows, and the fleet rolls onto K=3
        successor = _make_controller(store, db, fleet, run)
        resume_task = asyncio.ensure_future(successor.resume())
        await _drive_through(resume_task, workload, db, 6, "resume")
        settled = resume_task.result()
        if settled is None or settled.status != "applied":
            run.report.fail(f"resume() did not settle the pending "
                            f"decision: {settled}")
        assignment = await store.get_shard_assignment()
        if assignment.shard_count != 3 or assignment.epoch != 1 \
                or assignment.rebalancing:
            run.report.fail(f"assignment after resume: {assignment}")
        await fleet.wait_ready(part3)

        # no leaked slots: exactly one apply slot per shard of the new
        # topology — a resume that re-created the fence slot instead of
        # adopting it would show up here
        apply_slots = [n for n in db.slots
                       if (p := parse_slot_name(n)) is not None
                       and p.is_apply]
        if len(apply_slots) != 3:
            run.report.fail(f"expected 3 apply slots after the resumed "
                            f"flip, found {sorted(db.slots)}")

        # resume is idempotent: nothing pending, second call is a no-op
        if await successor.resume() is not None:
            run.report.fail("second resume() re-ran a settled decision")

        while workload.tx_index < shape.txs:
            await workload.run_tx(db)
        await fleet.wait_union(workload.table_ids, workload.expected,
                               "final")
        run.journal = (await store.get_autoscale_journal()) or {}
        await fleet.stop()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        for p in fleet.pipes.values():
            await _hard_kill(p)
        for dst in dests.values():
            await dst.shutdown()
        run.duration_s = time.monotonic() - t_start

    _finish(run, workload, dests, store, leak_probe)
    return run


def _finish(run: AutoscaleChaosRun, workload: _Workload, dests,
            store, leak_probe: LeakProbe) -> None:
    """Shared epilogue: thread drain, union reconstruction, invariants
    over the union of every destination (tables move between shards
    across epochs, so per-shard slices are epoch-dependent — the union
    vs committed truth is the loss/dup oracle, exactly the sharded
    scenario's cross-shard stance)."""
    from .invariants import _pipeline_thread_count

    # give decode-pipeline worker threads a beat to exit (close() is
    # asynchronous); the leak check inside check_invariants re-measures
    deadline = time.monotonic() + 3.0
    while _pipeline_thread_count() > leak_probe.pipeline_threads \
            and time.monotonic() < deadline:
        time.sleep(0.02)

    union = _UnionDest([dests[s] for s in sorted(dests)])
    run.union_matches = view_matches(union, workload.table_ids,
                                     workload.expected)
    if not run.union_matches:
        run.report.fail("union of shard destinations does not "
                        "reconstruct the committed source truth")
    check_invariants(
        expected=workload.expected, dest=union, store=store,
        restarts=run.restarts, fault_firings=0, leak_probe=leak_probe,
        report=run.report)
