"""The scenario corpus: every layer gets at least one fault, plus
compound crash-during-recovery cases. All of these run in tier-1
(tests/test_chaos.py) and from the CLI (`python -m etl_tpu.chaos`).

Layers covered (ISSUE 3 tentpole list):

  wire        — walsender disconnects mid-CDC, stream errors before
                table-sync streaming;
  decode      — pack / dispatch / fetch stage failures in the pipelined
                decode scheduler;
  device      — simulated OOM → host-oracle fallback (no stream failure);
  destination — transient rejects, the fail-after-apply lost-response
                ambiguity, partial-batch holds (Accepted, durable later);
  store       — state-commit, schema-commit, and progress-commit
                failures;
  crash       — hard process-style crash→restart mid-apply (between
                destination write and progress store — the at-least-once
                window), mid-copy, and crash-during-recovery compounds.
"""

from __future__ import annotations

from ..models.errors import ErrorKind
from .scenario import FaultKind, FaultSpec, Scenario
from . import failpoints as fp

SCENARIOS: tuple[Scenario, ...] = (
    # --- wire layer ---------------------------------------------------------
    Scenario(
        name="wire_disconnect_mid_cdc",
        description="walsender streams severed after tx 2; the apply "
                    "worker reconnects from durable progress",
        faults=(FaultSpec("wire", kind=FaultKind.SEVER, at_tx=2),),
        txs=6),
    Scenario(
        name="wire_error_before_streaming",
        description="table-sync catchup stream fails to start once; "
                    "worker rolls back and retries",
        faults=(FaultSpec(fp.BEFORE_STREAMING,
                          error_kind=ErrorKind.REPLICATION_STREAM_FAILED),),
        txs=5, tx_during_copy=True),
    # --- copy layer ---------------------------------------------------------
    Scenario(
        name="copy_partition_fault",
        description="a copy partition fails at its start boundary; the "
                    "table rolls back to a consistent recopy",
        faults=(FaultSpec(fp.COPY_PARTITION_START,
                          error_kind=ErrorKind.SOURCE_IO),),
        rows_per_table=6, txs=4),
    Scenario(
        name="copy_stream_fault",
        description="the COPY data stream errors mid-partition "
                    "(reference during-copy failpoint)",
        faults=(FaultSpec(fp.DURING_COPY,
                          error_kind=ErrorKind.SOURCE_IO),),
        rows_per_table=6, txs=4),
    # --- decode pipeline layer ----------------------------------------------
    Scenario(
        name="pipeline_pack_fault",
        description="the pack stage of the decode pipeline fails once; "
                    "the consumer sees the error and the worker retries "
                    "from durable progress",
        faults=(FaultSpec(fp.PIPELINE_PACK,
                          error_kind=ErrorKind.DEVICE_UNAVAILABLE,
                          after_hits=2),),
        txs=6),
    Scenario(
        name="pipeline_dispatch_fault",
        description="the dispatch stage fails once mid-stream (big "
                    "transactions so runs route past the oracle and "
                    "actually reach the dispatch stage)",
        faults=(FaultSpec(fp.PIPELINE_DISPATCH,
                          error_kind=ErrorKind.DEVICE_UNAVAILABLE,
                          after_hits=1),),
        txs=4, rows_per_tx=100),
    Scenario(
        name="pipeline_fetch_fault",
        description="the fetch stage fails once at the consumer",
        faults=(FaultSpec(fp.PIPELINE_FETCH,
                          error_kind=ErrorKind.DEVICE_UNAVAILABLE,
                          after_hits=2),),
        txs=6),
    # --- device layer -------------------------------------------------------
    Scenario(
        name="device_oom_fallback",
        description="simulated device OOM on two batches; the pipeline "
                    "degrades them to the host oracle with NO stream "
                    "failure (exactly-once must hold)",
        faults=(FaultSpec(fp.ENGINE_DEVICE_OOM,
                          error_kind=ErrorKind.DEVICE_UNAVAILABLE,
                          times=2),),
        txs=4, rows_per_tx=100),
    # --- destination layer --------------------------------------------------
    Scenario(
        name="dest_transient_reject",
        description="two transient destination rejects on the CDC write "
                    "path; apply retries re-stream the window",
        faults=(FaultSpec("write_events", kind=FaultKind.DEST_REJECT,
                          times=2, at_tx=1),),
        txs=6),
    Scenario(
        name="dest_fail_after_apply",
        description="the lost-response ambiguity: the write applies, the "
                    "ack reports failure; redelivery must stay within "
                    "the at-least-once budget",
        faults=(FaultSpec("write_events",
                          kind=FaultKind.DEST_FAIL_AFTER_APPLY,
                          at_tx=1),),
        txs=6),
    Scenario(
        name="dest_partial_batch_ack",
        description="a HOLD: one write acks Accepted and turns durable "
                    "only two transactions later; durable progress must "
                    "wait for the release",
        faults=(FaultSpec("write_events", kind=FaultKind.DEST_HOLD,
                          at_tx=1, hold_release_after_tx=3),),
        txs=6),
    Scenario(
        name="dest_copy_reject",
        description="the initial-copy write path rejects once; "
                    "crash-consistent drop-and-recopy",
        faults=(FaultSpec("write_table_rows", kind=FaultKind.DEST_REJECT),),
        rows_per_table=6, txs=4),
    # --- store layer --------------------------------------------------------
    Scenario(
        name="store_progress_commit_fault",
        description="the durable-progress store write fails once after a "
                    "flush (reference on_progress_store failpoint)",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE,
                          error_kind=ErrorKind.STATE_STORE_FAILED),),
        txs=6),
    Scenario(
        name="store_state_commit_fault",
        description="a table-state commit fails during table sync; the "
                    "worker parks Errored and the timed retry recovers",
        faults=(FaultSpec(fp.STORE_STATE_COMMIT,
                          error_kind=ErrorKind.STATE_STORE_FAILED,
                          after_hits=1),),
        txs=4),
    Scenario(
        name="store_schema_commit_fault",
        description="a schema-store commit fails during the copy phase",
        faults=(FaultSpec(fp.STORE_SCHEMA_COMMIT,
                          error_kind=ErrorKind.STATE_STORE_FAILED),),
        txs=4),
    # --- crash→restart ------------------------------------------------------
    Scenario(
        name="crash_mid_apply",
        description="hard crash BETWEEN destination write and progress "
                    "store (the at-least-once window): the restarted "
                    "pipeline re-streams the un-persisted window and "
                    "duplicates stay within budget",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1),),
        txs=6, expect_restarts=1),
    Scenario(
        name="crash_restart_warm_programs",
        description="hard crash with a WARM program cache (ISSUE 12): "
                    "the runner seeds a cache dir, clears the in-process "
                    "program caches at the restart (process-death "
                    "semantics for jit state), and the restarted "
                    "pipeline must serve its first batch from DISK-"
                    "cached programs — compile-counter delta == 0, disk "
                    "hits > 0 — while the usual zero-loss/bounded-dup "
                    "invariants hold",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1),),
        txs=4, rows_per_tx=96, expect_restarts=1,
        program_cache="warm"),
    Scenario(
        name="crash_restart_corrupt_program_cache",
        description="hard crash with a CORRUPTED program cache (ISSUE "
                    "12): every cache file is garbage at restart — the "
                    "load must degrade to a clean rebuild (invalid-miss "
                    "counted, file deleted, batches decode on the "
                    "oracle while the rebuild runs) and the invariants "
                    "must hold; a corrupt cache must never crash or "
                    "wedge a replicator",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1),),
        txs=4, rows_per_tx=96, expect_restarts=1,
        program_cache="corrupt"),
    Scenario(
        name="crash_mid_copy",
        description="hard crash mid-COPY: restart must drop the "
                    "half-written destination table and recopy",
        faults=(FaultSpec(fp.DURING_COPY, kind=FaultKind.CRASH),),
        rows_per_table=6, txs=4, expect_restarts=1),
    Scenario(
        name="crash_during_recovery_copy_then_apply",
        description="compound: crash mid-copy, then a SECOND crash in "
                    "the restarted pipeline's apply path while it is "
                    "still recovering",
        faults=(FaultSpec(fp.DURING_COPY, kind=FaultKind.CRASH),
                FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1)),
        rows_per_table=6, txs=5, expect_restarts=2),
    Scenario(
        name="crash_then_dest_fault_during_recovery",
        description="compound: crash mid-apply, then a transient "
                    "destination reject while the restarted pipeline "
                    "re-streams",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1),
                FaultSpec("write_events", kind=FaultKind.DEST_REJECT,
                          at_tx=3)),
        txs=6, expect_restarts=1),
    # --- multi-table + cpu-engine coverage ----------------------------------
    Scenario(
        name="multi_table_wire_and_dest",
        description="two tables, a sever and a destination reject in one "
                    "run",
        faults=(FaultSpec("wire", kind=FaultKind.SEVER, at_tx=2),
                FaultSpec("write_events", kind=FaultKind.DEST_REJECT,
                          at_tx=3)),
        tables=2, txs=6),
    Scenario(
        name="cpu_engine_crash_mid_apply",
        description="the reference per-tuple engine under the same "
                    "at-least-once-window crash",
        faults=(FaultSpec(fp.ON_PROGRESS_STORE, kind=FaultKind.CRASH,
                          after_hits=1),),
        txs=5, expect_restarts=1, engine="cpu"),
    # --- stall layer (ISSUE 4): silent hangs the watchdog must detect ------
    Scenario(
        name="stall_apply_frame_read",
        description="the apply loop wedges mid-frame (stops beating "
                    "entirely); the watchdog's hang detection cancels "
                    "and restarts the apply worker from durable progress",
        faults=(FaultSpec(fp.APPLY_FRAME_READ, kind=FaultKind.STALL,
                          stall_s=20.0, after_hits=6),),
        txs=6, fast_watchdog=True, expect_health_recovery=True),
    Scenario(
        name="stall_dest_write",
        description="a destination write never returns; the per-op "
                    "timeout bound (or the stall watchdog, whichever "
                    "fires first) classifies it and the worker "
                    "re-streams",
        faults=(FaultSpec(fp.DESTINATION_WRITE, kind=FaultKind.STALL,
                          stall_s=20.0, after_hits=2),),
        txs=6, fast_watchdog=True, expect_health_recovery=True),
    Scenario(
        name="stall_dest_flush",
        description="a destination flush (wait_durable) never resolves; "
                    "the bounded ack times out and recovery re-streams "
                    "the window",
        faults=(FaultSpec(fp.DESTINATION_FLUSH, kind=FaultKind.STALL,
                          stall_s=20.0, after_hits=2),),
        txs=6, fast_watchdog=True, expect_health_recovery=True),
    Scenario(
        name="stall_store_progress_commit",
        description="the durable-progress store write hangs INSIDE the "
                    "apply loop (heartbeat goes stale); hang detection "
                    "restarts the worker",
        faults=(FaultSpec(fp.STORE_PROGRESS_COMMIT, kind=FaultKind.STALL,
                          stall_s=20.0, after_hits=1),),
        txs=6, fast_watchdog=True, expect_health_recovery=True),
    Scenario(
        name="stall_copy_partition",
        description="a copy partition wedges before reading data; the "
                    "table-sync worker is cancelled, parks Errored, and "
                    "the timed retry recopies",
        faults=(FaultSpec(fp.COPY_PARTITION_START, kind=FaultKind.STALL,
                          stall_s=20.0),),
        rows_per_table=6, txs=4, fast_watchdog=True,
        expect_health_recovery=True),
    Scenario(
        name="stall_decode_fetch",
        description="a decode-pipeline fetch blocks its thread mid-copy "
                    "(the one stall that parks a REAL thread): the "
                    "owning sync worker is restarted by hang detection "
                    "while the thread unblocks on its own deadline",
        faults=(FaultSpec(fp.PIPELINE_FETCH, kind=FaultKind.STALL,
                          stall_s=3.0),),
        rows_per_table=8, txs=4, fast_watchdog=True,
        expect_health_recovery=True),
)


def get_scenario(name: str) -> Scenario:
    for s in SCENARIOS + WORKLOAD_MATRIX:
        if s.name == name:
            return s
    raise KeyError(f"unknown scenario {name!r}; known: "
                   f"{', '.join(s.name for s in SCENARIOS + WORKLOAD_MATRIX)}")


# --- chaos × workload matrix (ISSUE 7) --------------------------------------
#
# A curated subset of the corpus re-run under adversarial workload
# profiles (etl_tpu/workloads): the invariant checker must hold for
# update/delete/TOAST/truncate/DDL/partitioned traffic through the same
# fault schedules, not just insert-CDC. Curated rather than full
# cross-product to stay inside the tier-1 wall-clock budget — the
# crash→restart base runs against every profile (the at-least-once window
# is where non-insert semantics bite hardest); the stall and wire bases
# sample the profiles whose recovery differs most (truncate barriers,
# full-identity re-streams, DDL mid-recovery, partition fan-in).

#: the non-insert profiles the matrix proves out (≥4 required by the
#: acceptance criteria)
WORKLOAD_MATRIX_PROFILES = (
    "update_heavy_default", "update_heavy_full", "delete_heavy_default",
    "toast_heavy_full", "truncate_storm", "ddl_churn",
)


def _with_workload(base_name: str, profile: str) -> Scenario:
    from dataclasses import replace

    base = next(s for s in SCENARIOS if s.name == base_name)
    return replace(
        base, name=f"{base_name}__{profile}", workload=profile,
        description=f"{base.description} [workload={profile}]")


WORKLOAD_MATRIX: tuple[Scenario, ...] = tuple(
    [_with_workload("crash_mid_apply", p) for p in WORKLOAD_MATRIX_PROFILES]
    + [_with_workload("stall_dest_write", p)
       for p in ("update_heavy_full", "truncate_storm")]
    + [_with_workload("wire_disconnect_mid_cdc", p)
       for p in ("delete_heavy_default", "ddl_churn", "partitioned_root",
                 "tiny_txs")]
)
