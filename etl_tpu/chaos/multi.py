"""Multi-pipeline chaos: two replication streams sharing one device set.

The fair batch-admission scheduler (ops/pipeline.AdmissionScheduler) is
the one piece of state that spans pipelines, so it gets its own scenario
shape: two full Pipelines (separate fake databases, stores, and
destinations — they share NOTHING but the process device set and its
scheduler) run concurrently, one of them is hard-killed mid-stream with
process-death semantics and restarted, and the run proves

  1. the SURVIVOR keeps decoding while the other stream is down — its
     remaining transactions must deliver during the outage window, which
     fails if the dead pipeline stranded admission tickets the survivor
     needed (capacity is deliberately small so stranded tickets bite);
  2. the zero-loss / bounded-dup / monotonic-LSN / leak invariants hold
     for BOTH streams independently (chaos/invariants.py per stream);
  3. scheduler shutdown leaks nothing: after both pipelines close, the
     scheduler holds zero tickets and zero tenants, and staging-arena
     leases and decode-pipeline threads return to their baselines.

`python -m etl_tpu.chaos --multi-pipeline [--seed N]` replays it; the
same seed replays the same workload bytes on both streams.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from ..config import (BatchConfig, BatchEngine, PipelineConfig, RetryConfig,
                      SupervisionConfig)
from ..models.lsn import Lsn
from ..models.table_state import TableStateType
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name
from . import failpoints
from .invariants import InvariantReport, LeakProbe, check_invariants
from .runner import (RecordingStore, RestartRecord, TracingDestination,
                     _hard_kill, _wait_until, _Workload)
from .scenario import Scenario

#: distinct table-id bases so a cross-stream delivery bug (events of one
#: stream reaching the other's destination) breaks invariants loudly
#: instead of aliasing
_STREAM_BASE_IDS = (16384, 18432)


@dataclass
class MultiPipelineRun:
    seed: int
    report: InvariantReport = field(default_factory=InvariantReport)
    restarts: list[RestartRecord] = field(default_factory=list)
    survivor_txs_during_outage: int = 0
    scheduler_drained: bool = False
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": "multi_pipeline_crash_one_stream",
            "seed": self.seed,
            "ok": self.ok,
            "restarts": [r.describe() for r in self.restarts],
            "survivor_txs_during_outage": self.survivor_txs_during_outage,
            "scheduler_drained": self.scheduler_drained,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


class _Stream:
    """One replication stream: its own fake database, store, destination,
    and Pipeline — nothing shared with the other stream but the process
    device set and its admission scheduler."""

    def __init__(self, index: int, scenario: Scenario, seed: int,
                 admission_capacity: int):
        self.index = index
        self.workload = _Workload(scenario, random.Random(seed))
        # re-base the table ids so the two streams can never alias
        base = _STREAM_BASE_IDS[index]
        self.workload.table_ids = [base + i for i in range(scenario.tables)]
        self.workload.expected = {t: {} for t in self.workload.table_ids}
        self.workload._next_pk = {t: 1 for t in self.workload.table_ids}
        self.db = self.workload.build_db()
        self.store = RecordingStore()
        self.dest = TracingDestination()
        # supervision LIVE but lenient (the runner's fault-scenario
        # stance): deadlines far above any legitimate pause here, so the
        # dup budget needs no supervision-restart accounting
        self.config = PipelineConfig(
            pipeline_id=index + 1, publication_name="pub",
            batch=BatchConfig(max_size_bytes=64 * 1024, max_fill_ms=25,
                              batch_engine=BatchEngine("tpu"),
                              admission_capacity=admission_capacity),
            apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                    max_delay_ms=120),
            table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                    max_delay_ms=120),
            supervision=SupervisionConfig(
                check_interval_s=0.25, stall_deadline_s=10.0,
                hang_deadline_s=25.0, restart_backoff_s=1.0),
            wal_sender_timeout_ms=60_000,
            lag_sample_interval_s=0)
        self.pipeline = None

    def make_pipeline(self):
        from ..runtime import Pipeline

        self.pipeline = Pipeline(config=self.config, store=self.store,
                                 destination=self.dest,
                                 source_factory=lambda: FakeSource(self.db))
        return self.pipeline

    async def wait_ready(self) -> None:
        await _wait_until(
            lambda: all(
                (st := self.store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in self.workload.table_ids),
            30.0, f"stream {self.index}: tables never ready")

    async def wait_delivered(self, what: str) -> None:
        await _wait_until(lambda: self.workload.delivered(self.dest),
                          30.0, f"stream {self.index}: {what}")


async def run_multi_pipeline_scenario(seed: int = 7, txs: int = 6,
                                      rows_per_tx: int = 100,
                                      admission_capacity: int = 2
                                      ) -> MultiPipelineRun:
    """Two streams share the admission scheduler; stream 1 is hard-killed
    after half its transactions and restarted. rows_per_tx defaults past
    the host-XLA row threshold so flushes actually take admission tickets
    (sub-threshold flushes decode on the oracle, which holds none), and
    admission_capacity=2 keeps the scheduler tight enough that tickets
    stranded by the kill would visibly choke the survivor."""
    failpoints.disarm_all()
    from ..ops.pipeline import global_admission, reset_global_admission

    run = MultiPipelineRun(seed=seed)
    t_start = time.monotonic()
    reset_global_admission()
    leak_probe = LeakProbe.capture()
    shape = Scenario(name="multi", description="per-stream workload",
                     txs=txs, rows_per_tx=rows_per_tx)
    survivor = _Stream(0, shape, seed, admission_capacity)
    victim = _Stream(1, shape, seed + 1_000, admission_capacity)
    streams = (survivor, victim)
    try:
        for s in streams:
            s.make_pipeline()
            await s.pipeline.start()
        await asyncio.gather(*(s.wait_ready() for s in streams))
        half = txs // 2

        async def drive(s: _Stream, until: int) -> None:
            while s.workload.tx_index < until:
                await s.workload.run_tx(s.db)

        await asyncio.gather(*(drive(s, half) for s in streams))

        # hard crash stream 1: every task cancelled, no drain — the
        # decode pipeline's finally path must hand its admission tickets
        # back (DecodePipeline.close → TenantAdmission.close)
        await _hard_kill(victim.pipeline)
        resume = await victim.store.get_durable_progress(
            apply_slot_name(victim.config.pipeline_id))
        run.restarts.append(RestartRecord(
            kind="crash", resume_lsn=int(resume or Lsn.ZERO),
            at_tx=victim.workload.tx_index))

        # the survivor must keep decoding DURING the outage: its whole
        # remaining workload delivers while stream 1 is down
        before = survivor.workload.tx_index
        await drive(survivor, txs)
        await survivor.wait_delivered("survivor stalled during the "
                                      "other stream's outage")
        run.survivor_txs_during_outage = survivor.workload.tx_index - before

        # restart the crashed stream from its durable state; it must
        # finish its workload and reconverge
        t_restart = time.monotonic()
        victim.make_pipeline()
        await victim.pipeline.start()
        await drive(victim, txs)
        await victim.wait_delivered("crashed stream never reconverged "
                                    "after restart")
        run.restarts[-1].recovery_s = time.monotonic() - t_restart

        for s in streams:
            await s.pipeline.shutdown_and_wait()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        for s in streams:
            if s.pipeline is not None:
                await _hard_kill(s.pipeline)
            await s.dest.shutdown()
        run.duration_s = time.monotonic() - t_start

    # decode-pipeline worker threads exit asynchronously after close()
    from .invariants import _pipeline_thread_count

    try:
        await _wait_until(
            lambda: _pipeline_thread_count() <= leak_probe.pipeline_threads,
            3.0, "pipeline threads lingering")
    except TimeoutError as e:
        run.report.fail(str(e))

    # the scheduler-leak half of the satellite: zero tickets and zero
    # tenants after both pipelines closed — a stranded TenantAdmission
    # would throttle every future stream in the process
    sched = global_admission(admission_capacity)
    stats = sched.stats()
    run.scheduler_drained = stats["in_flight"] == 0 and not stats["tenants"]
    if not run.scheduler_drained:
        run.report.fail(
            f"admission scheduler leaked after shutdown: {stats}")

    # invariants per stream, independently: the victim's crash funds one
    # restart's worth of dup budget; the survivor gets none
    for s, restarts in ((survivor, []), (victim, run.restarts)):
        check_invariants(
            expected=s.workload.expected, dest=s.dest, store=s.store,
            restarts=restarts, fault_firings=0, leak_probe=leak_probe,
            report=run.report)
    return run
