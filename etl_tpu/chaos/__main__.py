"""CLI: `python -m etl_tpu.chaos --seed N [--scenario NAME]`.

Replays scenarios deterministically: the same (scenario, seed) pair
produces the same workload and the same injection trace, so a failing
run from CI reproduces locally from its two numbers. Prints one JSON
object per scenario (sorted keys) and exits non-zero if any invariant
was violated.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m etl_tpu.chaos",
        description="deterministic fault-injection scenario runner")
    parser.add_argument("--seed", type=int, default=7,
                        help="workload + injection RNG seed (default 7)")
    parser.add_argument("--scenario", default=None,
                        help="run one scenario by name (default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list scenario names and exit")
    parser.add_argument("--timeout", type=float, default=60.0,
                        help="per-scenario timeout in seconds")
    args = parser.parse_args(argv)

    import os

    if os.environ.get("JAX_PLATFORMS") is None:
        # chaos runs never need the accelerator tunnel; keep the CLI
        # usable on hosts without one (same knob as tests/conftest.py)
        os.environ["JAX_PLATFORMS"] = "cpu"

    from .corpus import SCENARIOS, get_scenario
    from .runner import run_scenario

    if args.list:
        for s in SCENARIOS:
            print(f"{s.name}: {s.description}")
        return 0

    scenarios = [get_scenario(args.scenario)] if args.scenario else \
        list(SCENARIOS)
    all_ok = True
    for scenario in scenarios:
        run = asyncio.run(run_scenario(scenario, args.seed,
                                       timeout_s=args.timeout))
        print(json.dumps(run.describe(), sort_keys=True))
        all_ok = all_ok and run.ok
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
