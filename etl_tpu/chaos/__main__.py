"""CLI: `python -m etl_tpu.chaos --seed N [--scenario NAME]`.

Replays scenarios deterministically: the same (scenario, seed) pair
produces the same workload and the same injection trace, so a failing
run from CI reproduces locally from its two numbers. Prints one JSON
object per scenario (sorted keys) and exits non-zero if any invariant
was violated.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m etl_tpu.chaos",
        description="deterministic fault-injection scenario runner")
    parser.add_argument("--seed", type=int, default=7,
                        help="workload + injection RNG seed (default 7)")
    parser.add_argument("--scenario", default=None,
                        help="run one scenario by name (default: all)")
    parser.add_argument("--workload", default=None,
                        help="drive the selected scenario(s) with a named "
                             "workload profile (etl_tpu/workloads) instead "
                             "of the default mixed-insert traffic; the "
                             "run manifest and injection trace identify "
                             "the profile and replay bit-identically per "
                             "(scenario, workload, seed)")
    parser.add_argument("--matrix", action="store_true",
                        help="run the curated chaos x workload matrix "
                             "(corpus.WORKLOAD_MATRIX) instead of the "
                             "base corpus")
    parser.add_argument("--multi-pipeline", dest="multi_pipeline",
                        action="store_true",
                        help="run the multi-pipeline scenario instead of "
                             "the corpus: two replication streams share "
                             "the batch-admission scheduler, one is "
                             "hard-killed mid-stream and restarted; the "
                             "survivor must keep decoding, invariants "
                             "must hold for both, and the scheduler must "
                             "drain without leaking tickets or tenants")
    parser.add_argument("--sharded", dest="sharded", type=int, nargs="?",
                        const=2, default=None, metavar="K",
                        help="run the sharded pod-kill scenario instead "
                             "of the corpus: K shard replicators (default "
                             "2) split one publication over one shared "
                             "store, one shard is hard-killed mid-stream "
                             "and restarted; survivors must deliver their "
                             "whole remaining slice during the outage, "
                             "per-shard AND cross-shard-union invariants "
                             "must hold, and no shard may see another's "
                             "tables")
    parser.add_argument("--ack-window", dest="ack_window",
                        action="store_true",
                        help="run the ack-window crash scenario instead "
                             "of the corpus: CDC flows into a destination "
                             "whose acks turn durable late, the pipeline "
                             "is hard-killed while >= 2 acks are "
                             "verifiably in flight, and the restart must "
                             "re-stream the unacked window — zero-loss, "
                             "dup budget = the window, monotonic durable "
                             "LSN")
    parser.add_argument("--autoscale", dest="autoscale",
                        action="store_true",
                        help="run the closed-loop elasticity scenarios "
                             "instead of the corpus: a seeded backlog "
                             "surge must scale K=2->3 under flowing "
                             "traffic via the autoscale controller, the "
                             "drain must scale back 3->2 only after the "
                             "cooldown, invariants must hold across both "
                             "rebalances; then the controller is hard-"
                             "killed mid-rebalance and a successor must "
                             "resume via the persisted decision journal "
                             "with no leaked slots")
    parser.add_argument("--dlq", dest="dlq", action="store_true",
                        help="run the poison-pill / dead-letter "
                             "scenarios instead of the corpus: (1) "
                             "seeded poison rows mid-stream must bisect "
                             "to the DLQ within the probe-write bound, "
                             "quarantine the poisoned table once the "
                             "budget trips while every OTHER table "
                             "delivers its full workload, hold "
                             "delivered ∪ dead-lettered == committed "
                             "truth, and replay+unquarantine must "
                             "restore exact truth idempotently; (2) a "
                             "hard kill mid-bisection must reconverge "
                             "within the dup budget after restart")
    parser.add_argument("--exactly-once", dest="exactly_once",
                        action="store_true",
                        help="run the exactly-once hard-kill matrix "
                             "instead of the corpus: CDC flows into a "
                             "transactional sink that records the acked "
                             "WAL coordinate range atomically with the "
                             "data, the pipeline is hard-killed at "
                             "mid-write, post-write-pre-progress-commit, "
                             "and mid-recovery windows, and every "
                             "restart must recover the sink high-water "
                             "mark and converge with duplication == 0, "
                             "zero-loss, and a monotone high-water mark")
    parser.add_argument("--fleet", dest="fleet", action="store_true",
                        help="run the fleet reconciliation scenario "
                             "instead of the corpus: a 100-pipeline "
                             "declarative fleet (seeded tenancy "
                             "profiles, biting quotas) reconciles from "
                             "empty, absorbs one versioned "
                             "add/remove/resize edit, the coordinator "
                             "is hard-killed mid-roll in BOTH crash "
                             "windows (before and after the actuation "
                             "landed) and the successor must converge "
                             "via the per-pipeline actuation journal "
                             "with zero double-actuations, zero leaked "
                             "pipelines, and per-pipeline zero-loss / "
                             "bounded-dup invariants intact; the three "
                             "policy plugins (PID lag-target, adaptive "
                             "ack-depth, admission weights) run on one "
                             "signal bus")
    parser.add_argument("--list", action="store_true",
                        help="list scenario names and exit")
    parser.add_argument("--timeout", type=float, default=60.0,
                        help="per-scenario timeout in seconds")
    args = parser.parse_args(argv)

    import os

    if os.environ.get("JAX_PLATFORMS") is None:
        # chaos runs never need the accelerator tunnel; keep the CLI
        # usable on hosts without one (same knob as tests/conftest.py)
        os.environ["JAX_PLATFORMS"] = "cpu"

    from .corpus import SCENARIOS, WORKLOAD_MATRIX, get_scenario
    from .runner import run_scenario

    if args.list:
        for s in SCENARIOS + WORKLOAD_MATRIX:
            print(f"{s.name}: {s.description}")
        return 0

    if args.exactly_once:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.autoscale or args.multi_pipeline \
                or args.ack_window or args.dlq or args.fleet:
            parser.error("--exactly-once runs its own hard-kill matrix "
                         "and cannot be combined with --matrix/"
                         "--workload/--scenario/--sharded/--autoscale/"
                         "--multi-pipeline/--ack-window/--dlq/--fleet")
        from .exactly_once import run_exactly_once_crash

        run = asyncio.run(run_exactly_once_crash(seed=args.seed))
        print(json.dumps(run.describe(), sort_keys=True))
        return 0 if run.ok else 1

    if args.fleet:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.autoscale or args.multi_pipeline \
                or args.ack_window or args.dlq:
            parser.error("--fleet runs its own 100-pipeline "
                         "reconciliation scenario and cannot be "
                         "combined with --matrix/--workload/--scenario/"
                         "--sharded/--autoscale/--multi-pipeline/"
                         "--ack-window/--dlq")
        from .fleet import run_fleet_chaos

        run = asyncio.run(run_fleet_chaos(seed=args.seed))
        print(json.dumps(run.describe(), sort_keys=True))
        return 0 if run.ok else 1

    if args.multi_pipeline:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.autoscale:
            parser.error("--multi-pipeline runs its own two-stream "
                         "scenario and cannot be combined with "
                         "--matrix/--workload/--scenario/--sharded/"
                         "--autoscale")
        from .multi import run_multi_pipeline_scenario

        run = asyncio.run(run_multi_pipeline_scenario(seed=args.seed))
        print(json.dumps(run.describe(), sort_keys=True))
        return 0 if run.ok else 1

    if args.ack_window:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.autoscale or args.multi_pipeline:
            parser.error("--ack-window runs its own K-in-flight crash "
                         "scenario and cannot be combined with --matrix/"
                         "--workload/--scenario/--sharded/--autoscale/"
                         "--multi-pipeline")
        from .ack_window import run_ack_window_crash

        run = asyncio.run(run_ack_window_crash(seed=args.seed))
        print(json.dumps(run.describe(), sort_keys=True))
        return 0 if run.ok else 1

    if args.dlq:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.autoscale or args.multi_pipeline or args.ack_window:
            parser.error("--dlq runs its own poison-isolation scenarios "
                         "and cannot be combined with --matrix/"
                         "--workload/--scenario/--sharded/--autoscale/"
                         "--multi-pipeline/--ack-window")
        from .dlq import run_dlq_scenarios

        runs = asyncio.run(run_dlq_scenarios(seed=args.seed))
        all_ok = True
        for run in runs:
            print(json.dumps(run.describe(), sort_keys=True))
            all_ok = all_ok and run.ok
        return 0 if all_ok else 1

    if args.autoscale:
        if args.matrix or args.workload or args.scenario or args.sharded \
                or args.multi_pipeline:
            parser.error("--autoscale runs its own elasticity scenarios "
                         "and cannot be combined with --matrix/"
                         "--workload/--scenario/--sharded/"
                         "--multi-pipeline")
        from .autoscale import (run_autoscale_controller_crash,
                                run_autoscale_surge_drain)

        all_ok = True
        for runner_fn in (run_autoscale_surge_drain,
                          run_autoscale_controller_crash):
            run = asyncio.run(runner_fn(seed=args.seed))
            print(json.dumps(run.describe(), sort_keys=True))
            all_ok = all_ok and run.ok
        return 0 if all_ok else 1

    if args.sharded is not None:
        if args.matrix or args.workload or args.scenario:
            parser.error("--sharded runs its own K-shard pod-kill "
                         "scenario and cannot be combined with "
                         "--matrix/--workload/--scenario")
        if args.sharded < 2:
            parser.error("--sharded needs K >= 2 (killing the only "
                         "shard proves nothing about isolation)")
        from .sharded import run_sharded_scenario

        run = asyncio.run(run_sharded_scenario(seed=args.seed,
                                               shards=args.sharded))
        print(json.dumps(run.describe(), sort_keys=True))
        return 0 if run.ok else 1

    if args.matrix:
        # the matrix entries carry their profile in their NAME
        # (base__profile); overriding it with --workload (or narrowing
        # with --scenario, which already selects matrix entries by name
        # on its own) would make the manifest name a run that didn't
        # happen
        if args.workload or args.scenario:
            parser.error("--matrix cannot be combined with --workload or "
                         "--scenario (use --scenario <base>__<profile> to "
                         "run one matrix entry)")
        scenarios = list(WORKLOAD_MATRIX)
    elif args.scenario:
        scenarios = [get_scenario(args.scenario)]
    else:
        scenarios = list(SCENARIOS)
    if args.workload:
        from dataclasses import replace

        from ..workloads import get_profile

        get_profile(args.workload)  # fail fast on a typo'd profile name
        # matrix entries embed their profile in their NAME
        # (base__profile); rewriting the workload underneath one would
        # produce a manifest whose name claims traffic that didn't run —
        # the same hazard the --matrix guard above blocks
        clash = [s.name for s in scenarios
                 if s.workload is not None and s.workload != args.workload]
        if clash:
            parser.error(f"--workload conflicts with matrix entr"
                         f"{'ies' if len(clash) > 1 else 'y'} "
                         f"{', '.join(clash)} (the name pins the profile; "
                         "pick --scenario <base> --workload <profile> "
                         "instead)")
        scenarios = [replace(s, workload=args.workload) for s in scenarios]
    all_ok = True
    for scenario in scenarios:
        run = asyncio.run(run_scenario(scenario, args.seed,
                                       timeout_s=args.timeout))
        print(json.dumps(run.describe(), sort_keys=True))
        all_ok = all_ok and run.ok
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
