"""The replicator binary: `python -m etl_tpu.replicator --config-dir DIR`.

Reference parity: crates/etl-replicator/src/main.rs:76 — config load →
tracing/metrics init → destination dispatch from config → pipeline start →
signal-driven graceful shutdown; plus the /metrics HTTP endpoint the
reference exposes through etl-telemetry.

Extra config keys consumed here (beyond PipelineConfig):
  destination: {type: memory|clickhouse|bigquery|lake|iceberg|snowflake, …}
  store:       {type: memory|sqlite|postgres, path: …, connection: …}
  metrics_port: 0 disables the endpoint
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

from aiohttp import web

from .config.load import (Environment, load_config_dict,
                          pipeline_config_from_dict)
from .destinations.registry import build_destination
from .models.errors import EtlError
from .postgres.client import PgReplicationClient
from .runtime.pipeline import Pipeline
from .store.memory import MemoryStore
from .store.sql import PostgresStore, SqliteStore
from .telemetry.metrics import registry
from .telemetry.tracing import init_tracing

logger = logging.getLogger("etl_tpu.replicator")


def build_observability_app(pipeline=None) -> web.Application:
    """The replicator pod's /metrics + /health + /health/detail routes.

    /health is a LIVE surface of the supervision health state machine
    (docs/supervision.md), not a static ok: 503 with "starting" before
    the pipeline has started, 200 with the state while healthy/degraded,
    503 with the fatal detail once the apply worker failed permanently.
    /health/detail adds per-component heartbeat ages, breaker states,
    and recent supervision events."""

    async def metrics(_request: web.Request) -> web.Response:
        return web.Response(text=registry.render_prometheus(),
                            content_type="text/plain")

    def _supervisor():
        return pipeline.supervisor if pipeline is not None else None

    def _shard_fields() -> dict:
        # sharded pods identify their slice on every health surface so a
        # fleet dashboard can tell WHICH shard is unhealthy
        if pipeline is None or pipeline.config.shard is None:
            return {}
        ident = pipeline.shard_identity
        return {"shard": ident.describe() if ident is not None else {
            "shard": pipeline.config.shard,
            "shard_count": pipeline.config.shard_count,
            "epoch": None}}

    async def health(_request: web.Request) -> web.Response:
        sup = _supervisor()
        if sup is None:
            # supervision disabled: liveness of the process is all we
            # can honestly attest
            return web.json_response({"status": "ok",
                                      "supervision": "disabled",
                                      **_shard_fields()})
        if not sup.started:
            return web.json_response(
                {"status": "starting", **_shard_fields()}, status=503)
        from .supervision import HealthState

        state = sup.health.state
        body = {"status": state.value, **_shard_fields()}
        if state is HealthState.FAULTED:
            body["fatal"] = sup.health.fatal
            return web.json_response(body, status=503)
        if state is HealthState.DEGRADED:
            body["reasons"] = sup.health.reasons
        return web.json_response(body)

    async def health_detail(_request: web.Request) -> web.Response:
        if pipeline is None:
            return web.json_response({"state": "unsupervised"})
        snap = pipeline.health_snapshot()
        status = 503 if snap.get("health", {}).get("state") == "faulted" \
            or not snap.get("started", True) else 200
        return web.json_response(snap, status=status)

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/health", health)
    app.router.add_get("/health/detail", health_detail)
    return app


async def serve_metrics(port: int, pipeline=None) -> web.AppRunner | None:
    if not port:
        return None
    runner = web.AppRunner(build_observability_app(pipeline))
    await runner.setup()
    await web.TCPSite(runner, "0.0.0.0", port).start()
    logger.info("metrics on :%d/metrics", port)
    return runner


def store_connection_from_doc(base, overrides_doc):
    """store.connection overrides merge ONTO the source connection
    (per-field); secrets/tls convert through the loader; unknown keys are
    typed CONFIG_INVALID errors."""
    if not overrides_doc:
        return base
    import dataclasses

    from .config.load import Secret, _build
    from .config.pipeline import PgConnectionConfig, TlsConfig
    from .models.errors import ErrorKind, EtlError

    overrides = dict(overrides_doc)
    known = {f.name for f in dataclasses.fields(PgConnectionConfig)}
    unknown = set(overrides) - known
    if unknown:
        raise EtlError(ErrorKind.CONFIG_INVALID,
                       f"store.connection: unknown keys {sorted(unknown)}")
    if overrides.get("password") is not None:
        overrides["password"] = Secret(overrides["password"])
    if "tls" in overrides:
        overrides["tls"] = _build(TlsConfig, overrides["tls"])
    merged = dataclasses.replace(base, **overrides)
    merged.validate()
    return merged


async def run_replicator(config_dir: str,
                         environment: Environment | None = None,
                         shard: int | None = None,
                         shard_count: int | None = None) -> None:
    doc = load_config_dict(config_dir, environment)
    # CLI shard identity wins over the config document: the orchestrator
    # writes per-shard config docs, but an operator can also pin a pod's
    # slice at the command line (docs/sharding.md runbook)
    if shard is not None:
        doc["shard"] = shard
    if shard_count is not None:
        doc["shard_count"] = shard_count
    dest_doc = doc.pop("destination", {"type": "memory"})
    store_doc = doc.pop("store", {"type": "memory"})
    maint_doc = doc.pop("maintenance", {})
    # validate BEFORE startup (config/load convention: unknown keys fail
    # typed at load time, not as a TypeError after slots exist)
    maint_policy = None
    if maint_doc:
        import dataclasses

        from .maintenance_coordination import MaintenancePolicy
        from .models.errors import ErrorKind

        known = {f.name for f in dataclasses.fields(MaintenancePolicy)}
        unknown = set(maint_doc) - known - {"coordination"}
        if unknown:
            raise EtlError(
                ErrorKind.CONFIG_INVALID,
                f"maintenance: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known | {'coordination'})})")
        maint_policy = MaintenancePolicy(
            **{k: v for k, v in maint_doc.items() if k != "coordination"})
        if maint_doc.get("coordination") and \
                dest_doc.get("type") != "lake":
            raise EtlError(
                ErrorKind.CONFIG_INVALID,
                "maintenance.coordination requires destination.type=lake "
                f"(got {dest_doc.get('type')!r}) — the coordination state "
                "lives in the lake catalog")
    metrics_port = doc.pop("metrics_port", 0)
    project_ref = doc.pop("project_ref", "")
    error_webhook = doc.pop("error_webhook_url", "")
    config = pipeline_config_from_dict(doc)

    env = environment or Environment.current()
    init_tracing(environment=env.value, project_ref=project_ref,
                 pipeline_id=config.pipeline_id)
    notifier = None
    if error_webhook:
        from .telemetry.notify import WebhookErrorNotifier

        notifier = WebhookErrorNotifier(error_webhook,
                                        pipeline_id=config.pipeline_id)
        notifier.install()
    logger.info("starting replicator pipeline=%s publication=%s engine=%s"
                "%s",
                config.pipeline_id, config.publication_name,
                config.batch.batch_engine.value,
                f" shard={config.shard}/{config.shard_count}"
                if config.shard is not None else "")

    store_type = store_doc.get("type", "memory")
    if store_type == "sqlite":
        store = SqliteStore(store_doc["path"], config.pipeline_id)
        await store.connect()
    elif store_type == "postgres":
        # durable state lives in a Postgres `etl` schema over the same
        # wire stack as replication (reference store/both/postgres.rs);
        # defaults to the SOURCE connection, overridable per-field
        store_conn = store_connection_from_doc(
            config.pg_connection, store_doc.get("connection"))
        store = PostgresStore(store_conn, config.pipeline_id)
        await store.connect()
    else:
        store = MemoryStore()
    destination = build_destination(dest_doc)

    pipeline = Pipeline(
        config=config, store=store, destination=destination,
        source_factory=lambda: PgReplicationClient(config.pg_connection))

    metrics_runner = await serve_metrics(metrics_port, pipeline)
    loop = asyncio.get_event_loop()
    # hold the shutdown-task handle: the loop keeps only a weak ref, so
    # a bare ensure_future in the handler could be GC'd mid-shutdown
    # (etl-lint: orphaned-task)
    signal_tasks: set[asyncio.Task] = set()

    def _request_shutdown() -> None:
        t = asyncio.ensure_future(pipeline.shutdown())
        signal_tasks.add(t)
        t.add_done_callback(signal_tasks.discard)

    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, _request_shutdown)

    maint_agent = None
    maint_store = None
    try:
        await pipeline.start()
        logger.info("pipeline started")
        if dest_doc.get("type") == "lake" and maint_doc.get("coordination"):
            # external-maintenance coordination (reference
            # etl-maintenance coordination.rs replicator role): sample
            # lake stats into operation requests, pause intake under the
            # controller's lease via the monitor's external pause
            from .maintenance_coordination import (
                CatalogMaintenanceStore, ReplicatorMaintenanceAgent)

            maint_store = CatalogMaintenanceStore(
                dest_doc["warehouse_path"], config.pipeline_id)
            mon = pipeline.memory_monitor
            loop_ = asyncio.get_event_loop()
            # call_soon_threadsafe: agent ticks run in a worker thread
            # (catalog lock waits must not stall WAL keepalives), and the
            # monitor's pause event belongs to this loop
            maint_agent = ReplicatorMaintenanceAgent(
                maint_store, policy=maint_policy,
                pause=lambda: loop_.call_soon_threadsafe(
                    mon.set_external_pause, True),
                resume=lambda: loop_.call_soon_threadsafe(
                    mon.set_external_pause, False))
            maint_agent.start()
            logger.info("maintenance coordination agent started")
        await pipeline.wait()
        logger.info("pipeline stopped cleanly")
    except BaseException as e:
        if not isinstance(e, asyncio.CancelledError):
            # log INSIDE the loop so the error webhook can still fire
            # (main() runs after asyncio.run() returns, where the hook
            # has no loop to post from)
            logger.error("replicator failed: %s", e)
        raise
    finally:
        if maint_agent is not None:
            await maint_agent.stop()
        if maint_store is not None:
            maint_store.close()
        if metrics_runner is not None:
            await metrics_runner.cleanup()
        close = getattr(store, "close", None)
        if close is not None:
            await close()
        if notifier is not None:
            await notifier.close()  # awaits in-flight notifications


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="etl_tpu.replicator",
        description="TPU-native Postgres logical-replication replicator")
    parser.add_argument("--config-dir", required=True,
                        help="directory with base.yaml / {env}.yaml")
    parser.add_argument("--environment", choices=[e.value for e in Environment],
                        default=None)
    parser.add_argument("--shard", type=int, default=None,
                        help="this pod's shard index in a K-way split of "
                             "the publication (etl_tpu/sharding); "
                             "overrides the config document's `shard` "
                             "key. The pod then replicates only its "
                             "ShardMap slice through `_s{shard}` slots "
                             "and fences its store writes by epoch.")
    parser.add_argument("--shard-count", dest="shard_count", type=int,
                        default=None,
                        help="total shard count K of the deployment; "
                             "overrides the config document's "
                             "`shard_count` key and must match the "
                             "store's authoritative assignment")
    args = parser.parse_args(argv)
    env = Environment(args.environment) if args.environment else None
    try:
        asyncio.run(run_replicator(args.config_dir, env,
                                   shard=args.shard,
                                   shard_count=args.shard_count))
        return 0
    except KeyboardInterrupt:
        return 0
    except EtlError:
        return 1  # already logged (and webhooked) inside the loop


if __name__ == "__main__":
    sys.exit(main())
