"""Device-mesh parallelism for the decode engine.

The reference's parallelism inventory (SURVEY §2.5) maps onto the TPU as:

  - inter-table / copy-partition parallelism → `dp` mesh axis: independent
    staged batches (one per table-sync copy partition or CDC flush) decode
    on disjoint device groups;
  - huge-batch scaling (the "sequence parallel" analogue — WAL bursts and
    CTID partitions of arbitrary size) → `sp` mesh axis: rows of one batch
    sharded across devices, with XLA collectives (psum/pmax over ICI) for
    the batch-level reductions the apply loop needs (decode-error counts,
    per-batch max LSN for durability accounting).

The decode itself is embarrassingly parallel over rows, so collectives ride
only the cheap reduction path — the design scales to multi-host DCN without
change (jax.sharding.Mesh spanning hosts).
"""

from __future__ import annotations

import threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh



def mesh_cache_key(mesh: "Mesh | None") -> tuple | None:
    """Canonical, hashable fingerprint of a mesh for program-cache keys
    (ops/engine._SHARED_FN_CACHE): axis names, shape, and the flat device
    ids. Two Mesh OBJECTS over the same devices/axes fingerprint equal (a
    recreated mesh must reuse the compiled program), while meshes over
    different device sets — or a mesh vs none — never collide even for
    identical (row_capacity, specs, nibble) signatures: the sharded
    program's output signature (packed words + per-shard fallback counts)
    differs from the single-device program's, so a collision would hand a
    caller the wrong result STRUCTURE, not just a misplaced shard."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


def decode_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh | None:
    """1D row-sharding mesh over all devices for the PRODUCTION decoder
    (DeviceDecoder(mesh=…)): decode is embarrassingly parallel over rows,
    so a single 'sp' axis covers it; None on single-device hosts."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < 2:
        return None
    return Mesh(np.asarray(devices), axis_names=("sp",))


_DEFAULT_MESH: "list[Mesh | None] | None" = None
# decoders are built on the event loop AND inside warm_host_programs'
# executor offload; the lock keeps the lazy init single-flight so both
# callers share ONE mesh object (program-cache keys fingerprint the
# mesh — two racing inits would double-compile every sharded program)
_DEFAULT_MESH_LOCK = threading.Lock()


def default_decode_mesh() -> Mesh | None:
    """Cached decode_mesh over jax.devices() — what DeviceDecoder uses when
    constructed with mesh='auto'. Thread-safe: see `_DEFAULT_MESH_LOCK`."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        with _DEFAULT_MESH_LOCK:
            if _DEFAULT_MESH is None:
                _DEFAULT_MESH = [decode_mesh()]
    return _DEFAULT_MESH[0]


def make_mesh(devices: Sequence[jax.Device] | None = None,
              dp: int | None = None) -> Mesh:
    """Build a 2D ('dp', 'sp') mesh over the given devices. `dp` defaults to
    the largest power-of-two split ≤ √n so both axes are populated."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        dp = 1
        while dp * 2 <= max(1, int(n**0.5)) and n % (dp * 2) == 0:
            dp *= 2
        if n % dp:
            dp = 1
    sp = n // dp
    arr = np.asarray(devices[: dp * sp]).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


