"""Device-mesh parallelism for the decode engine.

The reference's parallelism inventory (SURVEY §2.5) maps onto the TPU as:

  - inter-table / copy-partition parallelism → `dp` mesh axis: independent
    staged batches (one per table-sync copy partition or CDC flush) decode
    on disjoint device groups;
  - huge-batch scaling (the "sequence parallel" analogue — WAL bursts and
    CTID partitions of arbitrary size) → `sp` mesh axis: rows of one batch
    sharded across devices, with XLA collectives (psum/pmax over ICI) for
    the batch-level reductions the apply loop needs (decode-error counts,
    per-batch max LSN for durability accounting).

The decode itself is embarrassingly parallel over rows, so collectives ride
only the cheap reduction path — the design scales to multi-host DCN without
change (jax.sharding.Mesh spanning hosts).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.pgtypes import CellKind
from ..ops import parsers


def make_mesh(devices: Sequence[jax.Device] | None = None,
              dp: int | None = None) -> Mesh:
    """Build a 2D ('dp', 'sp') mesh over the given devices. `dp` defaults to
    the largest power-of-two split ≤ √n so both axes are populated."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        dp = 1
        while dp * 2 <= max(1, int(n**0.5)) and n % (dp * 2) == 0:
            dp *= 2
        if n % dp:
            dp = 1
    sp = n // dp
    arr = np.asarray(devices[: dp * sp]).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def _parse_columns(data, offsets, lengths, specs):
    """Shared per-shard decode body: offsets/lengths are [B, R, C] local
    shards; returns per-column component dict (parsers.parse_column order)
    + ok matrix [B, R, n_dense]."""
    B, R, C = offsets.shape
    out = {}
    oks = []
    for col_idx, kind, width in specs:
        off = offsets[:, :, col_idx].reshape(B * R)
        ln = lengths[:, :, col_idx].reshape(B * R)
        b = parsers.gather_fields(data, off, ln, width)
        comp, ok = parsers.parse_column(kind, b, ln)
        out[col_idx] = {k: v.reshape(B, R) for k, v in comp.items()}
        oks.append(ok.reshape(B, R))
    ok_mat = jnp.stack(oks, axis=-1) if oks else \
        jnp.ones((B, R, 0), dtype=bool)
    return out, ok_mat


def build_sharded_decode_step(mesh: Mesh,
                              specs: tuple[tuple[int, CellKind, int], ...]):
    """The multi-chip decode step: batches sharded over 'dp', rows over 'sp'.

    Inputs (global shapes):
      data      uint8[cap]      replicated byte buffer
      offsets   int32[B, R, C]  sharded P('dp', 'sp')
      lengths   int32[B, R, C]  sharded P('dp', 'sp')
      valid     bool[B, R, C]   sharded P('dp', 'sp')
      lsns      uint32[B, R]    per-row start-LSN low word, P('dp', 'sp')

    Outputs:
      components  per-column dicts, each [B, R] sharded P('dp', 'sp')
      n_bad       int32[B]   rows needing CPU fallback, psum over 'sp'
      max_lsn     uint32[B]  durability watermark per batch, pmax over 'sp'
    """

    specs = tuple(s[:3] for s in specs)  # accept engine 4-tuple specs too
    dense_idx = np.asarray([i for i, _, _ in specs], dtype=np.int32)

    def step(data, offsets, lengths, valid, lsns):
        comps, ok_mat = _parse_columns(data, offsets, lengths, specs)
        valid_dense = valid[:, :, dense_idx]  # align with ok_mat columns
        row_bad = (~ok_mat & valid_dense).any(axis=-1)  # [B, R] local
        n_bad = jax.lax.psum(row_bad.sum(axis=1, dtype=jnp.int32), "sp")
        max_lsn = jax.lax.pmax(lsns.max(axis=1), "sp")
        return comps, n_bad, max_lsn

    kwargs = dict(
        mesh=mesh,
        in_specs=(P(), P("dp", "sp", None), P("dp", "sp", None),
                  P("dp", "sp", None), P("dp", "sp")),
        out_specs=({i: {k: P("dp", "sp") for k in parsers.COLUMN_COMPONENTS[kind]}
                    for i, kind, _ in specs},
                   P("dp"), P("dp")))
    try:
        from jax import shard_map  # jax >= 0.7: replication-check kwarg
        sharded = shard_map(step, check_vma=False, **kwargs)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        sharded = shard_map(step, check_rep=False, **kwargs)
    return jax.jit(sharded)


def shard_staged_inputs(mesh: Mesh, data, offsets, lengths, valid, lsns):
    """Place host arrays onto the mesh with the step's shardings."""
    rep = NamedSharding(mesh, P())
    rc = NamedSharding(mesh, P("dp", "sp", None))
    rl = NamedSharding(mesh, P("dp", "sp"))
    return (jax.device_put(data, rep), jax.device_put(offsets, rc),
            jax.device_put(lengths, rc), jax.device_put(valid, rc),
            jax.device_put(lsns, rl))
