"""External-maintenance coordination between replicator and controller.

Reference parity: crates/etl-maintenance/src/coordination.rs (the
backend-neutral ExternalMaintenanceState document and its policies) with
the Postgres/Kubernetes store impls (coordination/{postgres,kubernetes}.rs)
collapsed onto the lake catalog — the one shared, crash-safe medium both
sides already reach (WAL-mode sqlite at `<warehouse>/catalog.db`).

Protocol (coordination.rs roles):
  - the REPLICATOR samples destination state (pending inlined bytes, CDC
    file counts) and posts an *operation request* when policy thresholds
    are crossed, subject to a request cooldown; it also watches for a
    controller-owned *pause lease* and pauses its lake writes while one
    is active, reporting its paused status back;
  - the CONTROLLER (maintenance binary) polls the state, turns a pending
    request into an *active run*, takes the pause lease, waits for the
    replicator to report paused (bounded), executes the selected
    operations, records per-operation history + last_completed_at, and
    clears the lease;
  - the pause lease carries `max_pause_s` (reference
    DEFAULT_MAX_PAUSE_SECONDS): if the controller dies mid-run, the
    replicator resumes on lease expiry instead of staying paused forever.
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
import uuid
from dataclasses import asdict, dataclass, field
from pathlib import Path

# reference coordination.rs defaults
DEFAULT_POLL_SECONDS = 5.0
DEFAULT_INLINE_FLUSH_MIN_INLINED_BYTES = 10_000_000
DEFAULT_MERGE_MIN_CDC_FILES = 40
DEFAULT_REQUEST_COOLDOWN_SECONDS = 300.0
DEFAULT_MAX_PAUSE_SECONDS = 2700.0


@dataclass(frozen=True)
class MaintenancePolicy:
    """Thresholds + cadence (coordination.rs policy constants)."""

    poll_seconds: float = DEFAULT_POLL_SECONDS
    inline_flush_min_inlined_bytes: int = \
        DEFAULT_INLINE_FLUSH_MIN_INLINED_BYTES
    merge_min_cdc_files: int = DEFAULT_MERGE_MIN_CDC_FILES
    request_cooldown_seconds: float = DEFAULT_REQUEST_COOLDOWN_SECONDS
    max_pause_seconds: float = DEFAULT_MAX_PAUSE_SECONDS
    # operation enablement (ExternalMaintenanceOperationPolicy)
    inline_flush_enabled: bool = True
    merge_adjacent_files_enabled: bool = True
    cleanup_old_files_enabled: bool = False


@dataclass
class Operations:
    """Requested/selected operation flags
    (ExternalMaintenanceOperations)."""

    inline_flush: bool = False
    merge_adjacent_files: bool = False
    cleanup_old_files: bool = False

    @property
    def is_empty(self) -> bool:
        return not (self.inline_flush or self.merge_adjacent_files
                    or self.cleanup_old_files)


@dataclass
class MaintenanceState:
    """The shared coordination document (ExternalMaintenanceState)."""

    exists: bool = False
    # controller-owned
    active_run_id: str | None = None
    active_run_started_at: float | None = None
    active_operations: Operations = field(default_factory=Operations)
    pause_run_id: str | None = None
    pause_requested_at: float | None = None
    pause_max_pause_s: float = DEFAULT_MAX_PAUSE_SECONDS
    # replicator-owned
    request_operations: Operations = field(default_factory=Operations)
    request_at: float | None = None
    replicator_paused: bool = False
    replicator_observed_run_id: str | None = None
    replicator_reported_at: float | None = None
    # history
    last_successful: dict = field(default_factory=dict)  # op -> ts
    last_completed_at: float | None = None

    def pause_active(self, now: float | None = None) -> bool:
        """Lease check: a pause request is live until max_pause expires —
        the replicator self-resumes past that (controller crash)."""
        if self.pause_run_id is None or self.pause_requested_at is None:
            return False
        now = time.time() if now is None else now
        return now - self.pause_requested_at < self.pause_max_pause_s

    def to_json(self) -> str:
        doc = asdict(self)
        return json.dumps(doc, separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: str) -> "MaintenanceState":
        doc = json.loads(raw)
        doc["active_operations"] = Operations(**doc["active_operations"])
        doc["request_operations"] = Operations(**doc["request_operations"])
        return cls(**doc)


class CatalogMaintenanceStore:
    """Coordination state in the lake catalog (the sqlite analogue of
    coordination/postgres.rs `ensure_schema` + state row per pipeline)."""

    def __init__(self, warehouse_path: str, pipeline_id: int):
        self.path = Path(warehouse_path) / "catalog.db"
        self.pipeline_id = pipeline_id
        self._db: sqlite3.Connection | None = None

    def _conn(self) -> sqlite3.Connection:
        if self._db is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            # check_same_thread=False: the replicator agent runs its ticks
            # via asyncio.to_thread so catalog lock waits never stall the
            # event loop (WAL keepalives must keep flowing)
            self._db = sqlite3.connect(self.path, timeout=10.0,
                                       check_same_thread=False)
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute("PRAGMA busy_timeout=10000")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS lake_external_maintenance ("
                "pipeline_id INTEGER PRIMARY KEY, state TEXT NOT NULL)")
            self._db.commit()
        return self._db

    def load(self) -> MaintenanceState:
        row = self._conn().execute(
            "SELECT state FROM lake_external_maintenance WHERE "
            "pipeline_id = ?", (self.pipeline_id,)).fetchone()
        if row is None:
            return MaintenanceState()
        return MaintenanceState.from_json(row[0])

    def save(self, state: MaintenanceState) -> None:
        state.exists = True
        db = self._conn()
        db.execute(
            "INSERT INTO lake_external_maintenance (pipeline_id, state) "
            "VALUES (?, ?) ON CONFLICT (pipeline_id) DO UPDATE SET "
            "state = excluded.state", (self.pipeline_id, state.to_json()))
        db.commit()

    def mutate(self, fn) -> MaintenanceState:
        """Read-modify-write under one catalog transaction (the CAS-like
        update both sides use; sqlite's write lock serializes them)."""
        db = self._conn()
        db.execute("BEGIN IMMEDIATE")
        try:
            row = db.execute(
                "SELECT state FROM lake_external_maintenance WHERE "
                "pipeline_id = ?", (self.pipeline_id,)).fetchone()
            state = MaintenanceState.from_json(row[0]) if row \
                else MaintenanceState()
            fn(state)
            state.exists = True
            db.execute(
                "INSERT INTO lake_external_maintenance (pipeline_id, "
                "state) VALUES (?, ?) ON CONFLICT (pipeline_id) DO UPDATE "
                "SET state = excluded.state",
                (self.pipeline_id, state.to_json()))
            db.commit()
        except BaseException:
            try:
                db.execute("ROLLBACK")
            except sqlite3.OperationalError:
                pass
            raise
        return state

    def delete(self) -> None:
        db = self._conn()
        db.execute("DELETE FROM lake_external_maintenance WHERE "
                   "pipeline_id = ?", (self.pipeline_id,))
        db.commit()

    # -- destination-state sampling (agent side) -------------------------------
    # The agent samples through THIS connection, not the pipeline's
    # LakeDestination: its ticks run on a worker thread, and the
    # destination's sqlite connection belongs to the event-loop thread.

    def sample_table_ids(self) -> list[int]:
        try:
            return [r[0] for r in self._conn().execute(
                "SELECT table_id FROM lake_tables").fetchall()]
        except sqlite3.OperationalError:
            return []  # lake not initialized yet

    def _current_generation(self, table_id: int) -> int | None:
        from .destinations.lake import TABLE_GENERATION_SQL

        row = self._conn().execute(TABLE_GENERATION_SQL,
                                   (table_id,)).fetchone()
        return None if row is None else row[0]

    def sample_cdc_file_count(self, table_id: int) -> int:
        from .destinations.lake import CDC_FILE_COUNT_SQL

        gen = self._current_generation(table_id)
        if gen is None:
            return 0
        return self._conn().execute(CDC_FILE_COUNT_SQL,
                                    (table_id, gen)).fetchone()[0]

    def sample_pending_inline_bytes(self, table_id: int) -> int:
        from .destinations.lake import PENDING_INLINE_BYTES_SQL

        gen = self._current_generation(table_id)
        if gen is None:
            return 0
        (n,) = self._conn().execute(PENDING_INLINE_BYTES_SQL,
                                    (table_id, gen)).fetchone()
        return int(n)

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None


class ReplicatorMaintenanceAgent:
    """The replicator side: samples lake stats into operation requests
    and honors the controller's pause lease (coordination.rs replicator
    role). `pause`/`resume` callbacks wire into the pipeline's intake
    pause (MemoryMonitor.set_external_pause).

    The background loop runs ticks via asyncio.to_thread, so the
    callbacks MAY FIRE FROM A WORKER THREAD — wire them through
    `loop.call_soon_threadsafe` when they touch event-loop state (the
    replicator does)."""

    def __init__(self, store: CatalogMaintenanceStore,
                 policy: MaintenancePolicy = MaintenancePolicy(),
                 pause=None, resume=None):
        self.store = store
        self.policy = policy
        self._pause_cb = pause or (lambda: None)
        self._resume_cb = resume or (lambda: None)
        self.paused = False
        self._task: asyncio.Task | None = None

    def sample_operations(self) -> Operations:
        """Destination-state sampling → requested operation flags. Reads
        ride the store's own (thread-safe) catalog connection — the
        pipeline's LakeDestination connection belongs to the loop
        thread."""
        ops = Operations()
        p = self.policy
        for tid in self.store.sample_table_ids():
            if (p.inline_flush_enabled and
                    self.store.sample_pending_inline_bytes(tid)
                    >= p.inline_flush_min_inlined_bytes):
                ops.inline_flush = True
            if (p.merge_adjacent_files_enabled and
                    self.store.sample_cdc_file_count(tid)
                    >= p.merge_min_cdc_files):
                ops.merge_adjacent_files = True
        return ops

    def tick(self, now: float | None = None) -> MaintenanceState:
        """One coordination step; returns the state after the step."""
        now = time.time() if now is None else now
        ops = self.sample_operations()

        def step(state: MaintenanceState) -> None:
            # publish the CURRENT sampled need, subject to the cooldown —
            # including clearing a stale request whose need has since
            # vanished (e.g. the lake's own flush threshold fired first),
            # so the controller never pauses the pipeline for nothing
            cooled = (state.request_at is None or
                      now - state.request_at
                      >= self.policy.request_cooldown_seconds)
            if cooled and state.request_operations != ops:
                state.request_operations = ops
                state.request_at = now
            # honor (or release) the pause lease
            want_paused = state.pause_active(now)
            if want_paused and not self.paused:
                self._pause_cb()
                self.paused = True
            elif not want_paused and self.paused:
                self._resume_cb()
                self.paused = False
            state.replicator_paused = self.paused
            state.replicator_observed_run_id = state.pause_run_id
            state.replicator_reported_at = now

        return self.store.mutate(step)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            try:
                # to_thread: a tick can wait up to busy_timeout on the
                # catalog write lock (e.g. mid-compaction); that wait must
                # never stall the event loop carrying WAL keepalives
                await asyncio.to_thread(self.tick)
            except Exception:  # coordination must never kill replication
                import logging

                logging.getLogger("etl_tpu.maintenance").exception(
                    "maintenance coordination tick failed")
            await asyncio.sleep(self.policy.poll_seconds)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self.paused:
            self._resume_cb()
            self.paused = False

    def clear_status(self) -> None:
        def step(state: MaintenanceState) -> None:
            state.replicator_paused = False
            state.replicator_observed_run_id = None
            state.replicator_reported_at = None

        self.store.mutate(step)


class MaintenanceController:
    """The controller side (the maintenance binary's coordination role):
    request → active run → pause lease → execute → history."""

    def __init__(self, store: CatalogMaintenanceStore, lake,
                 policy: MaintenancePolicy = MaintenancePolicy()):
        self.store = store
        self.lake = lake
        self.policy = policy

    def _conditions_still_hold(self, op: str) -> bool:
        """Re-sample the destination before acting: a stale request whose
        need has since vanished (e.g. the lake auto-flushed) must not
        pause the pipeline for nothing."""
        p = self.policy
        if op == "inline_flush":
            return any(self.lake.pending_inline_bytes(t) > 0
                       for t in self.lake.table_ids())
        if op == "merge_adjacent_files":
            return any(self.lake.current_cdc_file_count(t)
                       >= p.merge_min_cdc_files
                       for t in self.lake.table_ids())
        return True

    def _select_operations(self, state: MaintenanceState,
                           now: float) -> Operations:
        """Requested + re-validated + per-operation success cooldown
        (reference DEFAULT_REQUEST_COOLDOWN_SECONDS applied to history).
        cleanup_old_files is OPERATOR-driven (policy enablement, the
        --vacuum flag) rather than replicator-sampled."""
        req = state.request_operations
        sel = Operations()
        cd = self.policy.request_cooldown_seconds

        def cooled(op: str) -> bool:
            last = state.last_successful.get(op)
            return last is None or now - last >= cd

        sel.inline_flush = (req.inline_flush and cooled("inline_flush")
                            and self._conditions_still_hold("inline_flush"))
        sel.merge_adjacent_files = (
            req.merge_adjacent_files and cooled("merge_adjacent_files")
            and self._conditions_still_hold("merge_adjacent_files"))
        sel.cleanup_old_files = (
            (req.cleanup_old_files
             or self.policy.cleanup_old_files_enabled)
            and cooled("cleanup_old_files"))
        return sel

    async def run_once(self, *, wait_for_pause_s: float = 30.0,
                       now: float | None = None) -> dict:
        """One controller pass. Returns a report dict (the binary prints
        it as JSON)."""
        now = time.time() if now is None else now
        run_id = uuid.uuid4().hex[:12]
        selected = Operations()
        outcome: dict = {}

        def take(state: MaintenanceState) -> None:
            # check-and-take inside ONE catalog transaction: two
            # overlapping cron-launched controllers must not both take the
            # lease and clobber each other's run
            if state.active_run_id is not None and state.pause_active(now):
                outcome["skipped"] = "run already active"
                outcome["run_id"] = state.active_run_id
                return
            sel = self._select_operations(state, now)
            if sel.is_empty:
                outcome["skipped"] = ("no operations requested or all "
                                      "cooling down")
                # consume ONLY flags whose conditions no longer hold (a
                # merely-cooling-down request stays pending)
                req = state.request_operations
                state.request_operations = Operations(
                    inline_flush=req.inline_flush
                    and self._conditions_still_hold("inline_flush"),
                    merge_adjacent_files=req.merge_adjacent_files
                    and self._conditions_still_hold(
                        "merge_adjacent_files"),
                    cleanup_old_files=req.cleanup_old_files)
                return
            selected.inline_flush = sel.inline_flush
            selected.merge_adjacent_files = sel.merge_adjacent_files
            selected.cleanup_old_files = sel.cleanup_old_files
            state.active_run_id = run_id
            state.active_run_started_at = now
            state.active_operations = sel
            state.pause_run_id = run_id
            state.pause_requested_at = now
            state.pause_max_pause_s = self.policy.max_pause_seconds

        self.store.mutate(take)
        if "skipped" in outcome:
            return outcome
        # wait (bounded) for the replicator to observe the lease and
        # report paused; proceeding without it is still SAFE — the lake
        # catalog's per-table maintenance flag serializes writers — but
        # pausing first avoids compaction/writer catalog contention
        deadline = time.monotonic() + wait_for_pause_s
        replicator_paused = False
        while time.monotonic() < deadline:
            st = self.store.load()
            if st.replicator_paused and \
                    st.replicator_observed_run_id == run_id:
                replicator_paused = True
                break
            await asyncio.sleep(min(0.05, self.policy.poll_seconds))
        report: dict = {"run_id": run_id,
                        "replicator_paused": replicator_paused,
                        "operations": {}}
        succeeded: list[str] = []
        try:
            if selected.inline_flush:
                n = 0
                for tid in self.lake.table_ids():
                    n += await self.lake.flush_inlined(tid)
                report["operations"]["inline_flush"] = n
                succeeded.append("inline_flush")
            if selected.merge_adjacent_files:
                n = 0
                for tid in self.lake.table_ids():
                    n += await self.lake.compact(tid)
                report["operations"]["merge_adjacent_files"] = n
                succeeded.append("merge_adjacent_files")
            if selected.cleanup_old_files:
                n = 0
                for tid in self.lake.table_ids():
                    n += await self.lake.vacuum(tid)
                report["operations"]["cleanup_old_files"] = n
                succeeded.append("cleanup_old_files")
        finally:
            done_at = time.time()

            def finish(state: MaintenanceState) -> None:
                for op in succeeded:
                    state.last_successful[op] = done_at
                state.last_completed_at = done_at
                if state.active_run_id == run_id:
                    # only the lease owner clears it — an expired lease
                    # may have been re-taken by another controller whose
                    # live run must not be resumed from under it
                    state.active_run_id = None
                    state.active_run_started_at = None
                    state.active_operations = Operations()
                    state.pause_run_id = None
                    state.pause_requested_at = None
                # a satisfied request is consumed; a partial failure
                # leaves the remaining flags for the next pass
                state.request_operations = Operations(
                    inline_flush=state.request_operations.inline_flush
                    and "inline_flush" not in succeeded,
                    merge_adjacent_files=
                        state.request_operations.merge_adjacent_files
                        and "merge_adjacent_files" not in succeeded,
                    cleanup_old_files=
                        state.request_operations.cleanup_old_files
                        and "cleanup_old_files" not in succeeded)

            self.store.mutate(finish)
        return report
