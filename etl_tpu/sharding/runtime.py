"""Shard-scoped runtime: one pod's view of a shared pipeline store.

A sharded deployment runs K replicator pods against ONE publication and
ONE shared state store. Each pod wraps the store in `ShardScopedStore`,
which makes the shard boundary structural instead of advisory:

  reads   — `get_table_states()` / `owned_table_states()` return only
            the tables this shard's ShardMap slice owns, so the
            table-sync pool spawns workers for owned tables only and
            the pipeline's init/purge sweep can never touch a sibling
            shard's rows;
  writes  — table-state and destination-metadata writes to a table the
            map assigns elsewhere raise `SHARD_NOT_OWNED`; any write
            after the coordinator bumped the authoritative epoch raises
            `SHARD_EPOCH_STALE` (both MANUAL, not retryable — a stale
            pod must be rolled with the new topology, not retried);
  schemas — schema-store writes pass through UNguarded: the apply loop
            stores DDL schema versions for every table it sees on the
            wire (owned or not) so a later rebalance hands the new owner
            a warm schema history.

Progress keys pass through untouched: slot names already carry the
`_s{shard}` suffix (postgres/slots.py), so shards cannot collide.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..analysis.annotations import shard_scoped
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState
from ..store.base import (DestinationTableMetadata, PipelineStore,
                          ProgressKey)
from .shardmap import ShardAssignment, ShardMap


@dataclass(frozen=True)
class ShardIdentity:
    """Which slice of the publication THIS pod owns, at which epoch."""

    pipeline_id: int
    shard: int
    shard_count: int
    epoch: int

    def shard_map(self) -> ShardMap:
        return ShardMap(self.shard_count, self.epoch)

    def describe(self) -> dict:
        return {"shard": self.shard, "shard_count": self.shard_count,
                "epoch": self.epoch}


async def resolve_shard_scope(store: PipelineStore,
                              config) -> "ShardScopedStore":
    """Adopt (or bootstrap) the authoritative shard assignment and wrap
    `store` in this pod's shard view.

    The pod's configured shard_count must MATCH the store's record: a
    pod rolled out with a stale K would otherwise compute a different
    ShardMap and silently fight its siblings over table ownership."""
    assignment = await store.get_shard_assignment()
    if assignment is None:
        assignment = ShardAssignment(epoch=0,
                                     shard_count=config.shard_count)
        await store.update_shard_assignment(assignment)
    if assignment.shard_count != config.shard_count:
        raise EtlError(
            ErrorKind.SHARD_EPOCH_STALE,
            f"pod configured for shard_count={config.shard_count} but the "
            f"store's authoritative assignment (epoch {assignment.epoch}) "
            f"says shard_count={assignment.shard_count} — roll the pod "
            f"with the current topology")
    if not 0 <= config.shard < assignment.shard_count:
        raise EtlError(
            ErrorKind.CONFIG_INVALID,
            f"shard index {config.shard} out of range for "
            f"shard_count={assignment.shard_count}")
    identity = ShardIdentity(
        pipeline_id=config.pipeline_id, shard=config.shard,
        shard_count=assignment.shard_count, epoch=assignment.epoch)
    return ShardScopedStore(store, identity)


class ShardScopedStore(PipelineStore):
    """One shard's filtered, write-fenced view of a shared store."""

    def __init__(self, inner: PipelineStore, identity: ShardIdentity):
        self._inner = inner
        self.identity = identity
        self._map = identity.shard_map()

    # -- ownership fence -----------------------------------------------------

    def owns(self, table_id: TableId) -> bool:
        return self._map.owns(table_id, self.identity.shard)

    async def _check_write(self, table_id: TableId) -> None:
        from ..telemetry.metrics import (ETL_SHARD_WRITE_REFUSALS_TOTAL,
                                         registry)

        assignment = await self._inner.get_shard_assignment()
        if assignment is not None and assignment.epoch != self.identity.epoch:
            registry.counter_inc(ETL_SHARD_WRITE_REFUSALS_TOTAL,
                                 labels={"reason": "epoch_stale"})
            raise EtlError(
                ErrorKind.SHARD_EPOCH_STALE,
                f"shard {self.identity.shard} holds epoch "
                f"{self.identity.epoch} but the store's authoritative "
                f"epoch is {assignment.epoch}; refusing the write to "
                f"table {table_id}")
        if not self.owns(table_id):
            registry.counter_inc(ETL_SHARD_WRITE_REFUSALS_TOTAL,
                                 labels={"reason": "not_owned"})
            raise EtlError(
                ErrorKind.SHARD_NOT_OWNED,
                f"table {table_id} belongs to shard "
                f"{self._map.shard_of(table_id)}, not shard "
                f"{self.identity.shard} (epoch {self.identity.epoch})")

    # -- StateStore ----------------------------------------------------------

    @shard_scoped
    async def owned_table_states(self) -> dict[TableId, TableState]:
        """THE sanctioned filtered read: the shared store's full list
        narrowed to this shard's slice."""
        states = await self._inner.get_table_states()  # etl-lint: ignore[cross-shard-table-access] — this IS the shard filter the rule points everyone at
        return {tid: st for tid, st in states.items() if self.owns(tid)}

    async def get_table_states(self) -> dict[TableId, TableState]:
        # the PipelineStore contract spelling: runtime internals (the
        # table-sync pool, the init sweep) read through the same filter
        return await self.owned_table_states()

    async def get_table_state(self, table_id: TableId) -> TableState | None:
        if not self.owns(table_id):
            return None
        return await self._inner.get_table_state(table_id)

    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None:
        await self._check_write(table_id)
        await self._inner.update_table_state(table_id, state)

    async def delete_table_state(self, table_id: TableId) -> None:
        await self._check_write(table_id)
        await self._inner.delete_table_state(table_id)

    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None:
        return await self._inner.get_durable_progress(key)

    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        return await self._inner.update_durable_progress(key, lsn)

    async def delete_durable_progress(self, key: ProgressKey) -> None:
        await self._inner.delete_durable_progress(key)

    async def get_destination_metadata(
            self, table_id: TableId) -> DestinationTableMetadata | None:
        return await self._inner.get_destination_metadata(table_id)

    async def update_destination_metadata(
            self, meta: DestinationTableMetadata) -> None:
        await self._check_write(meta.table_id)
        await self._inner.update_destination_metadata(meta)

    async def delete_destination_metadata(self, table_id: TableId) -> None:
        await self._check_write(table_id)
        await self._inner.delete_destination_metadata(table_id)

    async def get_shard_assignment(self) -> ShardAssignment | None:
        return await self._inner.get_shard_assignment()

    async def update_shard_assignment(self,
                                      assignment: ShardAssignment) -> None:
        # pods never move the assignment — only the coordinator does,
        # against the RAW store
        raise EtlError(
            ErrorKind.SHARD_NOT_OWNED,
            "shard-scoped runtimes cannot rewrite the shard assignment; "
            "drive rebalances through ShardCoordinator")

    # -- dead-letter / quarantine (docs/dead-letter.md) -----------------------
    # Reads pass through (the CLI and invariant checkers read the whole
    # pipeline's DLQ); WRITES are shard-fenced exactly like table-state
    # writes — a pod may only dead-letter or quarantine tables its
    # ShardMap slice owns, and never after the coordinator bumped the
    # epoch (a stale pod parking a freshly-rehomed table would fight the
    # new owner's delivery).

    async def append_dead_letters(self, entries) -> "list[int]":
        for e in entries:
            await self._check_write(e.table_id)
        return await self._inner.append_dead_letters(entries)

    async def list_dead_letters(self, table_id=None, status="dead"):
        return await self._inner.list_dead_letters(table_id, status)

    async def get_dead_letter(self, entry_id: int):
        return await self._inner.get_dead_letter(entry_id)

    async def set_dead_letter_status(self, entry_id: int,
                                     status: str) -> None:
        await self._inner.set_dead_letter_status(entry_id, status)

    async def get_quarantined_tables(self):
        return await self._inner.get_quarantined_tables()

    async def set_table_quarantine(self, table_id, record) -> None:
        await self._check_write(table_id)
        await self._inner.set_table_quarantine(table_id, record)

    async def get_autoscale_journal(self) -> "dict | None":
        return await self._inner.get_autoscale_journal()

    async def update_autoscale_journal(self, journal: dict) -> None:
        # pods never write scale decisions — only the (pod-external)
        # AutoscaleController does, against the RAW store
        raise EtlError(
            ErrorKind.SHARD_NOT_OWNED,
            "shard-scoped runtimes cannot rewrite the autoscale journal; "
            "drive scale decisions through AutoscaleController")

    # -- fleet spec / actuation journals (docs/fleet.md) ----------------------
    # Reads pass through (a pod may inspect the fleet's desired state,
    # e.g. to report its tenancy profile on /health/detail); WRITES are
    # control-plane-only — only the fleet coordinator, against the RAW
    # store, ever moves the spec or a journal.

    async def get_fleet_spec(self) -> "dict | None":
        return await self._inner.get_fleet_spec()

    async def update_fleet_spec(self, spec: dict) -> None:
        raise EtlError(
            ErrorKind.SHARD_NOT_OWNED,
            "shard-scoped runtimes cannot rewrite the fleet spec; "
            "submit desired state through the fleet API")

    async def get_fleet_journal(self, pipeline_id: int) -> "dict | None":
        return await self._inner.get_fleet_journal(pipeline_id)

    async def get_fleet_journals(self) -> "dict[int, dict]":
        return await self._inner.get_fleet_journals()

    async def update_fleet_journal(self, pipeline_id: int,
                                   journal: dict) -> None:
        raise EtlError(
            ErrorKind.SHARD_NOT_OWNED,
            "shard-scoped runtimes cannot rewrite a fleet actuation "
            "journal; drive convergence through FleetReconciler")

    # -- SchemaStore (shared, unguarded — see module docstring) ---------------

    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None:
        await self._inner.store_table_schema(schema, snapshot_id)

    async def get_table_schema(
            self, table_id: TableId,
            at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        return await self._inner.get_table_schema(table_id, at_snapshot)

    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]:
        return await self._inner.get_schema_versions(table_id)

    async def get_table_ids_with_schemas(self) -> list[TableId]:
        # the schema-cleanup sweep iterates this: scope it to owned
        # tables so K pods don't prune each other's versions concurrently
        all_ids = await self._inner.get_table_ids_with_schemas()
        return [tid for tid in all_ids if self.owns(tid)]

    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        return await self._inner.prune_schema_versions(table_id, older_than)

    async def delete_table_schemas(self, table_id: TableId) -> None:
        await self._inner.delete_table_schemas(table_id)
