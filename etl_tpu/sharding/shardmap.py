"""Table→shard assignment: rendezvous hashing, versioned by epoch.

One publication's tables are split across K replicator pods by highest-
random-weight (HRW / rendezvous) hashing: each (table, shard) pair gets
a stable 64-bit weight from blake2b, and a table lives on the shard with
the highest weight. Properties this buys (property-tested in
tests/test_sharding.py):

  determinism      — the map is a pure function of (table_id, shard_count):
                     identical across processes, hosts, and Python hash
                     seeds (blake2b, never the salted builtin hash());
  minimal movement — growing K→K+1 moves only the tables whose new
                     shard's weight wins (≈ 1/(K+1) of them), and every
                     moved table moves TO the new shard — tables that
                     stay put keep their exact shard index, so a
                     rebalance never reshuffles unmoved tables;
  shrink symmetry  — removing the top shard (K→K-1) re-homes exactly
                     that shard's tables onto the survivors.

`ShardAssignment` is the persisted control-plane record (the StateStore
shard-assignment surface, store/base.py): the authoritative (epoch,
shard_count) every pod must agree with, plus the in-flight rebalance
bookkeeping (fence LSN, moved tables) while a two-phase epoch bump is
underway (sharding/coordinator.py).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..models.errors import ErrorKind, EtlError
from ..models.schema import TableId

#: domain-separation salt for the HRW weights; changing it is a full
#: reshuffle of every deployed map — never do that
_HRW_SALT = "etl"

#: assignment lifecycle (coordinator.py two-phase protocol)
STATUS_STEADY = "steady"
STATUS_REBALANCING = "rebalancing"


def _weight(table_id: TableId, shard: int) -> int:
    digest = hashlib.blake2b(
        f"{_HRW_SALT}:{table_id}:{shard}".encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big")


@dataclass(frozen=True)
class ShardMap:
    """Pure assignment function over `shard_count` shards at `epoch`.

    The epoch does NOT feed the hash — the same (tables, K) always
    produces the identical map; epochs version the *authoritative*
    assignment so a pod holding a stale map can be refused (the
    ShardScopedStore write fence, sharding/runtime.py)."""

    shard_count: int
    epoch: int = 0

    def __post_init__(self) -> None:
        if self.shard_count < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"shard_count must be >= 1, got {self.shard_count}")
        if self.epoch < 0:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"epoch must be >= 0, got {self.epoch}")

    def shard_of(self, table_id: TableId) -> int:
        """HRW winner; ties (a 2^-64 event) break toward the lower shard
        index so the map stays total and deterministic."""
        best_shard = 0
        best_weight = -1
        for shard in range(self.shard_count):
            w = _weight(table_id, shard)
            if w > best_weight:
                best_weight = w
                best_shard = shard
        return best_shard

    def owns(self, table_id: TableId, shard: int) -> bool:
        return self.shard_of(table_id) == shard

    def tables_for_shard(self, table_ids, shard: int) -> "list[TableId]":
        return [tid for tid in table_ids if self.shard_of(tid) == shard]

    def partition(self, table_ids) -> "dict[int, list[TableId]]":
        """{shard: owned tables} over every shard (empty lists included —
        an operator looking at tables-per-shard must see empty shards)."""
        out: dict[int, list[TableId]] = {s: [] for s in range(self.shard_count)}
        for tid in table_ids:
            out[self.shard_of(tid)].append(tid)
        return out

    def grown(self) -> "ShardMap":
        return ShardMap(self.shard_count + 1, self.epoch + 1)

    def shrunk(self) -> "ShardMap":
        if self.shard_count == 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "cannot shrink below one shard")
        return ShardMap(self.shard_count - 1, self.epoch + 1)


def moved_tables(old: ShardMap, new: ShardMap,
                 table_ids) -> "dict[TableId, tuple[int, int]]":
    """{table: (old shard, new shard)} for every table whose owner
    changes between the two maps — the rebalance quiesce set."""
    out: dict[TableId, tuple[int, int]] = {}
    for tid in table_ids:
        a, b = old.shard_of(tid), new.shard_of(tid)
        if a != b:
            out[tid] = (a, b)
    return out


@dataclass(frozen=True)
class ShardAssignment:
    """The persisted authoritative assignment (StateStore surface).

    steady:       every pod with (epoch, shard_count) matching this
                  record owns exactly its ShardMap slice.
    rebalancing:  a two-phase epoch bump is in flight: `fence_lsn` is the
                  handoff point (everything ≤ fence must be durable at
                  the OLD owner before the flip), `moved` the tables
                  changing owner, `next_shard_count` the K the flip will
                  install. Pods keep running their current epoch until
                  the coordinator flips.
    """

    epoch: int
    shard_count: int
    status: str = STATUS_STEADY
    fence_lsn: int = 0
    next_shard_count: int = 0  # 0 = no rebalance in flight
    # ((table_id, old_shard, new_shard), ...) — tuple for hashability
    moved: tuple = field(default=())

    def shard_map(self) -> ShardMap:
        return ShardMap(self.shard_count, self.epoch)

    @property
    def rebalancing(self) -> bool:
        return self.status == STATUS_REBALANCING

    def to_json(self) -> dict:
        return {
            "epoch": self.epoch,
            "shard_count": self.shard_count,
            "status": self.status,
            "fence_lsn": self.fence_lsn,
            "next_shard_count": self.next_shard_count,
            "moved": [list(m) for m in self.moved],
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ShardAssignment":
        return cls(
            epoch=int(doc["epoch"]),
            shard_count=int(doc["shard_count"]),
            status=str(doc.get("status", STATUS_STEADY)),
            fence_lsn=int(doc.get("fence_lsn", 0)),
            next_shard_count=int(doc.get("next_shard_count", 0)),
            moved=tuple(tuple(m) for m in doc.get("moved", [])),
        )
