"""Two-phase shard rebalancing: quiesce at a fence LSN, flip the epoch.

`ShardCoordinator` is the control-plane half of horizontal scale-out: it
owns the persisted `ShardAssignment` and drives add/remove-shard
topology changes so that NO committed row is lost and duplicates stay
bounded — by construction, not by luck:

  add shard (K → K+1):
    1. create the NEW shard's apply slot FIRST; its consistent point is
       the fence LSN. From this instant the source retains WAL ≥ fence
       for the new pod, no matter how long the rollout takes.
    2. persist `status=rebalancing` (fence, moved set, target K+1) at
       the CURRENT epoch — pods keep applying their current slices.
    3. wait until every shard that is LOSING tables has durable progress
       ≥ fence on its apply slot: everything committed before the fence
       is durably applied by its old owner.
    4. flip: persist (epoch+1, K+1, steady). From here stale-epoch pods
       are refused by the store fence (sharding/runtime.py) and the
       orchestrator rolls the fleet onto the new topology; the new owner
       resumes from max(durable, slot confirmed_flush) = fence.

    Zero-loss: events < fence were applied by old owners (step 3);
    events ≥ fence are retained by the new slot (step 1) and applied by
    the new owner. Bounded-dup: an old owner may have applied a window
    past the fence before the flip — the new owner re-applies it, the
    same at-least-once window every crash restart already funds.

  remove shard (K → K-1, the TOP shard retires):
    same dance with the fence at the source's current WAL position; the
    retiring shard must drain to the fence before the flip, then its
    slots are deleted.

The coordinator is deliberately pod-external (an operator action / API
call), writes through the RAW store (never a shard view), and is safe to
re-run after a crash: a persisted `rebalancing` record carries
everything needed to resume the wait-and-flip.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

from ..analysis.annotations import domain, handoff
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..postgres.slots import apply_slot_name, table_sync_slot_name
from ..telemetry.metrics import (ETL_SHARD_COUNT, ETL_SHARD_EPOCH,
                                 ETL_SHARD_REBALANCE_DURATION_SECONDS,
                                 ETL_SHARD_REBALANCE_MOVED_TABLES_TOTAL,
                                 ETL_SHARD_TABLES, registry)
from .shardmap import (STATUS_REBALANCING, STATUS_STEADY, ShardAssignment,
                       ShardMap, moved_tables)

logger = logging.getLogger("etl_tpu.sharding")


@dataclass
class RebalanceResult:
    old_epoch: int
    new_epoch: int
    old_shard_count: int
    new_shard_count: int
    fence_lsn: int
    moved: dict = field(default_factory=dict)  # {tid: (old, new)}
    duration_s: float = 0.0

    def describe(self) -> dict:
        return {
            "old_epoch": self.old_epoch, "new_epoch": self.new_epoch,
            "old_shard_count": self.old_shard_count,
            "new_shard_count": self.new_shard_count,
            "fence_lsn": self.fence_lsn,
            "moved": {str(t): list(m) for t, m in sorted(self.moved.items())},
            "moved_tables": len(self.moved),
            "duration_s": round(self.duration_s, 3),
        }


class ShardCoordinator:
    """Drives the assignment record in the SHARED store. `source_factory`
    opens control connections to the source database (slot creation /
    WAL position / slot cleanup)."""

    def __init__(self, store, pipeline_id: int, source_factory,
                 quiesce_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.05):
        self.store = store
        self.pipeline_id = pipeline_id
        self.source_factory = source_factory
        self.quiesce_timeout_s = quiesce_timeout_s
        self.poll_interval_s = poll_interval_s

    # -- assignment access ----------------------------------------------------

    @handoff  # the ONE seam that mutates the multi-process shard fence:
    # every epoch/status transition pods act on goes through here, so a
    # crashed coordinator always leaves a resumable record behind
    async def _persist_assignment(self,
                                  assignment: ShardAssignment) -> None:
        await self.store.update_shard_assignment(assignment)

    @domain("coordinator")
    async def current(self, bootstrap_shard_count: int = 1
                      ) -> ShardAssignment:
        assignment = await self.store.get_shard_assignment()
        if assignment is None:
            assignment = ShardAssignment(
                epoch=0, shard_count=bootstrap_shard_count)
            await self._persist_assignment(assignment)
        return assignment

    async def _published_tables(self) -> list:
        # a deliberate cross-shard sweep: the coordinator owns the GLOBAL
        # view (it is not @shard_scoped, and must never run inside a pod)
        return sorted(await self.store.get_table_states())

    def publish_topology_metrics(self, assignment: ShardAssignment,
                                 tables) -> None:
        registry.gauge_set(ETL_SHARD_COUNT, assignment.shard_count)
        registry.gauge_set(ETL_SHARD_EPOCH, assignment.epoch)
        for shard, owned in assignment.shard_map().partition(tables).items():
            registry.gauge_set(ETL_SHARD_TABLES, len(owned),
                               labels={"shard": str(shard)})

    # -- two-phase rebalance --------------------------------------------------

    @domain("coordinator")
    async def add_shard(self) -> RebalanceResult:
        """Grow K→K+1 (the new shard is index K). Re-running after a
        crash or quiesce timeout RESUMES the persisted in-flight record
        (same fence, same target); a record targeting a DIFFERENT
        transition is refused."""
        assignment = await self.current()
        new_count = assignment.shard_count + 1
        resume = self._resumable(assignment, new_count)
        source = self.source_factory()
        await source.connect()
        try:
            # phase 1a: the new shard's apply slot anchors the fence —
            # WAL ≥ fence is retained for the new pod from this instant
            new_slot = apply_slot_name(self.pipeline_id, new_count - 1)
            if resume is not None:
                fence = resume  # the persisted record's fence wins
            else:
                existing = await source.get_slot(new_slot)
                if existing is not None:
                    # slot created but the record write was lost: its
                    # confirmed flush still marks the retention point
                    fence = existing.confirmed_flush_lsn
                else:
                    fence = (await source.create_slot(
                        new_slot)).consistent_point
            return await self._run_rebalance(assignment, new_count,
                                             fence, source)
        finally:
            await source.close()

    @domain("coordinator")
    async def abort_rebalance(self) -> None:
        """Roll an in-flight rebalance back to steady at the SAME epoch
        (pods never noticed); an add-shard's already-created slot is
        deleted so it cannot pin WAL."""
        assignment = await self.current()
        if not assignment.rebalancing:
            return
        if assignment.next_shard_count > assignment.shard_count:
            source = self.source_factory()
            await source.connect()
            try:
                await source.delete_slot(apply_slot_name(
                    self.pipeline_id, assignment.next_shard_count - 1))
            finally:
                await source.close()
        await self._persist_assignment(ShardAssignment(
            epoch=assignment.epoch, shard_count=assignment.shard_count,
            status=STATUS_STEADY))

    @domain("coordinator")
    async def remove_shard(self) -> RebalanceResult:
        """Shrink K→K-1 (the TOP shard retires; its tables re-home onto
        the survivors). The retired shard's slots are deleted after the
        flip. Re-running resumes an in-flight shrink like add_shard."""
        assignment = await self.current()
        if assignment.shard_count < 2:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "cannot remove the only shard")
        new_count = assignment.shard_count - 1
        resume = self._resumable(assignment, new_count)
        source = self.source_factory()
        await source.connect()
        try:
            fence = resume if resume is not None \
                else await source.get_current_wal_lsn()
            result = await self._run_rebalance(assignment, new_count,
                                               fence, source)
            # cleanup: the retired shard's slots must not pin WAL forever
            retired = assignment.shard_count - 1
            await source.delete_slot(
                apply_slot_name(self.pipeline_id, retired))
            for tid, (old, _new) in result.moved.items():
                if old == retired:
                    await source.delete_slot(table_sync_slot_name(
                        self.pipeline_id, tid, retired))
            return result
        finally:
            await source.close()

    async def _run_rebalance(self, assignment: ShardAssignment,
                             new_count: int, fence: Lsn,
                             source) -> RebalanceResult:
        t0 = time.monotonic()
        old_map = assignment.shard_map()
        new_map = ShardMap(new_count, assignment.epoch + 1)
        tables = await self._published_tables()
        moved = moved_tables(old_map, new_map, tables)

        # phase 1b: persist the in-flight record — a coordinator crash
        # after this point leaves enough state to resume (same fence,
        # same moved set; re-running recomputes both identically)
        await self._persist_assignment(ShardAssignment(
            epoch=assignment.epoch, shard_count=assignment.shard_count,
            status=STATUS_REBALANCING, fence_lsn=int(fence),
            next_shard_count=new_count,
            moved=tuple((tid, a, b) for tid, (a, b) in sorted(moved.items()))))

        # phase 1c: quiesce — every shard LOSING tables must be durably
        # applied up to the fence before ownership flips away from it
        losing = sorted({a for (a, _b) in moved.values()
                         if a < assignment.shard_count})
        await self._wait_durable(losing, fence)

        # phase 2: flip. From here the old epoch is refused by the store
        # fence; the orchestrator rolls pods onto the new topology.
        flipped = ShardAssignment(epoch=assignment.epoch + 1,
                                  shard_count=new_count,
                                  status=STATUS_STEADY)
        await self._persist_assignment(flipped)

        duration = time.monotonic() - t0
        registry.histogram_observe(ETL_SHARD_REBALANCE_DURATION_SECONDS,
                                   duration)
        registry.counter_inc(ETL_SHARD_REBALANCE_MOVED_TABLES_TOTAL,
                             len(moved))
        self.publish_topology_metrics(flipped, tables)
        logger.info(
            "rebalanced %d->%d shards at epoch %d (fence %s, %d tables "
            "moved, %.3fs)", assignment.shard_count, new_count,
            flipped.epoch, fence, len(moved), duration)
        return RebalanceResult(
            old_epoch=assignment.epoch, new_epoch=flipped.epoch,
            old_shard_count=assignment.shard_count,
            new_shard_count=new_count, fence_lsn=int(fence),
            moved=moved, duration_s=duration)

    def _resumable(self, assignment: ShardAssignment,
                   new_count: int) -> "Lsn | None":
        """None = steady (fresh rebalance); the persisted fence when the
        in-flight record targets the SAME transition (crash/timeout
        retry); typed error when it targets a different one — that
        rebalance must finish or be abort_rebalance()d first."""
        if not assignment.rebalancing:
            return None
        if assignment.next_shard_count == new_count:
            return Lsn(assignment.fence_lsn)
        raise EtlError(
            ErrorKind.INVALID_STATE_TRANSITION,
            f"a rebalance to shard_count="
            f"{assignment.next_shard_count} is already in flight at "
            f"epoch {assignment.epoch} (fence "
            f"{assignment.fence_lsn}); finish it (re-run the same "
            f"action) or abort_rebalance() first")

    async def _wait_durable(self, shards, fence: Lsn) -> None:
        """Poll the per-shard apply-slot durable progress until every
        listed shard has applied through the fence."""
        deadline = time.monotonic() + self.quiesce_timeout_s
        pending = list(shards)
        while pending:
            still = []
            for shard in pending:
                key = apply_slot_name(self.pipeline_id, shard)
                durable = await self.store.get_durable_progress(key)
                if durable is None or durable < fence:
                    still.append(shard)
            pending = still
            if not pending:
                return
            if time.monotonic() >= deadline:
                raise EtlError(
                    ErrorKind.TIMEOUT,
                    f"quiesce timed out: shard(s) {pending} never reached "
                    f"the fence LSN {int(fence)} within "
                    f"{self.quiesce_timeout_s}s")
            await asyncio.sleep(self.poll_interval_s)
