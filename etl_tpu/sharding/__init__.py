"""Horizontal scale-out: shard one publication across K replicators.

Four parts (docs/sharding.md):

  - `shardmap` — rendezvous (HRW) table→shard hashing, versioned by
    epoch, plus the persisted `ShardAssignment` record;
  - `runtime` — the shard-scoped runtime seam: `ShardScopedStore` filters
    a shared PipelineStore down to one shard's tables and FENCES writes
    (a pod holding a stale epoch, or touching a table another shard owns,
    gets a typed refusal instead of silently corrupting the handoff);
  - `coordinator` — `ShardCoordinator` drives add/remove-shard
    rebalancing as a two-phase epoch bump: quiesce moved tables at a
    fence LSN, flip the assignment, resume on the new owner from durable
    progress — zero-loss / bounded-dup by construction;
  - slot naming rides `postgres/slots.py` (`_s{shard}` suffixes).

Only `shardmap` is imported eagerly: `store/base.py` imports the
assignment record at module-import time, so the runtime/coordinator
halves (which import the store back) resolve lazily to keep the import
graph acyclic — the same convention as `etl_tpu/chaos`.
"""

from __future__ import annotations

from .shardmap import (ShardAssignment, ShardMap, STATUS_REBALANCING,
                       STATUS_STEADY, moved_tables)  # noqa: F401

_LAZY = {
    "ShardScopedStore": "runtime",
    "ShardIdentity": "runtime",
    "resolve_shard_scope": "runtime",
    "ShardCoordinator": "coordinator",
    "RebalanceResult": "coordinator",
}

__all__ = ["ShardAssignment", "ShardMap", "STATUS_REBALANCING",
           "STATUS_STEADY", "moved_tables", *_LAZY]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module 'etl_tpu.sharding' has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
