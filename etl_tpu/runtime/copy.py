"""Parallel table copy: CTID-range partitioning + shared work queue.

Reference parity: crates/etl/src/replication/table_sync/copy.rs —
plan `max(partitions_per_connection × connections, rows / rows_per_partition)`
clamped to `max_partitions` (copy.rs:54-58,132-161); largest-range-first
scheduling (copy.rs:541); N child connections sharing the exported snapshot
(copy.rs:346-363) drain a shared queue (copy.rs:572-607); per-partition
batched stream → `write_table_rows` (copy.rs:641-694).

TPU-first: each partition's COPY chunks go through the vectorized staging
scan + device decode (`batch_engine=tpu`) or the CPU oracle, producing
ColumnarBatches for the destination.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from ..analysis.annotations import flush_path
from ..config.pipeline import BatchEngine, PipelineConfig
from ..models.errors import ErrorKind, EtlError
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from ..ops.engine import DeviceDecoder
from ..ops.pipeline import DecodePipeline
from ..ops.staging import stage_copy_chunk
from ..postgres.codec.copy_text import parse_copy_chunk_columns
from ..postgres.source import ReplicationSource
from ..destinations.base import Destination
from .ack_window import CopyAckWindow
from ..telemetry.egress import record_egress
from ..telemetry.metrics import (ETL_TABLE_COPY_BYTES_TOTAL,
                                 ETL_TABLE_COPY_DURATION_SECONDS,
                                 ETL_TABLE_COPY_ROWS_TOTAL, registry)
from . import failpoints
from .shutdown import ShutdownRequested, ShutdownSignal, or_shutdown


@dataclass(frozen=True)
class CopyPartition:
    """A CTID page range [start_page, end_page); end None = to table end.
    `relation_id` is the physical relation to COPY (a leaf partition when
    the published table is partitioned; None = the table itself)."""

    start_page: int
    end_page: int | None
    estimated_rows: int
    relation_id: "TableId | None" = None


@dataclass
class CopyProgress:
    total_rows: int = 0
    partitions_done: int = 0
    bytes_written: int = 0  # monotonic COPY text total across ALL partitions


def plan_copy_partitions(estimated_rows: int, heap_pages: int,
                         config: PipelineConfig) -> list[CopyPartition]:
    """Reference planning math (copy.rs:54-58,457-547)."""
    c = config.table_sync_copy
    if estimated_rows <= 0 or heap_pages <= 0:
        return [CopyPartition(0, None, max(0, estimated_rows))]
    want = max(c.partitions_per_connection * c.max_connections,
               estimated_rows // max(1, c.rows_per_partition_target))
    n = int(min(max(1, want), c.max_partitions, heap_pages))
    pages_per = heap_pages // n
    extra = heap_pages % n
    parts: list[CopyPartition] = []
    page = 0
    for i in range(n):
        span = pages_per + (1 if i < extra else 0)
        end = page + span
        parts.append(CopyPartition(
            page, None if i == n - 1 else end,
            estimated_rows * span // heap_pages))
        page = end
    # largest-first so stragglers start early (copy.rs:541)
    parts.sort(key=lambda p: -p.estimated_rows)
    return parts


@flush_path
async def _copy_partition(source: ReplicationSource,
                          schema: ReplicatedTableSchema, snapshot_id: str,
                          publication: str, part: CopyPartition,
                          decoder: DeviceDecoder | None,
                          destination: Destination,
                          progress: CopyProgress,
                          max_batch_bytes: int, monitor=None,
                          lease=None, pipeline_id: int = 0,
                          decode_window: int = 3, heartbeat=None,
                          supervisor=None,
                          admission_capacity: int = 0,
                          write_window: int = 4) -> None:
    failpoints.fail_point(failpoints.COPY_PARTITION_START)
    # chaos stall mode: a copy partition that wedges before reading any
    # data — recovered by the watchdog restarting the table-sync worker
    await failpoints.stall_point(failpoints.COPY_PARTITION_START)
    rng = None if part.end_page is None and part.start_page == 0 \
        else (part.start_page, part.end_page if part.end_page is not None
              else 1 << 30)
    if part.relation_id is not None and part.relation_id != schema.id:
        stream = await source.copy_table_stream(
            part.relation_id, publication, snapshot_id, ctid_range=rng,
            publication_table_id=schema.id)
    else:
        stream = await source.copy_table_stream(
            schema.id, publication, snapshot_id, ctid_range=rng)
    oids = [c.type_oid for c in schema.replicated_columns]
    # chunk list + running length, joined once per flush: `pending += raw`
    # re-copies the accumulated buffer per 43 KB stream chunk — O(n²)
    # toward an 8 MB threshold, measured 0.7s/85MB on the copy bench
    pending: list[bytes] = []
    pending_len = 0
    # bounded ack window (runtime/ack_window.py): the old `acks` list
    # accumulated EVERY batch's unresolved ack until end-of-copy — a
    # huge table held unbounded pending acks and surfaced a failed ack
    # only at the partition barrier. The window caps outstanding acks
    # (shrinking to 1 under memory pressure) and awaits the OLDEST
    # first, so per-partition ordering is preserved and errors surface
    # within `write_window` batches.
    acks = CopyAckWindow(
        write_window,
        pressure=(lambda: monitor.pressure) if monitor is not None
        else None)
    # three-stage decode pipeline (ops/pipeline.py): chunk N+1 packs on
    # the pipeline's worker thread into a pooled arena while chunk N
    # computes on the device and N-1 streams back — this partition keeps
    # reading COPY data the whole time. One pipeline PER partition: each
    # partition drains only its own handles in order, so a shared window
    # could never be exhausted by another partition's undispatched work
    # (the cross-partition deadlock the per-partition worker rules out).
    in_flight: list = []
    # name carries the partition identity so concurrent partitions get
    # distinct gauge series instead of last-writer-winning one label
    pipe_hb = None
    if supervisor is not None and decoder is not None:
        from ..supervision import DECODE_PREFIX

        pipe_hb = supervisor.register(
            f"{DECODE_PREFIX}copy:{schema.id}:p{part.start_page}")
    pipe = None
    if decoder is not None:
        # every copy partition is one tenant on the process-wide
        # admission scheduler: backfill batches contend fairly with the
        # CDC streams' (lag-weighted — lag 0 here, so a lagging CDC
        # tenant outranks bulk backfill) and the shared capacity caps
        # how many partition batches sit on the device at once
        from ..ops.pipeline import global_admission

        admission = global_admission(admission_capacity or None).register(
            f"copy:{schema.id}:p{part.start_page}", monitor=monitor)
        pipe = DecodePipeline(window=decode_window, monitor=monitor,
                              name=f"copy-p{part.start_page}",
                              heartbeat=pipe_hb, admission=admission)

    async def drain_one() -> None:
        handle = in_flight.pop(0)
        # fetch on a thread: the event loop keeps serving the OTHER copy
        # partitions while this one waits out its device round trip
        batch = await asyncio.to_thread(handle.result)
        # columnar write seam: the decoded batch goes to the destination
        # AS a batch (Arrow/proto/TSV encoders consume it column-wise);
        # row-oriented destinations fall back via the base-class shim
        await acks.add(await destination.write_table_batch(schema, batch))
        progress.total_rows += batch.num_rows
        if heartbeat is not None:
            heartbeat.beat(progress=("copy_rows", progress.total_rows),
                           busy=True)
        registry.counter_inc(ETL_TABLE_COPY_ROWS_TOTAL, batch.num_rows)

    # per-PARTITION byte counter: progress.bytes_written is shared across
    # concurrently copying partitions, so attributing egress from it would
    # let whichever partition finishes first claim everyone's bytes
    # (VERDICT r2 weak #6) — the shared counter stays a monotonic total
    partition_bytes = 0

    async def write_chunk(chunk: bytes) -> None:
        nonlocal partition_bytes
        if not chunk:
            return
        failpoints.fail_point(failpoints.DURING_COPY)
        progress.bytes_written += len(chunk)
        partition_bytes += len(chunk)
        if heartbeat is not None:
            # the owning table-sync worker's liveness: bytes copied IS
            # the progress token; a frozen counter mid-copy is a stall
            heartbeat.beat(progress=("copy_bytes", progress.bytes_written),
                           busy=True)
        registry.counter_inc(ETL_TABLE_COPY_BYTES_TOTAL, len(chunk))
        if decoder is not None:
            staged = stage_copy_chunk(chunk, len(oids))
            in_flight.append(pipe.submit(decoder, staged))
            # drain ahead of the window so the destination write overlaps
            # the pipeline instead of bunching at end-of-stream; the
            # effective window shrinks to 1 under memory pressure, which
            # drains eagerly and degrades the pipeline to serial decode
            while len(in_flight) > pipe.effective_window:
                await drain_one()
            return
        # CPU oracle path: parse the chunk straight into columns — no
        # TableRow objects, no from_rows re-transpose (the old row
        # round-trip masked the real parse cost in profiles)
        cells, n_rows = parse_copy_chunk_columns(chunk, oids)
        batch = ColumnarBatch.from_cells(schema, cells, n_rows)
        await acks.add(await destination.write_table_batch(schema, batch))
        progress.total_rows += batch.num_rows
        registry.counter_inc(ETL_TABLE_COPY_ROWS_TOTAL, batch.num_rows)

    try:
        async for raw in stream:
            if monitor is not None and monitor.pressure:
                # stop pulling COPY data under memory pressure; the
                # server-side cursor waits (reference
                # TryBatchBackpressureStream pause)
                await monitor.wait_until_resumed()
            pending.append(raw)
            pending_len += len(raw)
            # budget-aware chunking: the per-stream share shrinks when many
            # partitions copy concurrently (batch_budget.rs:72-96)
            threshold = max_batch_bytes if lease is None \
                else min(max_batch_bytes, lease.ideal_batch_bytes())
            if pending_len >= threshold:
                buf = b"".join(pending)
                cut = buf.rfind(b"\n") + 1
                await write_chunk(buf[:cut])
                pending = [buf[cut:]] if cut < len(buf) else []
                pending_len = len(buf) - cut
        await write_chunk(b"".join(pending))
        while in_flight:
            await drain_one()
        if heartbeat is not None:
            # the chunk beats carry busy=True; without this the LAST
            # chunk's frozen byte count reads as a stall while the
            # worker legitimately sits in the durability barrier / park
            heartbeat.beat(busy=False)
    finally:
        if pipe is not None:
            pipe.close()
    # durability barrier for this partition (mod.rs:360-378): the window
    # owns the waits (etl-lint rule 17) — drain what is still pending
    await acks.drain()
    # chaos site: the window between a partition's durability barrier and
    # its progress accounting — a crash here must recopy consistently
    failpoints.fail_point(failpoints.COPY_PARTITION_END)
    if partition_bytes:
        record_egress(pipeline_id=pipeline_id,
                      destination=getattr(destination, "telemetry_name",
                                          type(destination).__name__),
                      bytes_processed=partition_bytes,
                      kind="table_copy")
    progress.partitions_done += 1


async def parallel_table_copy(*, source_factory, primary_source,
                              schema: ReplicatedTableSchema,
                              snapshot_id: str, config: PipelineConfig,
                              destination: Destination,
                              shutdown: ShutdownSignal, monitor=None,
                              budget=None, heartbeat=None,
                              supervisor=None) -> CopyProgress:
    """Copy one table through N snapshot-sharing connections."""
    leaves = await primary_source.get_partition_leaves(schema.id)
    if leaves:
        # partitioned root: plan per leaf, weighted by each leaf's stats
        # (reference copy.rs:457-547); CTID ranges are per physical
        # relation, so page math never spans leaves
        parts = []
        for leaf_id, est_rows, heap_pages in leaves:
            for p in plan_copy_partitions(est_rows, heap_pages, config):
                parts.append(CopyPartition(p.start_page, p.end_page,
                                           p.estimated_rows, leaf_id))
        parts.sort(key=lambda p: -p.estimated_rows)
    else:
        est_rows, heap_pages = \
            await primary_source.estimate_table_stats(schema.id)
        parts = plan_copy_partitions(est_rows, heap_pages, config)
    n_conns = min(config.table_sync_copy.max_connections, len(parts))
    # nonblocking: cold decode programs compile off-thread while their
    # chunks decode on the oracle — an inline first-touch build of a wide
    # schema would freeze this sync worker past its stall deadline (see
    # runtime/assembler._seal_run). A configured program cache turns the
    # first touch into a disk load instead: table re-syncs after a
    # restart decode on the cached executable from chunk one
    # (ops/program_store.py)
    decoder = DeviceDecoder(
        schema, nonblocking_compile=True,
        # fuse the destination's wire encoder into the copy decode
        # programs too (ops/egress.py)
        egress=(getattr(destination, "egress_encoder", None)
                if config.batch.device_egress else None)) \
        if config.batch.batch_engine is BatchEngine.TPU else None
    progress = CopyProgress()
    queue: asyncio.Queue[CopyPartition] = asyncio.Queue()
    for p in parts:
        queue.put_nowait(p)

    async def worker(use_primary: bool) -> None:
        src = primary_source if use_primary else source_factory()
        if not use_primary:
            await src.connect()
        lease = budget.register_stream() if budget is not None else None
        try:
            while True:
                try:
                    part = queue.get_nowait()
                except asyncio.QueueEmpty:
                    return
                await or_shutdown(shutdown, _copy_partition(
                    src, schema, snapshot_id, config.publication_name, part,
                    decoder, destination, progress,
                    config.batch.max_size_bytes, monitor=monitor,
                    lease=lease, pipeline_id=config.pipeline_id,
                    decode_window=config.batch.decode_window,
                    heartbeat=heartbeat, supervisor=supervisor,
                    admission_capacity=config.batch.admission_capacity,
                    write_window=config.batch.write_window))
        finally:
            if lease is not None:
                lease.release()
            if not use_primary:
                await src.close()

    import time as _time

    _t0 = _time.perf_counter()
    tasks = [asyncio.ensure_future(worker(i == 0)) for i in range(n_conns)]
    results = await asyncio.gather(*tasks, return_exceptions=True)
    errors = [r for r in results if isinstance(r, BaseException)]
    if errors:
        for r in errors:
            if isinstance(r, ShutdownRequested):
                raise r
        first = errors[0]
        raise first if isinstance(first, EtlError) else EtlError(
            ErrorKind.SOURCE_IO, f"copy failed: {first!r}")
    # completed copies only: failed/aborted attempts would skew the
    # duration distribution low
    registry.histogram_observe(ETL_TABLE_COPY_DURATION_SECONDS,
                               _time.perf_counter() - _t0)
    return progress
