"""Replication runtime: pipeline, apply loop, workers, state machine."""

from .apply_loop import ApplyContext, ApplyLoop, ExitIntent, TableSyncContext
from .pipeline import Pipeline
from .shutdown import ShutdownRequested, ShutdownSignal, or_shutdown
from .state import TableState, TableStateType
