"""Shared per-table protocol state.

Reference parity: `SharedTableCache` (crates/etl/src/replication/
table_cache.rs:53). Invariant (table_cache.rs:10-44): exactly one worker
owns protocol interpretation for a table at a time — the apply worker for
Ready tables, the table-sync worker for its own table. The cache maps
relation id → the current positional decode view (from RELATION messages),
shared so a handoff does not re-learn schemas.
"""

from __future__ import annotations

from ..models.schema import ReplicatedTableSchema, TableId


class SharedTableCache:
    def __init__(self) -> None:
        self._schemas: dict[TableId, ReplicatedTableSchema] = {}
        # publication row filters by table (ops/predicate.RowFilter):
        # RELATION messages carry no filter, so `set` re-attaches the
        # pipeline-discovered predicate to every decode view that enters
        # the cache — the decoder compiled from that view then fuses the
        # filter into its device program
        self._row_predicates: dict[TableId, object] = {}

    def get(self, table_id: TableId) -> ReplicatedTableSchema | None:
        return self._schemas.get(table_id)

    def set_row_predicates(self, predicates: "dict[TableId, object]") -> None:
        """Install the publication's parsed row filters (Pipeline.start).
        Already-cached schemas re-attach so a worker handoff can't decode
        through a filterless stale view."""
        self._row_predicates = dict(predicates)
        for tid, schema in list(self._schemas.items()):
            pred = self._row_predicates.get(tid)
            if pred is not None:
                self._schemas[tid] = schema.with_row_predicate(pred)

    def set(self, schema: ReplicatedTableSchema) -> None:
        pred = self._row_predicates.get(schema.id)
        if pred is not None and schema.row_predicate is None:
            schema = schema.with_row_predicate(pred)
        # identity-preserving on equal schemas: the walsender re-sends
        # RELATION per transaction; keeping the existing object lets
        # downstream `is` checks (assembler decoder reuse — and with it the
        # per-schema jit cache) survive the re-sends
        prev = self._schemas.get(schema.id)
        if prev is None or prev != schema:
            self._schemas[schema.id] = schema

    def remove(self, table_id: TableId) -> None:
        self._schemas.pop(table_id, None)

    def table_ids(self) -> list[TableId]:
        return list(self._schemas)
