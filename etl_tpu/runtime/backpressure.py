"""Memory backpressure: monitor, budgets, and pausable streams.

Reference parity:
  - `MemoryMonitor` (crates/etl/src/runtime/memory_monitor.rs:84): samples
    RSS vs cgroup-or-host limit on an interval; hysteresis activate@0.85 /
    resume@0.75 (etl-config pipeline.rs:199-201); watch-channel subscription
    consumed by streams.
  - `BatchBudgetController` (runtime/batch_budget.rs:22): ideal batch bytes
    = min(total_mem × ratio / active_streams, max_bytes) with RAII stream
    registration and a briefly-cached reader (100 ms).
  - `BackpressureStream` / `TryBatchBackpressureStream`
    (runtime/concurrency/stream.rs:45,133): pause intake under pressure;
    batch items by size/deadline with budget-aware flush.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from dataclasses import dataclass
from typing import AsyncIterator, Awaitable, Callable, Generic, TypeVar

from ..config.pipeline import MemoryBackpressureConfig

T = TypeVar("T")


class InFlightWindow:
    """Bounded in-flight window for the decode pipeline, monitor-aware.

    The pipeline's dispatch stage `acquire()`s one slot per batch before
    packing/dispatching; the fetch stage `release()`s it after the result
    lands. The limit caps host arenas + device buffers held by in-flight
    batches; under memory pressure (`MemoryMonitor.pressure`) the
    EFFECTIVE limit drops to 1 — the pipeline degrades to serial decode
    until the monitor's hysteresis resumes, the same stance as the WAL
    intake pause (BackpressureStream), applied to the decode stage.

    Thread-based (not asyncio): acquire happens on the pipeline's pack
    worker thread; release on whichever thread consumes the result. The
    pressure flag is re-read on every wakeup AND on a short poll tick, so
    a pressure transition never needs to signal the condition to be seen.
    """

    _POLL_S = 0.05

    def __init__(self, limit: int, monitor: "MemoryMonitor | None" = None):
        if limit < 1:
            raise ValueError("in-flight window needs limit >= 1")
        self.limit = limit
        self.monitor = monitor
        self._held = 0
        self._cond = threading.Condition()

    @property
    def effective_limit(self) -> int:
        if self.monitor is not None and self.monitor.pressure:
            return 1
        return self.limit

    def __len__(self) -> int:
        return self._held

    def acquire(self, bypass: "Callable[[], bool] | None" = None) -> None:
        """Block until a slot frees. `bypass` is a liveness valve: when it
        returns True (the pipeline has a consumer blocked on a batch that
        cannot dispatch until this acquire returns), the window overshoots
        its limit rather than deadlocking — memory cap traded for
        progress, only under out-of-order consumption. Re-checked on the
        poll tick, so no extra signalling is needed."""
        with self._cond:
            while self._held >= self.effective_limit \
                    and not (bypass is not None and bypass()):
                self._cond.wait(timeout=self._POLL_S)
            self._held += 1

    def release(self) -> None:
        with self._cond:
            self._held = max(0, self._held - 1)
            self._cond.notify_all()


def read_memory_limit_bytes() -> int:
    """cgroup v2/v1 limit if set, else total host memory
    (reference memory_monitor.rs:38-45)."""
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            raw = open(path).read().strip()
            if raw and raw != "max":
                v = int(raw)
                if 0 < v < 1 << 60:
                    return v
        except (OSError, ValueError):
            pass
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        return pages * page
    except (ValueError, OSError):
        return 8 << 30


def read_rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class MemoryMonitor:
    """Periodic RSS sampler with hysteresis; `pressure` is the watch value.

    `pressure_changed` is an asyncio.Event pulsed on every transition so
    streams can wait for resume without polling."""

    def __init__(self, config: MemoryBackpressureConfig,
                 limit_bytes: int | None = None,
                 rss_reader: Callable[[], int] = read_rss_bytes,
                 heartbeat=None):
        self.config = config
        self.limit_bytes = limit_bytes or read_memory_limit_bytes()
        self._rss_reader = rss_reader
        # supervision.Heartbeat | None: each sample beats with a sample
        # counter — a stale monitor heartbeat means the sampler died and
        # backpressure is blind
        self._hb = heartbeat
        self._samples = 0
        self.pressure = False
        self.last_rss = 0
        self._mem_pressure = False
        # externally-imposed pause (maintenance coordination lease): the
        # published `pressure` is the OR of memory pressure and this flag
        self.external_pause = False
        self._resumed = asyncio.Event()
        self._resumed.set()
        self._task: asyncio.Task | None = None

    def set_external_pause(self, paused: bool) -> None:
        """Pause/resume intake for a non-memory reason (external
        maintenance pause lease). Composes with memory hysteresis: intake
        resumes only when BOTH conditions clear."""
        self.external_pause = paused
        self._publish()

    def _publish(self) -> None:
        effective = self._mem_pressure or self.external_pause
        if effective and not self.pressure:
            self.pressure = True
            self._resumed.clear()
        elif not effective and self.pressure:
            self.pressure = False
            self._resumed.set()

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._hb is not None:
            self._hb.close()
            self._hb = None

    def sample_once(self) -> bool:
        """One sample + hysteresis update; returns current pressure. The
        monitor owns the backpressure metrics: it is the single hysteresis
        authority, so one pressure episode counts once no matter how many
        streams pause on it."""
        from ..telemetry.metrics import (
            ETL_MEMORY_BACKPRESSURE_ACTIVATIONS_TOTAL,
            ETL_MEMORY_BACKPRESSURE_ACTIVE, registry)

        self.last_rss = self._rss_reader()
        self._samples += 1
        if self._hb is not None:
            self._hb.beat(progress=("samples", self._samples))
        ratio = self.last_rss / max(1, self.limit_bytes)
        if not self._mem_pressure and ratio >= self.config.activate_ratio:
            self._mem_pressure = True
            registry.counter_inc(ETL_MEMORY_BACKPRESSURE_ACTIVATIONS_TOTAL)
            registry.gauge_set(ETL_MEMORY_BACKPRESSURE_ACTIVE, 1)
        elif self._mem_pressure and ratio <= self.config.resume_ratio:
            self._mem_pressure = False
            registry.gauge_set(ETL_MEMORY_BACKPRESSURE_ACTIVE, 0)
        self._publish()
        return self.pressure

    async def _run(self) -> None:
        interval = self.config.refresh_interval_ms / 1000
        while True:
            self.sample_once()
            await asyncio.sleep(interval)

    async def wait_until_resumed(self) -> None:
        await self._resumed.wait()  # etl-lint: ignore[unbounded-await] — resume is hysteresis-driven by design; callers are cancellation-scoped (apply loop select, copy partitions under or_shutdown)


class BatchBudgetController:
    """Per-stream byte budgets: ideal = min(limit × ratio / active, max)
    (reference batch_budget.rs:72-96), cached for 100 ms."""

    CACHE_TTL_S = 0.1

    def __init__(self, config: MemoryBackpressureConfig, max_bytes: int,
                 limit_bytes: int | None = None):
        self.config = config
        self.max_bytes = max_bytes
        self.limit_bytes = limit_bytes or read_memory_limit_bytes()
        self._active = 0
        self._cached: tuple[float, int] | None = None

    def register_stream(self) -> "BudgetLease":
        self._active += 1
        self._cached = None
        return BudgetLease(self)

    def _release(self) -> None:
        self._active = max(0, self._active - 1)
        self._cached = None

    def ideal_batch_bytes(self) -> int:
        now = time.monotonic()
        if self._cached is not None and now - self._cached[0] < self.CACHE_TTL_S:
            return self._cached[1]
        share = self.limit_bytes * self.config.memory_ratio \
            / max(1, self._active)
        value = int(min(share, self.max_bytes))
        self._cached = (now, value)
        return value


class BudgetLease:
    """RAII registration (reference batch_budget.rs:49-54,141-152)."""

    def __init__(self, controller: BatchBudgetController):
        self._controller = controller
        self._released = False

    def ideal_batch_bytes(self) -> int:
        return self._controller.ideal_batch_bytes()

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._controller._release()

    def __enter__(self) -> "BudgetLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


async def backpressured(source: AsyncIterator[T],
                        monitor: MemoryMonitor) -> AsyncIterator[T]:
    """Pause pulling from `source` while the monitor reports pressure
    (reference BackpressureStream, stream.rs:45-122)."""
    async for item in source:
        yield item
        if monitor.pressure:
            await monitor.wait_until_resumed()


@dataclass
class Batch(Generic[T]):
    items: list[T]
    size_bytes: int


async def batch_with_budget(source: AsyncIterator[T],
                            size_of: Callable[[T], int],
                            lease: BudgetLease,
                            max_fill_s: float) -> AsyncIterator[Batch[T]]:
    """Batch items by budget bytes + fill deadline (reference
    TryBatchBackpressureStream, stream.rs:133)."""
    items: list[T] = []
    size = 0
    deadline: float | None = None
    it = source.__aiter__()
    pending: asyncio.Task | None = None
    try:
        while True:
            if pending is None:
                pending = asyncio.ensure_future(it.__anext__())
            timeout = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            done, _ = await asyncio.wait({pending}, timeout=timeout)
            if pending in done:
                try:
                    item = pending.result()
                except StopAsyncIteration:
                    break
                pending = None
                items.append(item)
                size += size_of(item)
                if deadline is None:
                    deadline = time.monotonic() + max_fill_s
                if size >= lease.ideal_batch_bytes():
                    yield Batch(items, size)
                    items, size, deadline = [], 0, None
            elif items:  # deadline hit
                yield Batch(items, size)
                items, size, deadline = [], 0, None
            else:
                deadline = None
    finally:
        if pending is not None and not pending.done():
            pending.cancel()
            try:
                await pending
            except (asyncio.CancelledError, StopAsyncIteration):
                pass
    if items:
        yield Batch(items, size)
