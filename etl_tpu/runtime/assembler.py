"""Event assembly: pgoutput row messages → destination events, on either
decode engine.

The apply loop pushes raw row messages here; `flush()` returns the ordered
event list for the destination write.

- CPU engine: each message decodes immediately via the codec oracle
  (reference-architecture per-tuple path, codec/event.rs).
- TPU engine: row-message payloads accumulate as raw bytes per contiguous
  same-table run; at flush, each run is framed (native framer), staged and
  decoded on device in one batch, emitted as `DecodedBatchEvent`s. Control
  events (Begin/Commit/Relation/Truncate/SchemaChange) stay host-decoded
  and act as run barriers — mirroring the reference's per-table batching
  between barriers (bigquery/core.rs:956-978).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..analysis.annotations import hot_loop
from ..config.pipeline import BatchEngine
from ..models.errors import ErrorKind, EtlError
from ..models.event import DecodedBatchEvent, Event
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from ..ops.engine import DeviceDecoder
from ..ops.pipeline import DecodePipeline
from ..ops.wal import stage_wal_batch
from ..postgres.codec import event as event_codec
from ..postgres.codec import pgoutput


@dataclass
class _Run:
    """A contiguous run of row messages for one table."""

    table_id: TableId
    schema: ReplicatedTableSchema
    payloads: list[bytes] = field(default_factory=list)
    start_lsns: list[int] = field(default_factory=list)
    commit_lsns: list[int] = field(default_factory=list)
    tx_ordinals: list[int] = field(default_factory=list)
    nbytes: int = 0  # size-hint bytes (64/row + payload), the seal bound


#: seal an open run once it reaches this many rows. Two effects: decode
#: dispatch starts while the stream keeps flowing (the device/host XLA
#: call overlaps further WAL intake instead of bunching at flush), and
#: staged batches never exceed the 16384-row bucket — so the decode
#: program's (row-bucket, width-signature) key space stays small and a
#: long-running pipeline stops hitting fresh ~0.3s XLA compiles when a
#: backlog drains through ever-larger flushes.
RUN_SEAL_ROWS = 16384

#: backlog growth cap for the dynamic seal (the largest standard row
#: bucket): under sustained backlog the apply loop grows seals toward
#: this so staged batches clear the measured device-routing threshold —
#: the steady-state data plane then decodes on the accelerator instead
#: of capping every run at the host-size bucket (VERDICT r4 #1b).
MEGA_SEAL_ROWS = 262_144


#: distinguishes concurrent assemblers' decode heartbeats (apply loop +
#: table-sync catchup loops each own one)
_ASSEMBLER_SEQ = [0]


class EventAssembler:
    def __init__(self, engine: BatchEngine, monitor=None,
                 decode_window: int = 3, supervisor=None,
                 lag_bytes=None, admission_capacity: int = 0,
                 seal_bytes: int = 0, egress_encoder: "str | None" = None):
        self.engine = engine
        # wire-encoder name (ops/egress.py) the destination consumes —
        # bound into every DeviceDecoder this loop creates so decoded
        # batches carry device-rendered wire buffers (`device_egress`)
        self.egress_encoder = egress_encoder
        # byte seal (0 = off): seal the open run once its size-hint
        # bytes reach this bound (scaled with the dynamic row seal the
        # same ×-factor _scaled_max_bytes uses), so one contiguous run
        # can never exceed the flush sizing — size-bounded flushes then
        # cut at event granularity and the write window has batches to
        # pipeline. The apply loop passes BatchConfig.max_size_bytes; at
        # typical row widths the 16384-row seal binds first, so decode
        # batch shapes are unchanged.
        self.seal_bytes = seal_bytes
        # fair-admission wiring (ops/pipeline.AdmissionScheduler): this
        # loop's decode pipeline takes one tenant seat on the process-
        # wide scheduler, weighted by `lag_bytes` (the apply loop's
        # received−durable delta — the SlotLagMetrics shape) so a
        # lagging stream wins more batch admissions when several streams
        # share the device set
        self._lag_bytes = lag_bytes
        self._admission_capacity = admission_capacity
        self._events: list[Event] = []
        # per-event (size_bytes, row_events) — lets flush(max_bytes=...)
        # cut a WAL-ordered prefix at event granularity and keep the
        # remainder's accounting exact (the write window dispatches
        # size-bounded batches instead of one backlog-sized mega write)
        self._meta: list[tuple[int, int]] = []
        # commit watermarks: (n_events_covered, commit_end_lsn) — all
        # events with index < n (counting the open run as one future
        # event) belong to commits ending ≤ commit_end_lsn, so a prefix
        # flush of ≥ n events may claim durability at that LSN once
        # acked. The apply loop records one per commit boundary
        # (note_commit_end); flush() consumes the covered prefix.
        self._commit_marks: list[tuple[int, int]] = []
        self._run: _Run | None = None
        self._decoders: dict[TableId, DeviceDecoder] = {}
        # one decode pipeline (worker thread + bounded in-flight window)
        # serves every table this loop assembles; created lazily so the
        # CPU engine never spawns the thread. The monitor shrinks the
        # window to 1 under memory pressure (runtime/backpressure).
        self._monitor = monitor
        self._decode_window = decode_window
        self._supervisor = supervisor  # supervision.Supervisor | None
        _ASSEMBLER_SEQ[0] += 1
        self._seq = _ASSEMBLER_SEQ[0]
        self._pipeline: DecodePipeline | None = None
        # dynamic: the apply loop grows it ×4 (one row bucket per step)
        # under sustained backlog and resets it when the stream idles
        self.seal_rows = RUN_SEAL_ROWS
        self.size_bytes = 0
        # row (non-control) events in the open window: the apply loop's
        # idle-commit fast flush keys on this — control-only windows
        # (CPU-engine Begin/Commit of unowned-table transactions) must
        # stay on the deadline path or durable progress would be written
        # once per commit instead of once per fill window
        self.row_events = 0

    def __len__(self) -> int:
        return len(self._events) + (len(self._run.payloads) if self._run else 0)

    # -- pushes ---------------------------------------------------------------

    def push_control(self, ev: Event, size_hint: int = 64) -> None:
        """Begin/Commit/Relation/Truncate/SchemaChange — barrier events."""
        self._seal_run()
        self._events.append(ev)
        self._meta.append((size_hint, 0))
        self.size_bytes += size_hint

    @hot_loop
    def push_raw_row(self, payload: bytes, schema: ReplicatedTableSchema,
                     start_lsn: Lsn, commit_lsn: Lsn,
                     tx_ordinal: int) -> None:
        """TPU fast path: accumulate the raw row-message payload without
        host-side tuple parsing (the framer parses it on the device staging
        path). Callers guarantee payload[0] is I/U/D. @hot_loop: runs once
        per CDC row — a host transfer here caps stream throughput."""
        if self._run is None or self._run.table_id != schema.id \
                or self._run.schema is not schema:
            self._seal_run()
            self._run = _Run(table_id=schema.id, schema=schema)
        r = self._run
        r.payloads.append(payload)
        r.start_lsns.append(int(start_lsn))
        r.commit_lsns.append(int(commit_lsn))
        r.tx_ordinals.append(tx_ordinal)
        r.nbytes += 64 + len(payload)
        self.size_bytes += 64 + len(payload)
        self.row_events += 1
        if len(r.payloads) >= self.seal_rows \
                or (self.seal_bytes
                    and r.nbytes >= self._scaled_seal_bytes()):
            self._seal_run()

    @hot_loop
    def push_raw_rows(self, payloads: list[bytes],
                      schema: ReplicatedTableSchema, start_lsns: list[int],
                      commit_lsn: int, tx_ordinal0: int) -> int:
        """Bulk form of push_raw_row for a contiguous same-table span (the
        apply loop's drained-window fast path): one call per span, list
        extends instead of per-row pushes. Returns the span's payload
        bytes (the caller's tx_bytes accounting needs the same sum).
        @hot_loop: one call per drained span on the saturated path."""
        if self._run is None or self._run.table_id != schema.id \
                or self._run.schema is not schema:
            self._seal_run()
            self._run = _Run(table_id=schema.id, schema=schema)
        r = self._run
        k = len(payloads)
        if len(r.payloads) + k > self.seal_rows and r.payloads:
            # seal BEFORE extending: overshooting the cap would bump the
            # staged batch into the next (unwarmed) row bucket
            self._seal_run()
            self._run = r = _Run(table_id=schema.id, schema=schema)
        r.payloads.extend(payloads)
        r.start_lsns.extend(start_lsns)
        r.commit_lsns.extend([commit_lsn] * k)
        r.tx_ordinals.extend(range(tx_ordinal0, tx_ordinal0 + k))
        nbytes = sum(map(len, payloads))
        r.nbytes += 64 * k + nbytes
        self.size_bytes += 64 * k + nbytes
        self.row_events += k
        if len(r.payloads) >= self.seal_rows \
                or (self.seal_bytes
                    and r.nbytes >= self._scaled_seal_bytes()):
            # byte overshoot of at most one span: the seal check runs per
            # span push, so a drained-window span lands whole
            self._seal_run()
        return nbytes

    def _scaled_seal_bytes(self) -> int:
        """Byte seal scaled with the dynamic row seal — the same growth
        factor the apply loop's _scaled_max_bytes applies, so backlog
        mega-batching grows flush payloads and run seals in lockstep."""
        return self.seal_bytes * max(1, self.seal_rows // RUN_SEAL_ROWS)

    # -- dynamic seal (backlog mega-batching) ---------------------------------

    def grow_seal(self) -> None:
        """×4 per step = exactly one standard row bucket (16384 → 65536 →
        262144), so growth never lands in an intermediate bucket whose
        decode program would be a wasted compile."""
        if self.seal_rows < MEGA_SEAL_ROWS:
            self.seal_rows = min(self.seal_rows * 4, MEGA_SEAL_ROWS)

    def reset_seal(self) -> None:
        self.seal_rows = RUN_SEAL_ROWS

    def push_row_message(self, msg: pgoutput.LogicalReplicationMessage,
                         payload: bytes, schema: ReplicatedTableSchema,
                         start_lsn: Lsn, commit_lsn: Lsn,
                         tx_ordinal: int) -> None:
        if self.engine is BatchEngine.CPU:
            if isinstance(msg, pgoutput.InsertMessage):
                ev: Event = event_codec.decode_insert(
                    msg, schema, start_lsn, commit_lsn, tx_ordinal)
            elif isinstance(msg, pgoutput.UpdateMessage):
                ev = event_codec.decode_update(
                    msg, schema, start_lsn, commit_lsn, tx_ordinal)
            elif isinstance(msg, pgoutput.DeleteMessage):
                ev = event_codec.decode_delete(
                    msg, schema, start_lsn, commit_lsn, tx_ordinal)
            else:
                raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                               f"not a row message: {type(msg).__name__}")
            self._events.append(ev)
            self._meta.append((64 + len(payload), 1))
            self.size_bytes += 64 + len(payload)
            self.row_events += 1
            return
        # TPU path: defer decode, accumulate raw payloads
        self.push_raw_row(payload, schema, start_lsn, commit_lsn, tx_ordinal)

    # -- flush ----------------------------------------------------------------

    def _seal_run(self) -> None:
        if self._run is None or not self._run.payloads:
            self._run = None
            return
        from ..chaos import failpoints

        # chaos site: fires once per sealed run (a decode batch is born)
        failpoints.fail_point(failpoints.ASSEMBLER_SEAL)
        r = self._run
        self._run = None
        decoder = self._decoders.get(r.table_id)
        if decoder is None or decoder.schema is not r.schema:
            # nonblocking: a cold (bucket, specs) program compiles on a
            # background thread while its batches decode on the oracle —
            # a synchronous first-touch build of a wide schema (measured
            # 32s at 120 columns) would wedge the apply loop past the
            # stall deadline and spiral the watchdog into restarts.
            # With a program cache dir configured the cold key usually
            # isn't cold at all: Pipeline.start's prewarm (or the
            # first-touch disk probe in engine._host_fn_ready) loads the
            # previous incarnation's AOT executable, so a warm restart
            # decodes its first flush on the real program, zero builds
            # (ops/program_store.py)
            decoder = DeviceDecoder(r.schema, nonblocking_compile=True,
                                    egress=self.egress_encoder)
            self._decoders[r.table_id] = decoder
        lens = np.fromiter((len(p) for p in r.payloads), dtype=np.int32,
                           count=len(r.payloads))
        offs = np.zeros(len(r.payloads), dtype=np.int64)
        np.cumsum(lens[:-1], out=offs[1:])
        buf = b"".join(r.payloads)
        n_cols = r.schema.replicated_column_count()
        wal = stage_wal_batch(buf, offs, lens, n_cols)
        if wal.bad_from >= 0:
            raise EtlError(ErrorKind.WAL_DECODE_FAILED,
                           f"malformed row message at run index {wal.bad_from}")
        # pipelined dispatch (ops/pipeline.py): the pack runs on the
        # pipeline's worker thread into a pooled arena and the device
        # decodes (and streams results back) while the apply loop keeps
        # reading WAL; the DecodedBatchEvent resolves the batch lazily
        # when the destination write consumes it, in submit order — the
        # bounded in-flight window caps staged memory across flushes
        if self._pipeline is None:
            hb = None
            if self._supervisor is not None:
                # decode components are observe-only: recovery of a stuck
                # pipeline rides the owning worker's restart, and repeated
                # detections escalate to the host-oracle degrade
                from ..supervision import DECODE_PREFIX

                hb = self._supervisor.register(
                    f"{DECODE_PREFIX}cdc-{self._seq}")
            from ..ops.pipeline import global_admission

            admission = global_admission(
                self._admission_capacity or None).register(
                    f"cdc-{self._seq}", lag_bytes=self._lag_bytes,
                    monitor=self._monitor)
            self._pipeline = DecodePipeline(window=self._decode_window,
                                            monitor=self._monitor,
                                            name="cdc", heartbeat=hb,
                                            admission=admission)
        # publication row-filter eligibility: the fused device filter
        # compacts INSERT-only runs (and the COPY path); runs carrying
        # updates/deletes keep the server-side filtering contract — the
        # U/D row-filter transforms (UPDATE whose old image leaves the
        # filter becomes INSERT, etc.) are walsender semantics the client
        # does not re-implement (docs/decode-pipeline.md)
        from ..models.event import ChangeType

        wal.staged.allow_row_filter = bool(
            wal.old_staged is None
            and (wal.change_types == ChangeType.INSERT).all())
        if wal.old_staged is not None:
            wal.old_staged.allow_row_filter = False
        pending = self._pipeline.submit(decoder, wal.staged)
        old_pending = self._pipeline.submit(decoder, wal.old_staged) \
            if wal.old_staged is not None else None
        self._events.append(DecodedBatchEvent(
            Lsn(r.start_lsns[0]), Lsn(r.commit_lsns[-1]), r.schema,
            pending=pending,
            change_types=wal.change_types,
            commit_lsns=np.asarray(r.commit_lsns, dtype=np.uint64),
            tx_ordinals=np.asarray(r.tx_ordinals, dtype=np.uint64),
            old_pending=old_pending, old_rows=wal.old_rows,
            old_is_key=wal.old_is_key, delete_is_key=wal.delete_is_key,
        ))
        self._meta.append((64 * len(r.payloads) + sum(map(len, r.payloads)),
                           len(r.payloads)))

    def note_commit_end(self, end_lsn: Lsn) -> None:
        """Record a commit watermark: every event assembled SO FAR
        (counting the open run as the one event it seals into) belongs
        to transactions whose commit ends ≤ `end_lsn`. The open run may
        still grow past the mark — the sealed event then carries extra
        later rows, which only makes the covered prefix a superset
        (claiming durability at the mark stays exact). The apply loop
        calls this once per commit boundary; flush() consumes marks with
        the prefix they cover."""
        n = len(self._events) \
            + (1 if self._run is not None and self._run.payloads else 0)
        lsn = int(end_lsn)
        if self._commit_marks and self._commit_marks[-1][0] == n:
            self._commit_marks[-1] = (n, max(self._commit_marks[-1][1], lsn))
        else:
            self._commit_marks.append((n, lsn))

    def flush(self) -> list[Event]:
        """Seal any open run, return and reset the assembled events
        (the whole window — legacy signature; the apply loop's
        size-bounded dispatch goes through `flush_bounded`)."""
        return self.flush_bounded()[0]

    def flush_bounded(self, max_bytes: "int | None" = None
                      ) -> "tuple[list[Event], Lsn | None, Lsn | None]":
        """Seal any open run and return `(events, covered_commit_end,
        remaining_commit_end)`.

        With `max_bytes=None` (or everything fitting) the whole window
        flushes — exact legacy behavior. Otherwise a WAL-ORDERED PREFIX
        of events totalling ≤ max_bytes (always at least one event) is
        returned and the remainder stays assembled, so the write window
        dispatches size-bounded batches a backlog can pipeline instead
        of one backlog-sized mega write.

        `covered_commit_end` is the highest commit watermark whose
        events are ALL inside the returned prefix (None = the flush
        covers no commit boundary — mid-transaction split);
        `remaining_commit_end` is the highest watermark still pending in
        the assembler (None = nothing awaits a future flush)."""
        self._seal_run()
        if max_bytes is None or self.size_bytes <= max_bytes \
                or len(self._events) <= 1:
            events = self._events
            covered = Lsn(self._commit_marks[-1][1]) \
                if self._commit_marks else None
            self._events = []
            self._meta = []
            self._commit_marks = []
            self.size_bytes = 0
            self.row_events = 0
            return events, covered, None
        cum = 0
        k = 0
        n = len(self._events)
        while k < n and (k == 0 or cum + self._meta[k][0] <= max_bytes):
            cum += self._meta[k][0]
            k += 1
        events = self._events[:k]
        self._events = self._events[k:]
        self._meta = self._meta[k:]
        self.size_bytes -= cum
        self.row_events = sum(r for _, r in self._meta)
        covered = None
        while self._commit_marks and self._commit_marks[0][0] <= k:
            covered = Lsn(self._commit_marks.pop(0)[1])
        self._commit_marks = [(m - k, lsn) for m, lsn in self._commit_marks]
        remaining = Lsn(self._commit_marks[-1][1]) \
            if self._commit_marks else None
        return events, covered, remaining

    def close(self) -> None:
        """Stop the decode pipeline's worker (apply-loop teardown).
        Already-flushed DecodedBatchEvents stay resolvable — close only
        fences new submits, and _seal_run re-creates the pipeline if a
        resumed loop reuses this assembler."""
        if self._pipeline is not None:
            self._pipeline.close()
            self._pipeline = None
