"""Apply worker: owns the main replication slot and the retry loop.

Reference parity: crates/etl/src/runtime/apply/worker.rs —
start LSN = max(durable progress, slot confirmed_flush) (worker.rs:440-465);
invalidated-slot handling per InvalidatedSlotBehavior (Error vs
Recreate+reset-all-tables, worker.rs:476-527); policy-driven timed retry
loop (worker.rs:148-207,237-281).
"""

from __future__ import annotations

import asyncio
import logging

from ..config.pipeline import InvalidatedSlotBehavior, PipelineConfig
from ..models.errors import ErrorKind, EtlError, RetryKind
from ..retry import RetryPolicy
from ..models.lsn import Lsn
from ..postgres.slots import apply_slot_name
from ..postgres.source import ReplicationSource
from ..store.base import PipelineStore
from ..destinations.base import Destination
from .apply_loop import ApplyContext, ApplyLoop, ExitIntent
from .shutdown import ShutdownRequested, ShutdownSignal, or_shutdown
from .table_cache import SharedTableCache
from .table_sync import TableSyncWorkerPool

logger = logging.getLogger("etl_tpu.apply_worker")


class ApplyWorker:
    def __init__(self, *, config: PipelineConfig, store: PipelineStore,
                 destination: Destination, source_factory,
                 pool: TableSyncWorkerPool, table_cache: SharedTableCache,
                 shutdown: ShutdownSignal, monitor=None, budget=None,
                 supervisor=None):
        self.config = config
        self.store = store
        self.destination = destination
        self.source_factory = source_factory
        self.pool = pool
        self.cache = table_cache
        self.shutdown = shutdown
        self.monitor = monitor
        self.budget = budget
        self.supervisor = supervisor  # supervision.Supervisor | None
        self._restart_requested: asyncio.Event | None = None
        self._hb = None  # registered in _guarded_run (loop must be live)
        # sharded pods stream through their own `_s{shard}` slot: the
        # durable-progress key AND the replication stream are per-shard
        self.slot_name = apply_slot_name(config.pipeline_id, config.shard)
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self._guarded_run())
        return self._task

    async def _guarded_run(self) -> None:
        """Timed-retry wrapper (reference worker.rs:237-281), backoff via
        the unified worker-scoped RetryPolicy (etl_tpu/retry.py). Under
        supervision each attempt races the supervisor's restart request:
        a detected stall/hang cancels the attempt and funnels into the
        SAME retry loop as any transient error."""
        policy = RetryPolicy.from_config(self.config.apply_retry)
        if self.supervisor is not None:
            self._restart_requested = asyncio.Event()
            self._hb = self.supervisor.register(
                "apply", restartable=True,
                on_restart=self._restart_requested.set)
        attempt = 0
        try:
            await self._retry_loop(policy, attempt)
        finally:
            if self._hb is not None:
                self._hb.close()
                self._hb = None

    async def _retry_loop(self, policy: RetryPolicy, attempt: int) -> None:
        while not self.shutdown.is_triggered:
            try:
                await self._run_once_supervised()
                return  # clean pause
            except ShutdownRequested:
                return
            except asyncio.CancelledError:
                raise
            except EtlError as e:
                if policy.classify(e) is not RetryKind.TIMED \
                        or attempt + 1 >= policy.max_attempts:
                    logger.error("apply worker failed permanently: %s", e)
                    raise
                attempt += 1
                delay = policy.delay(attempt - 1)
                logger.warning("apply worker error (attempt %d, retry in "
                               "%.1fs): %s", attempt, delay, e)
                try:
                    await or_shutdown(self.shutdown, asyncio.sleep(delay))
                except ShutdownRequested:
                    return
            except Exception as e:  # containment → timed retry
                attempt += 1
                if attempt >= policy.max_attempts:
                    raise EtlError(ErrorKind.WORKER_PANICKED, repr(e))
                try:
                    await or_shutdown(
                        self.shutdown,
                        asyncio.sleep(policy.delay(attempt - 1)))
                except ShutdownRequested:
                    return

    async def _run_once_supervised(self) -> None:
        """Race one attempt against the supervisor's restart request; a
        won race cancels the wedged attempt (the stall sites are all
        cancellable awaits) and raises a TIMED-retryable stall error."""
        if self._restart_requested is None:
            return await self._run_once()
        if self._hb is not None:
            self._hb.reset_clocks()  # fresh deadlines per attempt
        # a restart request that landed while the previous attempt was
        # already failing on its own must not instantly abort THIS fresh
        # attempt with a fabricated stall
        self._restart_requested.clear()
        run = asyncio.ensure_future(self._run_once())
        trip = asyncio.ensure_future(self._restart_requested.wait())
        try:
            done, _ = await asyncio.wait({run, trip},
                                         return_when=asyncio.FIRST_COMPLETED)
            if run in done:
                return run.result()
            self._restart_requested.clear()
            raise EtlError(
                ErrorKind.STALL_DETECTED,
                "apply worker cancelled by the supervision watchdog "
                "(stalled or hung); restarting from durable progress")
        finally:
            # drain_cancelled, NOT try/await/except: a hard-kill cancel
            # landing in this finally must still kill us
            from .shutdown import drain_cancelled

            await drain_cancelled(run, trip)

    async def _run_once(self) -> None:
        source: ReplicationSource = self.source_factory()
        await source.connect()
        try:
            start_lsn = await self._get_start_lsn(source)
            await self.pool.refresh_states()
            stream = await source.start_replication(
                self.slot_name, self.config.publication_name, start_lsn)
            ctx = ApplyContext(progress_key=self.slot_name,
                               coordination=self.pool)
            loop = ApplyLoop(ctx=ctx, stream=stream, store=self.store,
                             destination=self.destination,
                             table_cache=self.cache, config=self.config,
                             shutdown=self.shutdown, start_lsn=start_lsn,
                             monitor=self.monitor, budget=self.budget,
                             heartbeat=self._hb, supervisor=self.supervisor)
            sampler = asyncio.ensure_future(self._lag_sampler(loop)) \
                if self.config.lag_sample_interval_s > 0 else None
            try:
                intent = await loop.run()
            finally:
                if sampler is not None:
                    sampler.cancel()
                    try:
                        await sampler
                    except asyncio.CancelledError:
                        pass
            assert intent is ExitIntent.PAUSE
        finally:
            await source.close()

    async def _lag_sampler(self, loop: ApplyLoop) -> None:
        """Out-of-band lag gauges on a lazy side connection (reference
        apply.rs:579-624 + observability.rs:46-50): polls the server's
        current WAL position so end-to-end and effective-flush lag keep
        updating even when the apply loop is busy or idle."""
        from ..telemetry.metrics import (
            ETL_APPLY_LOOP_EFFECTIVE_FLUSH_LAG_BYTES,
            ETL_APPLY_LOOP_END_TO_END_LAG_BYTES, registry)

        interval = self.config.lag_sample_interval_s
        source: ReplicationSource | None = None
        try:
            while True:
                await asyncio.sleep(interval)
                try:
                    if source is None:
                        source = self.source_factory()
                        await source.connect()
                    wal = await source.get_current_wal_lsn()
                except asyncio.CancelledError:
                    raise
                except Exception:  # etl-lint: ignore[cancellation-swallow]
                    # lag sampling must never take down the apply worker;
                    # drop the connection and retry on the next tick
                    if source is not None:
                        try:
                            await source.close()
                        except Exception:  # etl-lint: ignore[cancellation-swallow] — best-effort close of an already-broken connection
                            pass
                        source = None
                    continue
                registry.gauge_set(
                    ETL_APPLY_LOOP_END_TO_END_LAG_BYTES,
                    max(0, int(wal) - int(loop.state.durable_lsn)))
                registry.gauge_set(
                    ETL_APPLY_LOOP_EFFECTIVE_FLUSH_LAG_BYTES,
                    max(0, int(wal) - int(loop.state.last_status_flush_lsn)))
        finally:
            if source is not None:
                await source.close()

    async def _get_start_lsn(self, source: ReplicationSource) -> Lsn:
        """max(durable progress, slot confirmed_flush); create slot if
        missing; invalidation policy (worker.rs:366-527)."""
        slot = await source.get_slot(self.slot_name)
        if slot is not None and slot.invalidated:
            from ..telemetry.metrics import (ETL_SLOT_INVALIDATIONS_TOTAL,
                                             registry)

            registry.counter_inc(ETL_SLOT_INVALIDATIONS_TOTAL)
            behavior = self.config.invalidated_slot_behavior
            if behavior is InvalidatedSlotBehavior.ERROR:
                raise EtlError(
                    ErrorKind.SLOT_INVALIDATED,
                    f"slot {self.slot_name} invalidated; configure "
                    f"invalidated_slot_behavior=recreate_and_resync to "
                    f"rebuild")
            # recreate + full resync: reset every table and start fresh
            await source.delete_slot(self.slot_name)
            for tid in await source.get_publication_table_ids(
                    self.config.publication_name):
                await self.store.reset_table(tid)
            await self.store.delete_durable_progress(self.slot_name)
            slot = None
        if slot is None:
            created = await source.create_slot(self.slot_name)
            slot_flush = created.consistent_point
        else:
            slot_flush = slot.confirmed_flush_lsn
        durable = await self.store.get_durable_progress(self.slot_name)
        start = max(durable or Lsn.ZERO, slot_flush)
        sink = await self._recover_sink_high_water()
        if sink is not None and sink.commit_end_lsn:
            sink_lsn = Lsn(sink.commit_end_lsn)
            if sink_lsn > start:
                # sink is ahead of the progress store: the crash landed
                # between the committed write and the progress commit.
                # Bootstrap the store from the sink's own record so the
                # re-stream window starts past what the sink already
                # holds (exactly-once recovery, docs/destinations.md)
                logger.info(
                    "sink high-water %s ahead of durable progress %s; "
                    "bootstrapping store and resuming past it",
                    sink_lsn, start)
                await self.store.update_durable_progress(
                    self.slot_name, sink_lsn)
                start = sink_lsn
        return start

    async def _recover_sink_high_water(self):
        """Query a transactional sink's recovery high-water mark
        (`Destination.recover_high_water`), bounded and retried.

        Failure policy (exactly-once satellite): each attempt is bounded
        by `destination_op_timeout_s`, failures surface as typed
        `EtlError`s through the worker-scoped `RetryPolicy`, and
        exhausting it DEGRADES — loud warning + fallback counter, return
        None, resume from the progress store (blind at-least-once
        re-stream; the sink's own coordinate dedup still holds dup==0).
        `Pipeline.start` must never wedge on a sink that cannot answer
        its recovery query."""
        if not self.destination.supports_transactional_commit():
            return None
        from ..telemetry.metrics import (
            ETL_EXACTLY_ONCE_RECOVERIES_TOTAL,
            ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL, registry)

        timeout_s = self.config.destination_op_timeout_s

        async def _one_attempt():
            try:
                if timeout_s > 0:
                    return await asyncio.wait_for(
                        self.destination.recover_high_water(), timeout_s)
                return await self.destination.recover_high_water()
            except asyncio.TimeoutError:
                raise EtlError(
                    ErrorKind.TIMEOUT,
                    f"sink recovery query exceeded {timeout_s:.1f}s")
            except EtlError:
                raise
            except asyncio.CancelledError:
                raise
            except Exception as e:  # untyped sink client failure
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"sink recovery query failed: {e!r}")

        policy = RetryPolicy.from_config(self.config.apply_retry)
        try:
            rng = await policy.execute(_one_attempt)
        except EtlError as e:
            reason = "timeout" if e.kind is ErrorKind.TIMEOUT else "error"
            registry.counter_inc(
                ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL,
                labels={"reason": reason})
            logger.warning(
                "sink recovery high-water query failed after retries "
                "(%s); DEGRADING to blind re-stream from the progress "
                "store — at-least-once window reopens until the sink "
                "answers again (sink-side dedup still bounds "
                "duplicates): %s", reason, e)
            return None
        if rng is not None:
            registry.counter_inc(ETL_EXACTLY_ONCE_RECOVERIES_TOTAL)
        return rng
