"""Re-export shim: the table state machine lives in models (model-level,
no runtime dependencies) — this path is kept for discoverability next to
the workers that drive the transitions."""

from ..models.table_state import (PERSISTENT_STATES, TableState,
                                  TableStateType)

__all__ = ["PERSISTENT_STATES", "TableState", "TableStateType"]
