"""Poison-pill isolation: batch bisection, per-table quarantine, and the
durable dead-letter protocol on the apply path.

Before this module, a single undeliverable row took the whole shard
down: a PERMANENT destination error (schema drift, unencodable value,
destination 4xx → `models.errors.POISON_KINDS`) exhausted `RetryPolicy`
at the worker level and the apply worker died, halting replication for
every table the shard owns. The streaming CDC path had no isolation
boundary between one poisoned row and the pipeline.

`PoisonIsolator.submit(events)` is that boundary. It sits inside the
ack-window write task (the apply loop's flush `submit()` calls it
instead of `Destination.write_event_batches` directly) and guarantees:

  fast path     — one extra set-membership check per flush when nothing
                  is quarantined and the write succeeds;
  quarantine    — events of quarantined tables bypass the destination
                  and park straight on the dead-letter surface (counted,
                  durable) while every other table's events deliver;
  isolation     — a write failing with a poison kind (and only a poison
                  kind: transient/breaker failures re-raise into the
                  normal worker-retry path, destination-down NEVER
                  bisects) is split by table, each failing table's batch
                  is binary-bisected down to the poison row(s) in
                  O(log batch) probe writes, the healthy complement
                  delivers in WAL order, and the poison rows append to
                  the DLQ keyed by their WAL coordinates (idempotent
                  under crash-and-re-stream);
  budget        — a table exceeding `PoisonConfig.budget_rows`
                  dead-lettered rows inside a sliding window transitions
                  active → quarantined: its remaining rows park WITHOUT
                  further probe writes (the budget bounds isolation work)
                  and the quarantine record persists so a restarted
                  worker parks the table from its first flush.

The zero-loss invariant becomes `delivered ∪ dead-lettered == committed
truth`, enforced by the chaos invariant checker (`python -m
etl_tpu.chaos --dlq`) together with the bisection write bound
(≤ 2·log₂(batch) probe writes per poison row).

Durability ordering: a flush only acks durable after its healthy rows
are destination-durable AND its poison/parked rows are store-durable
(`STORE_DLQ_COMMIT` fires inside the append). A hard kill anywhere in
between re-streams the whole flush from durable progress; re-isolated
rows UPSERT on their WAL key (attempts += 1), re-delivered healthy rows
ride the normal at-least-once dup budget.

This module — like runtime/ack_window.py — is a sanctioned owner of
inline durability waits (etl-lint rule 17 applies to @flush_path
callers, not here): the probe writes ARE the durability protocol.
"""

from __future__ import annotations

import asyncio
import logging
import math
import re
import time
from collections import deque

from ..config.pipeline import PipelineConfig
from ..destinations.base import (CommitRange, WriteAck,
                                 expand_batch_events)
from ..models.errors import ErrorKind, EtlError, is_poison_error
from ..models.event import (DecodedBatchEvent, DeleteEvent, InsertEvent,
                            RelationEvent, TruncateEvent, UpdateEvent)
from ..models.schema import TableId
from ..store.base import DeadLetterEntry, QuarantineRecord
from ..telemetry.metrics import (ETL_DLQ_ENTRIES_TOTAL,
                                 ETL_POISON_BISECTION_WRITES_TOTAL,
                                 ETL_POISON_ISOLATIONS_TOTAL,
                                 ETL_QUARANTINE_PARKED_EVENTS_TOTAL,
                                 ETL_QUARANTINED_TABLES, registry)
from . import failpoints

logger = logging.getLogger("etl_tpu.poison")

_ROW_EVENTS = (InsertEvent, UpdateEvent, DeleteEvent)

#: per-isolation trace records (appended by every `_isolate` run):
#: {"rows", "tables", "probe_writes", "control_probes", "poison_rows",
#: "quarantined"} — the chaos scenario and bench gate read these to
#: assert the bisection bound (≤ 2·log₂(batch) probes per poison row +
#: one probe per table; control-event barrier writes are counted
#: separately, outside the bound). Bounded: a long-running worker
#: facing a poison trickle must not grow this without limit.
ISOLATION_TRACE: "deque[dict]" = deque(maxlen=256)


def reset_isolation_trace() -> None:
    ISOLATION_TRACE.clear()


#: cap on the stored per-entry column attribution (comma-joined names)
_COLUMNS_MAX_CHARS = 200


def attribute_poison_columns(detail: str, schema) -> str:
    """Best-effort column attribution for an isolated poison row: the
    replicated column names that appear as whole tokens in the
    classified error detail (destinations name the offending column in
    schema-drift / unencodable-value rejections), comma-joined in
    schema order. Empty when the detail names no column — attribution
    is a hint for `dlq inspect`, never load-bearing."""
    if not detail:
        return ""
    hits = []
    for col in schema.replicated_columns:
        name = col.name
        if not name:
            continue
        if re.search(r"(?<![A-Za-z0-9_])" + re.escape(name)
                     + r"(?![A-Za-z0-9_])", detail):
            hits.append(name)
    return ",".join(hits)[:_COLUMNS_MAX_CHARS]


def bisection_bound(rows: int, tables: int, poison_rows: int) -> int:
    """The probe-write budget the protocol must stay under for one
    isolation: one split probe per table in the flush plus 2·⌈log₂ n⌉
    probes per poison row found (each bisection level retries both
    halves of one failing batch). Quarantine parking consumes NO
    probes, so a budget trip only ever tightens the real count."""
    if rows <= 0:
        return tables
    levels = max(1, math.ceil(math.log2(max(2, rows))))
    return tables + max(1, poison_rows) * 2 * levels


class _IsolationAborted(Exception):
    """A probe write failed with a NON-poison error (destination sick,
    breaker opened, store down): isolation stops and the original
    transient error surfaces into the worker-retry path."""

    def __init__(self, cause: BaseException):
        self.cause = cause


class _PoisonGuardedAck:
    """Wraps a deferred (accepted) destination ack so a write error that
    only surfaces at DURABILITY time — BigQuery resolves append failures
    through the ack future — still hits the isolation boundary. The ack
    window awaits this inside its own write task, so overlap across the
    window is preserved; isolations themselves serialize on the
    isolator's lock like any synchronous-failure isolation. On a poison
    failure, `wait_durable` runs the full protocol and then RESOLVES
    (the flush is durable: healthy rows delivered or re-delivered,
    poison rows on the dead-letter store); every other failure
    propagates into the normal worker-retry path."""

    __slots__ = ("_inner", "_events", "_isolator")

    def __init__(self, inner, events, isolator: "PoisonIsolator"):
        self._inner = inner
        self._events = events
        self._isolator = isolator

    @property
    def is_durable(self) -> bool:
        return self._inner.is_durable

    async def wait_durable(self) -> None:
        try:
            await self._inner.wait_durable()
        except EtlError as e:
            await self._isolator._handle_poison(self._events, e)
        finally:
            self._events = None  # the payload is consumed either way


def _settled_ack() -> WriteAck:
    """A durable ack constructed WITHOUT the destination-write failpoint
    (nothing was written by the destination for a fully-parked flush —
    chaos must not count a phantom destination write)."""
    fut = asyncio.get_event_loop().create_future()
    fut.set_result(None)
    return WriteAck(fut)


def _event_table(ev) -> "TableId | None":
    """The table a flush event belongs to, None for table-less controls
    (Begin/Commit)."""
    if isinstance(ev, (DecodedBatchEvent, RelationEvent, *_ROW_EVENTS)):
        return ev.schema.id
    sch = getattr(ev, "table_id", None)
    return sch


class PoisonIsolator:
    """One apply loop's isolation boundary. Created per ApplyLoop (apply
    context only — initial sync keeps the reference's per-table error
    states), shares the loop's store and (wrapped) destination."""

    def __init__(self, *, store, destination, config: PipelineConfig):
        self.store = store
        self.destination = destination
        self.config = config.poison
        # quarantined-table set: loaded from the store on first use so a
        # restarted worker parks from its very first flush; updated by
        # this isolator on budget trips. External lifts (the operator
        # CLI's `unquarantine`) are adopted LIVE: submit() re-reads the
        # store every `quarantine_poll_s` and swaps in the fresh set, so
        # a lifted table resumes streaming without a worker restart.
        self._quarantined: "set[TableId] | None" = None
        self._records: dict[TableId, QuarantineRecord] = {}
        self._last_poll = time.monotonic()
        # sliding poison budget per table: dead-letter timestamps
        self._poison_times: "dict[TableId, deque[float]]" = {}
        # serialize isolations across overlapping ack-window tasks: two
        # concurrent bisections would interleave probe writes and the
        # trace/budget accounting
        self._lock = asyncio.Lock()
        self.stats = {"isolations": 0, "poison_rows": 0,
                      "parked_events": 0, "probe_writes": 0,
                      "quarantined_tables": 0}

    # -- quarantine state -----------------------------------------------------

    async def _ensure_loaded(self) -> None:
        if self._quarantined is not None:
            return
        try:
            self._records = dict(await self.store.get_quarantined_tables())
        except EtlError:
            self._records = {}
        self._quarantined = set(self._records)
        self._last_poll = time.monotonic()
        registry.gauge_set(ETL_QUARANTINED_TABLES, len(self._quarantined))

    async def _maybe_refresh(self) -> None:
        """Live quarantine-lift adoption: every `quarantine_poll_s` the
        flush path re-reads the store's quarantine records and swaps in
        the fresh set, so an operator `unquarantine` (another process)
        takes effect without a worker restart. Serialized on the
        isolation lock — a budget trip persists its record BEFORE the
        local set mutates, so a refresh that waited out an isolation
        always reads at-least-as-current state. A store read failure
        keeps the current set and retries next poll (never fails a
        flush over a poll)."""
        poll = getattr(self.config, "quarantine_poll_s", 0.0)
        if not poll or self._quarantined is None:
            return
        if time.monotonic() - self._last_poll < poll:
            return
        async with self._lock:
            if time.monotonic() - self._last_poll < poll:
                return  # a concurrent submit refreshed while we waited
            self._last_poll = time.monotonic()
            try:
                fresh = dict(await self.store.get_quarantined_tables())
            except EtlError:
                return
            lifted = set(self._quarantined) - set(fresh)
            adopted = set(fresh) - set(self._quarantined)
            self._records = fresh
            self._quarantined = set(fresh)
            registry.gauge_set(ETL_QUARANTINED_TABLES,
                               len(self._quarantined))
            if lifted:
                logger.info(
                    "quarantine lift adopted live for table(s) %s: "
                    "their events stream to the destination again",
                    sorted(lifted))
            if adopted:
                logger.warning(
                    "externally-quarantined table(s) %s adopted from "
                    "the store", sorted(adopted))

    def quarantined_tables(self) -> "set[TableId]":
        return set(self._quarantined or ())

    async def _quarantine(self, table_id: TableId, since_lsn: int,
                          reason: str) -> None:
        assert self._quarantined is not None
        if table_id in self._quarantined:
            return
        record = QuarantineRecord(
            table_id=table_id, since_lsn=since_lsn,
            poison_rows=len(self._poison_times.get(table_id, ())),
            reason=reason[:self.config.max_detail_chars])
        await self.store.set_table_quarantine(table_id, record)
        self._quarantined.add(table_id)
        self._records[table_id] = record
        self.stats["quarantined_tables"] += 1
        registry.gauge_set(ETL_QUARANTINED_TABLES, len(self._quarantined))
        logger.error(
            "table %d QUARANTINED after %d poison rows inside %.0fs "
            "(budget %d): its events now park on the dead-letter store "
            "while other tables keep replicating; replay + unquarantine "
            "via `python -m etl_tpu.dlq` (%s)",
            table_id, record.poison_rows, self.config.window_s,
            self.config.budget_rows, reason[:200])

    def _budget_tripped(self, table_id: TableId) -> bool:
        times = self._poison_times.get(table_id)
        if not times:
            return False
        horizon = time.monotonic() - self.config.window_s
        while times and times[0] < horizon:
            times.popleft()
        return len(times) >= self.config.budget_rows

    def _note_poison(self, table_id: TableId) -> None:
        self._poison_times.setdefault(table_id, deque()).append(
            time.monotonic())

    # -- breaker integration --------------------------------------------------

    def _breaker_open(self) -> bool:
        from ..supervision.breaker import breaker_is_open

        return breaker_is_open(self.destination)

    # -- dead-letter appends --------------------------------------------------

    async def _dead_letter(self, events, error: "EtlError | None",
                           reason: str, columns: str = "") -> int:
        """Append per-row events to the DLQ (idempotent keyed upsert).
        Returns the number appended. A store that cannot persist dead
        letters surfaces as _IsolationAborted carrying the ORIGINAL
        poison error — pre-PR worker behavior, never silent row loss."""
        from ..dlq.codec import encode_row_event

        entries = []
        # parked rows are labeled `quarantine` regardless of the
        # triggering error: most of them are HEALTHY rows the quarantine
        # owns, and the operator CLI must distinguish them from rows a
        # bisection actually proved poison
        kind_name = reason if reason == "quarantine" or error is None \
            else error.kind.name
        detail = (error.detail if error is not None else reason)
        detail = detail[:self.config.max_detail_chars]
        for ev in events:
            change, payload = encode_row_event(ev)
            entries.append(DeadLetterEntry(
                entry_id=0, table_id=ev.schema.id,
                commit_lsn=int(ev.commit_lsn), tx_ordinal=ev.tx_ordinal,
                change_type=change, payload=payload,
                error_kind=kind_name, detail=detail, columns=columns))
        if not entries:
            return 0
        try:
            await self.store.append_dead_letters(entries)
        except EtlError as e:
            if e.kind is ErrorKind.STATE_STORE_FAILED \
                    and "does not persist" in e.detail:
                # store has no DLQ surface: isolation is impossible —
                # fail the flush with the original poison error (the
                # pre-isolation behavior) rather than dropping rows
                raise _IsolationAborted(error or e)
            raise _IsolationAborted(e)
        registry.counter_inc(ETL_DLQ_ENTRIES_TOTAL, len(entries),
                             labels={"reason": reason})
        return len(entries)

    # -- probe writes ---------------------------------------------------------

    async def _probe_write(self, events, trace: dict, *,
                           control: bool = False) -> None:
        """One bisection probe: write a candidate sub-batch and wait its
        durability. Raises EtlError(poison kind) when the sub-batch is
        (still) poisoned, _IsolationAborted on anything else. Control-
        event barrier writes (`control=True`) are accounted separately —
        they are WAL-order bookkeeping, not bisection cost, and must not
        eat into the 2·log₂(batch) bound the chaos gate asserts."""
        if self._breaker_open():
            # the destination went down mid-isolation: stop bisecting
            # immediately — the worker's backoff (not probe writes) is
            # the backpressure against a sick destination
            raise _IsolationAborted(EtlError(
                ErrorKind.DESTINATION_UNAVAILABLE,
                "circuit breaker opened during poison isolation; "
                "re-streaming from durable progress"))
        failpoints.fail_point(failpoints.POISON_BISECT)
        await failpoints.stall_point(failpoints.POISON_BISECT)
        if control:
            trace["control_probes"] += 1
        else:
            trace["probe_writes"] += 1
            self.stats["probe_writes"] += 1
            registry.counter_inc(ETL_POISON_BISECTION_WRITES_TOTAL)
        try:
            batch = list(events)
            if self.destination.supports_transactional_commit():
                # per-probe sub-range: the healthy complement of a
                # bisection must stay coordinated (WAL order makes the
                # sink's high-water advance monotone across probes),
                # while a failing probe lands nothing — so a later DLQ
                # replay of the isolated row deduplicates by exact key,
                # not against a high-water this probe never earned
                rng = CommitRange.from_events(batch)
                if rng is not None:
                    ack = await self.destination \
                        .write_event_batches_committed(batch, rng)
                else:
                    ack = await self.destination.write_event_batches(batch)
            else:
                ack = await self.destination.write_event_batches(batch)
            if ack is not None:
                await ack.wait_durable()
        except EtlError as e:
            if is_poison_error(e):
                raise
            raise _IsolationAborted(e)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            raise _IsolationAborted(e)

    async def _bisect(self, table_id: TableId, events: list,
                      error: EtlError, trace: dict) -> None:
        """Binary-bisect one table's failing per-row batch down to the
        poison row(s): halves that deliver are done, failing halves
        recurse, a failing singleton IS a poison row → dead-letter it.
        WAL order within the table is preserved (left half probes before
        right). O(2·log₂ n) probes per poison row."""
        if self._budget_tripped(table_id):
            # budget exhausted mid-bisection: park the remainder without
            # further probes — the budget bounds isolation work
            await self._quarantine(
                table_id, int(events[0].commit_lsn),
                f"poison budget exceeded during isolation: {error}")
            n = await self._dead_letter(events, error, "quarantine")
            trace["parked"] += n
            self.stats["parked_events"] += n
            registry.counter_inc(ETL_QUARANTINE_PARKED_EVENTS_TOTAL, n)
            return
        if len(events) == 1:
            ev = events[0]
            self._note_poison(table_id)
            await self._dead_letter(
                [ev], error, "poison",
                columns=attribute_poison_columns(error.detail or "",
                                                 ev.schema))
            trace["poison_rows"] += 1
            self.stats["poison_rows"] += 1
            logger.warning(
                "poison row isolated: table %d commit_lsn %s ordinal %d "
                "(%s) parked on the dead-letter store",
                table_id, ev.commit_lsn, ev.tx_ordinal, error.kind.name)
            if self._budget_tripped(table_id):
                await self._quarantine(
                    table_id, int(ev.commit_lsn),
                    f"poison budget exceeded: {error}")
            return
        mid = len(events) // 2
        for half in (events[:mid], events[mid:]):
            try:
                await self._probe_write(half, trace)
            except EtlError as e:
                await self._bisect(table_id, half, e, trace)

    async def _isolate(self, events, error: EtlError) -> None:
        """The isolation protocol over one failed flush: expand to
        per-row events (WAL order preserved), split by table within
        control-event-delimited segments, probe each table once, bisect
        the failing ones, park everything a quarantine owns."""
        registry.counter_inc(ETL_POISON_ISOLATIONS_TOTAL)
        self.stats["isolations"] += 1
        expanded = expand_batch_events(list(events))
        n_rows = sum(1 for e in expanded if isinstance(e, _ROW_EVENTS))
        trace = {"rows": n_rows, "tables": 0, "probe_writes": 0,
                 "control_probes": 0, "poison_rows": 0, "parked": 0,
                 "quarantined": []}
        before_q = set(self._quarantined or ())
        logger.warning(
            "flush failed with permanent %s over %d rows: entering "
            "poison isolation (bisection bound: see docs/dead-letter.md)",
            error.kind.name, n_rows)
        try:
            segment: "dict[TableId, list]" = {}
            seg_order: list[TableId] = []

            async def flush_segment() -> None:
                for tid in seg_order:
                    rows = segment[tid]
                    trace["tables"] += 1
                    if self._budget_tripped(tid) \
                            or tid in (self._quarantined or ()):
                        await self._quarantine(
                            tid, int(rows[0].commit_lsn),
                            f"poison budget exceeded: {error}")
                        n = await self._dead_letter(rows, error,
                                                    "quarantine")
                        trace["parked"] += n
                        self.stats["parked_events"] += n
                        registry.counter_inc(
                            ETL_QUARANTINE_PARKED_EVENTS_TOTAL, n)
                        continue
                    try:
                        await self._probe_write(rows, trace)
                    except EtlError as e:
                        await self._bisect(tid, rows, e, trace)
                segment.clear()
                seg_order.clear()

            for ev in expanded:
                if isinstance(ev, _ROW_EVENTS):
                    tid = ev.schema.id
                    if tid not in segment:
                        segment[tid] = []
                        seg_order.append(tid)
                    segment[tid].append(ev)
                    continue
                # control event: a WAL-order barrier — deliver every
                # pending row segment first, then the control alone. A
                # control write that fails poison cannot be bisected
                # further; it aborts isolation with the original error.
                await flush_segment()
                try:
                    await self._probe_write([ev], trace, control=True)
                except EtlError as e:
                    raise _IsolationAborted(e)
            await flush_segment()
        except _IsolationAborted as a:
            trace["aborted"] = repr(a.cause)
            ISOLATION_TRACE.append(trace)
            cause = a.cause
            raise cause if isinstance(cause, BaseException) else EtlError(
                ErrorKind.DESTINATION_FAILED, str(cause))
        trace["quarantined"] = sorted(set(self._quarantined or ())
                                      - before_q)
        ISOLATION_TRACE.append(trace)

    # -- the flush seam -------------------------------------------------------

    async def _handle_poison(self, events, e: EtlError) -> WriteAck:
        """The single poison dispatch point for BOTH failure surfaces —
        a write call raising synchronously, and a deferred (accepted)
        ack resolving its error at durability time. Re-raises anything
        that must keep worker-retry semantics; isolates otherwise and
        returns a settled ack."""
        if not is_poison_error(e):
            # transient / ambiguous failures keep the existing
            # worker-retry semantics: backoff + re-stream
            raise e
        if self._breaker_open():
            # destination-down never bisects — but the poison error
            # itself must not surface either: its MANUAL directive
            # would park the worker permanently for a row that WILL
            # isolate once the breaker closes. Re-classify as the
            # breaker's own (worker-TIMED) kind; the re-streamed
            # flush isolates after the backoff.
            raise EtlError(
                ErrorKind.DESTINATION_UNAVAILABLE,
                "circuit breaker open at poison classification; "
                "deferring isolation to the re-streamed flush") from e
        async with self._lock:
            # _isolate owns the _IsolationAborted unwrap: any abort
            # (transient probe failure, breaker opening mid-isolation,
            # a DLQ-less store) re-raises its cause from there
            await self._isolate(events, e)
        return _settled_ack()

    async def submit(self, events,
                     commit=None) -> "WriteAck | None":
        """The apply loop's flush `submit()` body. Fast path: one
        membership check + the destination write. Slow paths: park
        quarantined tables' events, isolate on a poison failure —
        whether it surfaces at the write call or (deferred-ack
        destinations: BigQuery transfers append errors to the ack
        future) at durability time, via the guarded ack.

        `commit` (a `CommitRange`, exactly-once pipelines only) rides
        the fast path through `write_event_batches_committed` so the
        sink lands data + coordinate range atomically. Isolation probe
        writes re-derive their own sub-ranges (`_probe_write`): the
        flush-level range covers rows a bisection may park, and
        advancing the sink's high-water past a parked row would make
        its DLQ replay look like a duplicate."""
        await self._ensure_loaded()
        await self._maybe_refresh()
        if self._quarantined:
            healthy, parked = [], []
            for ev in events:
                tid = _event_table(ev)
                if tid in self._quarantined \
                        and isinstance(ev, (DecodedBatchEvent,
                                            *_ROW_EVENTS)):
                    parked.append(ev)
                elif isinstance(ev, TruncateEvent) and all(
                        s.id in self._quarantined for s in ev.schemas):
                    # a truncate of ONLY quarantined tables would clear
                    # destination rows the quarantine still owes; park
                    # it as a log-only drop (content-independent, the
                    # replay runbook re-syncs the table anyway)
                    logger.warning("dropping TRUNCATE of quarantined "
                                   "table(s) %s",
                                   [s.id for s in ev.schemas])
                else:
                    healthy.append(ev)
            if parked:
                rows = expand_batch_events(parked)
                rows = [e for e in rows if isinstance(e, _ROW_EVENTS)]
                n = await self._park_rows(rows)
                self.stats["parked_events"] += n
            events = healthy
        if not events:
            return _settled_ack()
        events = list(events)
        try:
            if commit is not None:
                ack = await self.destination.write_event_batches_committed(
                    events, commit)
            else:
                ack = await self.destination.write_event_batches(events)
        except EtlError as e:
            return await self._handle_poison(events, e)
        if ack is None or ack.is_durable:
            return ack
        # deferred (accepted) ack: the write's errors may only surface
        # at durability time — extend the isolation boundary over the
        # wait, or a poison rejection there would reach the worker
        # unisolated (and, being MANUAL, park the whole shard)
        return _PoisonGuardedAck(ack, events, self)

    async def _park_rows(self, rows) -> int:
        try:
            n = await self._dead_letter(rows, None, "quarantine")
        except _IsolationAborted as a:
            raise a.cause
        if n:
            registry.counter_inc(ETL_QUARANTINE_PARKED_EVENTS_TOTAL, n)
            # keep the persisted record's parked counter current so the
            # operator CLI shows how much the table owes on replay.
            # RECOMPUTED from the store, not incremented: an
            # at-least-once re-stream re-parks the same rows (the DLQ
            # upsert absorbs them by WAL key) and an increment would
            # double-count them on the operator-facing record
            assert self._quarantined is not None
            for tid in {r.schema.id for r in rows}:
                rec = self._records.get(tid)
                if rec is None:
                    continue
                from dataclasses import replace

                parked = await self.store.list_dead_letters(
                    table_id=tid, status=None)
                rec = replace(rec, parked_events=sum(
                    1 for p in parked if p.error_kind == "quarantine"))
                self._records[tid] = rec
                await self.store.set_table_quarantine(tid, rec)
        return n
