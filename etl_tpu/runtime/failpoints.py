"""Re-export shim: the failpoint registry moved to `etl_tpu.chaos`.

Reference parity: crates/etl/src/failpoints.rs:14-54 — the seven named
sites live on under chaos/failpoints.py alongside the chaos subsystem's
expanded injection surface. Runtime call sites and existing tests keep
importing from here unchanged.
"""

from __future__ import annotations

from ..chaos.failpoints import (  # noqa: F401
    AFTER_FINISHED_COPY, ALL_SITES, APPLY_FRAME_READ, ASSEMBLER_SEAL,
    ASYNC_STALL_SITES, BEFORE_SLOT_CREATION,
    BEFORE_STREAMING, CHAOS_SITES, COPY_PARTITION_END, COPY_PARTITION_START,
    DESTINATION_FLUSH, DESTINATION_WRITE, DURING_COPY, ENGINE_DEVICE_OOM,
    ON_PROGRESS_STORE, ON_SCHEMA_CLEANUP, ON_STATUS_UPDATE, PIPELINE_DISPATCH,
    PIPELINE_FETCH, PIPELINE_PACK, POISON_BISECT, REFERENCE_SITES,
    STORE_DLQ_COMMIT, STORE_PROGRESS_COMMIT,
    STORE_SCHEMA_COMMIT, STORE_STATE_COMMIT, arm, arm_error, arm_stall,
    armed_sites, disarm, disarm_all, fail_point, release_stalls, scope,
    stall_point, stalls_armed)
