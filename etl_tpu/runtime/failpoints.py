"""Failpoints: named crash/error injection sites for restart testing.

Reference parity: crates/etl/src/failpoints.rs:14-54 — seven named sites
with parameterized retry-kind errors, used inside the apply loop and the
table-sync flow; driven by the failpoint test suite (SURVEY §4.3). Always
compiled in (unlike the reference's `failpoints` feature, the registry is
a no-op dict lookup when nothing is armed).
"""

from __future__ import annotations

from typing import Callable

from ..models.errors import ErrorKind, EtlError

# the reference's named sites (failpoints.rs:14-21)
BEFORE_SLOT_CREATION = "table_sync.before_slot_creation"
DURING_COPY = "table_sync.during_copy"
AFTER_FINISHED_COPY = "table_sync.after_finished_copy"
BEFORE_STREAMING = "table_sync.before_streaming"
ON_STATUS_UPDATE = "apply.on_status_update"
ON_PROGRESS_STORE = "apply.on_progress_store"
ON_SCHEMA_CLEANUP = "apply.on_schema_cleanup"

_armed: dict[str, Callable[[], None]] = {}


def arm(name: str, action: Callable[[], None]) -> None:
    """Arm a failpoint with an action (usually raising)."""
    _armed[name] = action


def arm_error(name: str, kind: ErrorKind = ErrorKind.SOURCE_IO,
              times: int = 1, detail: str = "") -> None:
    """Arm to raise an EtlError of `kind` the next `times` hits."""
    remaining = [times]

    def action() -> None:
        if remaining[0] > 0:
            remaining[0] -= 1
            raise EtlError(kind, detail or f"failpoint {name}")
        disarm(name)

    arm(name, action)


def disarm(name: str) -> None:
    _armed.pop(name, None)


def disarm_all() -> None:
    _armed.clear()


def fail_point(name: str) -> None:
    """Hit a failpoint (no-op unless armed)."""
    action = _armed.get(name)
    if action is not None:
        action()
