"""Bounded destination-ack window: overlap N in-flight writes with
contiguous-prefix durability.

Every upstream stage is batched and overlapped (decode pipeline,
columnar egress, mesh sharding), but a one-in-flight apply loop caps the
whole pipeline at `batch_size / ack_round-trip` on any destination with
real ack latency. The `WriteAck` seam already separates submission from
durability — this module exploits it:

  - the apply loop keeps dispatching flushes IN WAL ORDER while up to
    `BatchConfig.write_window` earlier acks are still pending (bytes-
    capped by `write_window_max_bytes`; the memory monitor shrinks the
    window to 1 under pressure, same as the decode pipeline);
  - submissions are CHAINED: write N+1's `write_event_batches` call
    starts only after write N's submission returned its ack — the
    destination sees batches in WAL order, only the durability waits
    overlap (the ack-pipelining contract, docs/destinations.md);
  - durable progress advances only over the CONTIGUOUS ACKED PREFIX:
    an out-of-order ack completion is held until everything before it
    is durable, so the progress store — and the replication slot —
    never claim durability past an unacked write;
  - a mid-window failure fails the worker, which re-streams from
    durable progress: at-least-once preserved, and the bounded-dup
    budget grows by at most the window size (the batches that were in
    flight past the durable prefix).

THE WINDOW OWNS THE DURABILITY WAITS. Flush/dispatch paths are marked
`@flush_path` and etl-lint rule 17 (`inline-durability-wait`) forbids a
bare `await ack.wait_durable()` there — an inline wait would silently
re-serialize the pipeline to one ack round-trip per batch. This module
is the sanctioned owner (and is deliberately unmarked).

`CopyAckWindow` is the copy-path sibling: `runtime/copy.py` used to
accumulate every partition ack in an unbounded list until end-of-copy —
a huge table could hold arbitrarily many unresolved acks (and surface a
failed ack only at the partition barrier). The bounded window caps
outstanding copy acks and awaits the OLDEST first, preserving
per-partition ordering while surfacing errors as soon as the window
turns over.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Awaitable, Callable

from ..destinations.base import WriteAck
from ..models.errors import ErrorKind, EtlError
from ..telemetry.metrics import (ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL,
                                 ETL_DESTINATION_ACK_IN_FLIGHT,
                                 ETL_DESTINATION_ACK_LATENCY_SECONDS,
                                 ETL_DESTINATION_ACK_OVERLAP_RATIO,
                                 ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL,
                                 ETL_EXACTLY_ONCE_HIGH_WATER_LSN,
                                 registry)


class AckEntry:
    """One dispatched flush: its write task (submission + durability
    wait), the durable watermark it covers, the transactional
    CommitRange it shipped (None on at-least-once paths), its
    accounting, and the payload events (so a hard-killed loop can
    abandon the pending decodes of entries that will never deliver)."""

    __slots__ = ("task", "commit_end_lsn", "commit_range", "n_events",
                 "nbytes", "dispatched_at", "payload")

    def __init__(self, task: asyncio.Task, commit_end_lsn, n_events: int,
                 nbytes: int, dispatched_at: float, payload=None,
                 commit_range=None):
        self.task = task
        self.commit_end_lsn = commit_end_lsn
        self.commit_range = commit_range
        self.n_events = n_events
        self.nbytes = nbytes
        self.dispatched_at = dispatched_at
        self.payload = payload


class AckWindow:
    """The apply loop's bounded write window.

    `dispatch(submit, ...)` spawns one write task per flush. Tasks chain
    their SUBMISSIONS (WAL order at the destination) and overlap their
    durability waits; `pop_ready()` consumes the contiguous completed
    prefix and reports the first failure. Capacity: at most
    `effective_limit()` entries (1 under memory pressure) and at most
    `max_bytes` of pending payload — but an empty window always accepts
    one dispatch, so a single over-budget mega batch can never deadlock.
    """

    def __init__(self, limit: int, max_bytes: int = 0,
                 pressure: "Callable[[], bool] | None" = None,
                 path: str = "apply"):
        self._limit = max(1, int(limit))
        self._max_bytes = max(0, int(max_bytes))
        self._pressure = pressure
        self._entries: "deque[AckEntry]" = deque()
        self._bytes = 0
        # tail of the submission chain: resolves True when that entry's
        # write_event_batches returned (ack obtained), False when it
        # failed/was cancelled — the successor refuses to submit after a
        # failed predecessor so the destination never sees a gap
        self._submit_tail: "asyncio.Future[bool] | None" = None
        self._labels = {"path": path}
        # overlap accounting: busy = ≥1 in flight, overlap = ≥2
        self._last_t = time.monotonic()
        self._busy_s = 0.0
        self._overlap_s = 0.0
        # max (commit_lsn, tx_ordinal) across ACKED transactional writes:
        # monotone because submissions chain in WAL order and only the
        # contiguous durable prefix pops
        self._acked_high: "tuple[int, int] | None" = None

    @property
    def acked_high_water(self) -> "tuple[int, int] | None":
        return self._acked_high

    # -- capacity -------------------------------------------------------------

    def effective_limit(self) -> int:
        if self._pressure is not None and self._pressure():
            return 1  # drain-to-serial under memory pressure
        return self._limit

    def set_limit(self, limit: int) -> None:
        """Retarget the window depth at runtime (the fleet signal bus's
        adaptive-depth plugin drives this from the measured ack-latency
        histogram). Shrinking never cancels in-flight writes — the
        window just refuses new dispatches until it drains below the
        new depth; memory pressure still clamps to 1 regardless."""
        self._limit = max(1, int(limit))

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def is_empty(self) -> bool:
        return not self._entries

    @property
    def pending_bytes(self) -> int:
        return self._bytes

    def can_dispatch(self, nbytes: int = 0) -> bool:
        if not self._entries:
            return True  # always admit one: no byte-cap deadlock
        if len(self._entries) >= self.effective_limit():
            return False
        if self._max_bytes and self._bytes + nbytes > self._max_bytes:
            return False
        return True

    def tasks(self) -> "list[asyncio.Task]":
        return [e.task for e in self._entries]

    def any_done(self) -> bool:
        return any(e.task.done() for e in self._entries)

    def any_actionable(self) -> bool:
        """A completion the select loop can act on NOW: the HEAD entry
        finished (the contiguous prefix can advance) or any completed
        entry failed (fail fast). A successful OUT-OF-ORDER completion
        is deliberately not actionable — it pops only once contiguous,
        so treating it as a wake condition would spin the loop against
        pop_ready's empty result until the head ack resolves."""
        if self._entries and self._entries[0].task.done():
            return True
        return any(
            e.task.done() and (e.task.cancelled()
                               or e.task.exception() is not None)
            for e in self._entries)

    def pending_tasks(self) -> "list[asyncio.Task]":
        """Tasks still running — what the select loop waits on (a done
        task in the wait set would make asyncio.wait return immediately
        on every iteration)."""
        return [e.task for e in self._entries if not e.task.done()]

    # -- dispatch -------------------------------------------------------------

    def dispatch(self, submit: "Callable[[], Awaitable[WriteAck | None]]",
                 *, commit_end_lsn=None, n_events: int = 0,
                 nbytes: int = 0,
                 on_durable: "Callable[[], None] | None" = None,
                 payload=None, commit_range=None) -> AckEntry:
        """Start one write: `submit()` performs the destination call and
        returns its ack (None for an event-less commit-boundary flush).
        The window serializes submissions in dispatch order and owns the
        durability wait; `on_durable` runs after the ack resolves (egress
        accounting rides durable acks). `commit_range` is the
        transactional CommitRange the submit ships (None on at-least-once
        paths): because submissions chain in WAL order and pops consume
        only the contiguous durable prefix, the acked ranges advance
        monotonically — `acked_high_water` exposes the max, the
        coordinate a restart's sink-side recovery should agree with."""
        prev = self._submit_tail
        loop = asyncio.get_event_loop()
        submitted: "asyncio.Future[bool]" = loop.create_future()
        self._submit_tail = submitted
        t0 = time.monotonic()

        async def run() -> None:
            ack = None
            try:
                if prev is not None and not await prev:
                    raise EtlError(
                        ErrorKind.DESTINATION_FAILED,
                        "an earlier write in the ack window failed to "
                        "submit; this batch re-streams from durable "
                        "progress")
                ack = await submit()
            except BaseException:
                if not submitted.done():
                    submitted.set_result(False)
                raise
            if not submitted.done():
                submitted.set_result(True)
            if ack is not None:
                await ack.wait_durable()
                registry.histogram_observe(
                    ETL_DESTINATION_ACK_LATENCY_SECONDS,
                    time.monotonic() - t0, labels=self._labels)
            if on_durable is not None:
                on_durable()

        self._tick()
        entry = AckEntry(asyncio.ensure_future(run()), commit_end_lsn,
                         n_events, nbytes, t0, payload,
                         commit_range=commit_range)
        self._entries.append(entry)
        self._bytes += nbytes
        self._publish()
        return entry

    @staticmethod
    def _abandon_entry(entry: AckEntry) -> None:
        for ev in entry.payload or ():
            ab = getattr(ev, "abandon", None)
            if ab is not None:
                ab()

    def abandon_payloads(self) -> None:
        """Teardown (cancel/kill path): the remaining entries will never
        deliver — abandon their events' pending decodes so pooled
        resources (staging arenas, decode-window slots, admission
        tickets) return instead of leaking with the discarded window.
        Safe after the tasks were cancelled; popped/delivered entries
        already resolved their decodes inside the destination write
        (failed pops abandoned theirs in pop_ready)."""
        for entry in self._entries:
            self._abandon_entry(entry)

    # -- completion -----------------------------------------------------------

    @staticmethod
    def _entry_tables(entry: AckEntry) -> "list[int]":
        tids = set()
        for ev in entry.payload or ():
            sch = getattr(ev, "schema", None)
            if sch is not None:
                tids.add(sch.id)
            for s in getattr(ev, "schemas", ()) or ():
                tids.add(s.id)
        return sorted(tids)

    @staticmethod
    def _entry_failure(entry: AckEntry) -> "BaseException | None":
        if entry.task.cancelled():
            return EtlError(ErrorKind.DESTINATION_FAILED,
                            "in-flight destination write cancelled")
        return entry.task.exception()

    @classmethod
    def _aggregate_failures(
            cls, failed: "list[tuple[AckEntry, BaseException]]"
    ) -> "BaseException | None":
        """EVERY completed failure in the window surfaces at once, each
        annotated with its entry's tables. A single failure raises
        unchanged (exact legacy behavior); multiple failures aggregate
        into one EtlError whose `kinds()` union all causes — so
        multi-table poison in one window reaches the isolation layer as
        ONE signal (bisected once), not across N worker restarts, and
        the retry classifier still sees every kind."""
        if not failed:
            return None
        if len(failed) == 1:
            return failed[0][1]
        causes = []
        table_note = []
        for entry, exc in failed:
            tables = cls._entry_tables(entry)
            table_note.append(f"tables {tables}")
            if isinstance(exc, EtlError):
                wrapped = EtlError(
                    exc.kind, f"{exc.detail} [tables {tables}]",
                    causes=exc.causes)
            else:
                wrapped = EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    f"{exc!r} [tables {tables}]")
            # keep the original exception (and its traceback) on the
            # chain — a repr alone makes a multi-failure window
            # materially harder to debug than the single-failure path
            wrapped.__cause__ = exc
            causes.append(wrapped)
        # kind of the FIRST failure, every other as a cause: kinds()
        # reports the full union (no synthetic UNKNOWN diluting the
        # poison/transient classification the way EtlError.many would)
        return EtlError(
            causes[0].kind,
            f"{len(causes)} window writes failed "
            f"({'; '.join(table_note)})", causes=causes[1:])

    def pop_ready(self) -> "tuple[list[AckEntry], BaseException | None]":
        """Consume the contiguous completed prefix. Returns the entries
        that completed durably (in WAL order) plus the failure signal:
        ALL completed failures — the popped head-most one and every
        completed failure DEEPER in the window — aggregated into one
        error naming each failed entry's tables (a permanent multi-table
        poison in one window surfaces whole, not one table per worker
        restart). Still-running entries before a deep failure are NOT
        popped. The caller advances durable progress over the returned
        entries BEFORE raising, so a mid-window error re-streams as
        little as possible."""
        self._tick()
        done: "list[AckEntry]" = []
        failed: "list[tuple[AckEntry, BaseException]]" = []
        while self._entries and self._entries[0].task.done():
            entry = self._entries.popleft()
            self._bytes -= entry.nbytes
            exc = self._entry_failure(entry)
            if exc is not None:
                failed.append((entry, exc))
                # the failed entry leaves the window here, so teardown's
                # abandon_payloads would miss it: release its pending
                # decodes now (the restart re-streams the events — they
                # will never be consumed from this incarnation).
                # Successors stay in the window: durable progress must
                # never advance past the failed entry's undelivered WAL,
                # so a done SUCCESSOR cannot pop either.
                self._abandon_entry(entry)
                break
            if entry.commit_range is not None \
                    and not entry.commit_range.replay:
                high = entry.commit_range.high
                if self._acked_high is None or high > self._acked_high:
                    self._acked_high = high
                    registry.gauge_set(ETL_EXACTLY_ONCE_HIGH_WATER_LSN,
                                       high[0], labels=self._labels)
            done.append(entry)
        # surface every other completed failure too (fail fast + the
        # whole poison signal): a later entry that already failed can
        # never become durable, and every entry after the first failure
        # re-streams anyway. Cancellation counts (same as the head path)
        # — any_actionable treats it as a failure, so skipping it here
        # would zero-timeout-spin the select loop against an empty pop
        for entry in self._entries:
            if not entry.task.done():
                continue
            exc = self._entry_failure(entry)
            if exc is not None:
                failed.append((entry, exc))
        self._publish()
        return done, self._aggregate_failures(failed)

    async def wait_all(self) -> None:
        """Await every in-flight task (results stay queued for
        `pop_ready`; exceptions are NOT raised here)."""
        tasks = self.tasks()
        if tasks:
            await asyncio.wait(tasks)

    # -- telemetry ------------------------------------------------------------

    def _tick(self) -> None:
        now = time.monotonic()
        dt = now - self._last_t
        self._last_t = now
        n = len(self._entries)
        if n >= 1:
            self._busy_s += dt
            registry.counter_inc(ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL,
                                 dt, labels=self._labels)
        if n >= 2:
            self._overlap_s += dt
            registry.counter_inc(ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL,
                                 dt, labels=self._labels)

    def _publish(self) -> None:
        registry.gauge_set(ETL_DESTINATION_ACK_IN_FLIGHT,
                           len(self._entries), labels=self._labels)
        if self._busy_s > 0:
            registry.gauge_set(ETL_DESTINATION_ACK_OVERLAP_RATIO,
                               self._overlap_s / self._busy_s,
                               labels=self._labels)

    def stats(self) -> dict:
        self._tick()
        return {
            "in_flight": len(self._entries),
            "pending_bytes": self._bytes,
            "busy_seconds": self._busy_s,
            "overlap_seconds": self._overlap_s,
            "overlap_ratio": (self._overlap_s / self._busy_s)
            if self._busy_s else 0.0,
        }


class CopyAckWindow:
    """Bounded FIFO of unresolved copy acks for ONE partition: `add()`
    awaits the oldest ack once the window is full (per-partition ordering
    preserved — exactly the order the old end-of-copy drain used), so a
    huge table holds at most `limit` pending acks instead of one per
    batch, and a failed ack surfaces within `limit` batches instead of at
    the partition barrier. Shrinks to 1 under memory pressure."""

    def __init__(self, limit: int,
                 pressure: "Callable[[], bool] | None" = None):
        self._limit = max(1, int(limit))
        self._pressure = pressure
        self._acks: "deque[tuple[WriteAck, float]]" = deque()
        self._labels = {"path": "copy"}

    def effective_limit(self) -> int:
        if self._pressure is not None and self._pressure():
            return 1
        return self._limit

    def set_limit(self, limit: int) -> None:
        """Runtime depth retarget (see AckWindow.set_limit): excess
        pending acks drain FIFO on the next add()."""
        self._limit = max(1, int(limit))

    def __len__(self) -> int:
        return len(self._acks)

    async def _pop_oldest(self) -> None:
        ack, t0 = self._acks.popleft()
        try:
            await ack.wait_durable()
        finally:
            registry.gauge_set(ETL_DESTINATION_ACK_IN_FLIGHT,
                               len(self._acks), labels=self._labels)
        registry.histogram_observe(ETL_DESTINATION_ACK_LATENCY_SECONDS,
                                   time.monotonic() - t0,
                                   labels=self._labels)

    async def add(self, ack: WriteAck) -> None:
        self._acks.append((ack, time.monotonic()))
        registry.gauge_set(ETL_DESTINATION_ACK_IN_FLIGHT,
                           len(self._acks), labels=self._labels)
        while len(self._acks) > self.effective_limit():
            await self._pop_oldest()

    async def drain(self) -> None:
        """The partition durability barrier (reference mod.rs:360-378):
        every remaining ack must resolve before copy progress counts."""
        while self._acks:
            await self._pop_oldest()
