"""The apply loop — heart of the replication runtime.

One loop type shared by the apply worker and table-sync workers via a
worker-context object (reference `ApplyLoop` + `WorkerContext`,
crates/etl/src/replication/apply.rs:215,1048). Responsibilities:

  - event-driven select with explicit priorities (apply.rs:1280-1336):
    shutdown > in-flight flush result > batch deadline > new WAL message
    > proactive keepalive;
  - decode pgoutput messages into typed events (via EventAssembler — CPU
    per-tuple or TPU batched decode);
  - batch events by size-hint bytes + fill deadline; dispatch flushes in
    WAL order through a bounded write window (runtime/ack_window.py) —
    up to `BatchConfig.write_window` destination writes overlap their
    ack round-trips (the reference dispatches at most ONE in-flight
    `write_events`, apply.rs:1956-2023; the window generalizes it and
    window=1 reproduces it exactly);
  - advance durable progress only over the CONTIGUOUS ACKED PREFIX of
    the window, at commit boundaries (apply.rs:2665-2719), and send
    standby status updates with the effective flush LSN (the
    ack/flow-control channel, apply.rs:1575);
  - drive the table-sync handoff state machine at commit/flush/idle points
    (apply.rs:2874-3441) — the restart-window reasoning from
    apply.rs:2907-2929 applies: Catchup is set only in memory, so a crash
    between SyncWait and SyncDone re-runs the wait, which is safe;
  - handle DDL logical messages → versioned schema store (apply.rs:2160).

Exit intents (apply.rs:139): PAUSE (shutdown; resumable) or COMPLETE
(table-sync context reached its catchup target).
"""

from __future__ import annotations

import asyncio
import enum
import time
from dataclasses import dataclass, field
from typing import Protocol

from ..config.pipeline import BatchEngine, PipelineConfig
from ..models.errors import ErrorKind, EtlError
from ..models.event import (BeginEvent, CommitEvent, RelationEvent,
                            SchemaChangeEvent, TruncateEvent)
from ..models.lsn import Lsn
from ..models.schema import TableId
from ..ops.engine import accelerator_backend
from ..postgres.codec import event as event_codec
from ..postgres.codec import pgoutput
from ..postgres.source import FrameSpan, ReplicationStream
from ..store.base import PipelineStore
from ..analysis.annotations import flush_path
from ..destinations.base import Destination
from ..telemetry.egress import record_egress
from ..telemetry.metrics import (ETL_APPLY_LOOP_BATCHES_TOTAL,
                                 ETL_APPLY_LOOP_EVENTS_TOTAL,
                                 ETL_APPLY_LOOP_FLUSH_LAG_BYTES,
                                 ETL_APPLY_LOOP_RECEIVED_LAG_BYTES,
                                 ETL_SHARD_DELIVERED_EVENTS,
                                 ETL_SLOT_LAG_BYTES,
                                 ETL_TRANSACTION_SIZE_BYTES,
                                 ETL_TRANSACTIONS_TOTAL, registry)
from . import failpoints
from .ack_window import AckWindow
from .assembler import RUN_SEAL_ROWS, EventAssembler
from .shutdown import ShutdownSignal
from .state import TableState, TableStateType
from .table_cache import SharedTableCache


class ExitIntent(enum.Enum):
    PAUSE = "pause"  # shutdown requested; resumable from durable progress
    COMPLETE = "complete"  # table-sync caught up to its target


class SyncCoordination(Protocol):
    """What the apply-context loop needs from the table-sync worker pool."""

    # pulsed on table-state transitions so the apply loop can process
    # handoffs immediately instead of polling on keepalives (optional —
    # the loop degrades to keepalive-paced processing without it)
    state_changed: asyncio.Event

    def table_state(self, table_id: TableId) -> TableState | None:
        """Merged store+memory view of one table's state (synchronous — the
        pool keeps its cache current across worker transitions)."""

    def syncing_table_states(self) -> dict[TableId, TableState]:
        """Merged store+memory view of tables NOT owned by the apply worker
        (everything except Ready)."""

    async def set_catchup(self, table_id: TableId, target: Lsn) -> None: ...

    async def wait_for_sync_done_or_errored(
        self, table_id: TableId) -> TableState: ...

    async def mark_ready(self, table_id: TableId) -> None: ...

    async def ensure_worker(self, table_id: TableId) -> None: ...


@dataclass
class ApplyContext:
    """Apply worker: owns the main slot and all Ready tables."""

    progress_key: str  # the apply slot name
    coordination: SyncCoordination


@dataclass
class TableSyncContext:
    """Table-sync worker: owns exactly one table; streams from its snapshot
    until the catchup target, then completes."""

    table_id: TableId
    progress_key: str  # the table-sync slot name
    catchup_target: "asyncio.Future[Lsn]"  # resolved when apply sets Catchup


@dataclass
class _LoopState:
    last_commit_end_lsn: Lsn | None = None  # end of last fully-seen commit
    current_commit_lsn: Lsn = Lsn.ZERO  # from BEGIN
    tx_ordinal: int = 0
    durable_lsn: Lsn = Lsn.ZERO
    received_lsn: Lsn = Lsn.ZERO
    server_end_lsn: Lsn = Lsn.ZERO  # latest end-of-WAL the server reported
    batch_commit_end: Lsn | None = None  # last commit boundary inside batch
    last_status_flush_lsn: Lsn = Lsn.ZERO  # flush LSN last reported upstream
    tx_bytes: int = 0  # payload bytes since the current BEGIN
    in_transaction: bool = False  # between BEGIN and COMMIT


class ApplyLoop:
    def __init__(self, *, ctx: "ApplyContext | TableSyncContext",
                 stream: ReplicationStream, store: PipelineStore,
                 destination: Destination, table_cache: SharedTableCache,
                 config: PipelineConfig, shutdown: ShutdownSignal,
                 start_lsn: Lsn, monitor=None, budget=None,
                 heartbeat=None, supervisor=None):
        self.ctx = ctx
        self.stream = stream
        self.store = store
        self.destination = destination
        self.cache = table_cache
        self.config = config
        self.shutdown = shutdown
        self.monitor = monitor  # MemoryMonitor | None
        # supervision wiring: this loop beats its owner's heartbeat on
        # every select wakeup, progress token = (durable, received) LSNs;
        # busy while a write is in flight or events are assembled — the
        # supervisor reads a frozen token under busy as a stall
        self._hb = heartbeat  # supervision.Heartbeat | None
        self._supervisor = supervisor  # for the decode pipeline's beat
        self._lease = budget.register_stream() if budget is not None else None
        # the assembler owns this loop's decode pipeline; the monitor
        # shrinks its in-flight window to 1 under memory pressure. The
        # lag reader feeds the fair-admission weight: received−durable is
        # this stream's replication lag in WAL bytes (the
        # SlotLagMetrics.confirmed_flush_lag shape, read in-process), so
        # when several streams share the device set the one furthest
        # behind wins proportionally more decode admissions
        self.assembler = EventAssembler(
            config.batch.batch_engine, monitor=monitor,
            decode_window=config.batch.decode_window,
            supervisor=supervisor,
            lag_bytes=lambda: max(
                0, int(self.state.received_lsn) - int(self.state.durable_lsn)),
            admission_capacity=config.batch.admission_capacity,
            seal_bytes=config.batch.max_size_bytes,
            # fuse the destination's wire encoder into the decode
            # programs (ops/egress.py; docs/decode-pipeline.md)
            egress_encoder=(getattr(destination, "egress_encoder", None)
                            if config.batch.device_egress else None))
        self.state = _LoopState(durable_lsn=start_lsn, received_lsn=start_lsn,
                                last_status_flush_lsn=start_lsn)
        # bounded write window (runtime/ack_window.py): flushes keep
        # dispatching in WAL order while up to write_window earlier acks
        # settle; durable progress advances only over the contiguous
        # acked prefix. Shrinks to 1 under memory pressure (the decode
        # pipeline's stance), and window=1 reproduces the reference's
        # one-in-flight loop exactly.
        self._ack_window = AckWindow(
            config.batch.write_window,
            max_bytes=config.batch.write_window_max_bytes,
            pressure=(lambda: monitor.pressure)
            if monitor is not None else None)
        # poison-pill isolation boundary (runtime/poison.py): flush
        # submits route through it so a permanent destination error
        # bisects down to the poison row(s) and dead-letters them
        # instead of killing the worker. Apply context only — initial
        # sync keeps the reference's per-table error states
        # (table_retry), and a sync worker's batches cover one table
        # anyway.
        self._poison = None
        if config.poison.enabled and isinstance(ctx, ApplyContext):
            from .poison import PoisonIsolator

            self._poison = PoisonIsolator(store=store,
                                          destination=destination,
                                          config=config)
        self._batch_deadline: float | None = None
        # True while the CURRENT drain keeps coming back full: flush
        # pacing defers to mega-batching only during a live backlog
        # (the moment the producer pauses, normal deadlines resume)
        self._backlog_now = False
        self._ready_states: dict[TableId, bool] = {}
        # durably delivered event count, published per shard on the
        # status-update cadence (the autoscale collector's rate signal)
        self._delivered_events = 0
        interval = config.schema_cleanup_interval_s
        self._next_schema_cleanup = (time.monotonic() + interval) \
            if interval > 0 and isinstance(ctx, ApplyContext) else None

    # -- ownership filter -----------------------------------------------------

    async def _table_owned(self, table_id: TableId) -> bool:
        """Does THIS worker apply events for the table right now?

        Apply context: Ready tables, plus the SYNC_DONE window — a
        transaction whose commit LSN is ≥ the table's sync-done LSN was NOT
        delivered by the (already exited) sync worker, so the apply worker
        must deliver it even though the Ready transition hasn't happened
        yet (same rule as Postgres tablesync: apply when lsn > syncdone
        lsn). Proof of exactness: a sync-delivered transaction has commit
        END ≤ done_lsn, hence commit LSN < done_lsn — no overlap, no loss.
        """
        if isinstance(self.ctx, TableSyncContext):
            return table_id == self.ctx.table_id
        if self._ready_states.get(table_id):
            return True
        st = self.ctx.coordination.table_state(table_id)
        if st is None:
            return False
        if st.type is TableStateType.READY:
            self._ready_states[table_id] = True
            return True
        if st.type is TableStateType.SYNC_DONE:
            return self.state.current_commit_lsn >= (st.lsn or Lsn.ZERO)
        return False

    def _invalidate_ownership(self, table_id: TableId | None = None) -> None:
        if table_id is None:
            self._ready_states.clear()
        else:
            self._ready_states.pop(table_id, None)

    # -- main loop ------------------------------------------------------------

    async def run(self) -> ExitIntent:
        keepalive_s = self.config.keepalive_deadline_ms / 1000
        stream_iter = self.stream.__aiter__()
        msg_task: asyncio.Task | None = None
        resume_task: asyncio.Task | None = None
        coord_task: asyncio.Task | None = None
        coord_event: asyncio.Event | None = getattr(
            self.ctx.coordination, "state_changed", None) \
            if isinstance(self.ctx, ApplyContext) else None
        # table-sync context: selecting on the catchup future lets the
        # worker react the moment the apply loop sets its target instead
        # of at the next keepalive; disarmed after first resolution
        catchup_future = self.ctx.catchup_target \
            if isinstance(self.ctx, TableSyncContext) \
            and not self.ctx.catchup_target.done() else None
        shutdown_task = asyncio.ensure_future(self.shutdown.wait())
        # consecutive full drain windows: the backlog signal that grows
        # the assembler's seal toward device-size batches (TPU engine)
        backlog_streak = 0
        try:
            while True:
                # memory backpressure: under RSS pressure stop pulling WAL
                # (the walsender buffers; standby feedback keeps flowing via
                # the keepalive timeout) until the monitor's hysteresis
                # resumes — reference BackpressureStream, stream.rs:45-122
                paused = self.monitor is not None and self.monitor.pressure
                if msg_task is None and not paused:
                    msg_task = asyncio.ensure_future(stream_iter.__anext__())
                waits = {shutdown_task}
                if msg_task is not None:
                    waits.add(msg_task)
                if paused:
                    if resume_task is None:
                        resume_task = asyncio.ensure_future(
                            self.monitor.wait_until_resumed())
                    waits.add(resume_task)
                if coord_event is not None and coord_task is None:
                    coord_task = asyncio.ensure_future(coord_event.wait())
                if coord_task is not None:
                    waits.add(coord_task)
                if catchup_future is not None:
                    waits.add(catchup_future)
                # every still-running window task: the head completion
                # advances the durable prefix, and a deeper failure must
                # fail fast. Done-but-unactionable tasks (successful
                # out-of-order completions held for contiguity) are
                # excluded — a done task in the wait set would make every
                # select return immediately until the head ack resolves
                waits.update(self._ack_window.pending_tasks())
                now = time.monotonic()
                if self._ack_window.any_actionable():
                    # a completion became actionable while the loop was
                    # busy elsewhere: handle it this iteration (its task
                    # is done, so nothing in `waits` would wake us)
                    timeout = 0.0
                # the batch deadline only matters when a flush could actually
                # dispatch — honoring it while the window is full (or the
                # breaker holds dispatch) would busy-spin with a zero
                # timeout until an ack settles
                elif self._batch_deadline is not None \
                        and not self._dispatch_blocked():
                    timeout = min(max(0.0, self._batch_deadline - now),
                                  keepalive_s)
                else:
                    timeout = keepalive_s
                done, _ = await asyncio.wait(
                    waits, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                if self._hb is not None:
                    # one beat per wakeup (≤ keepalive cadence when idle):
                    # cheap enough for the hot path, fresh enough for the
                    # hang deadline. Progress = the durability frontier.
                    self._hb.beat(
                        progress=(int(self.state.durable_lsn),
                                  int(self.state.received_lsn)),
                        busy=not self._ack_window.is_empty
                        or self.state.batch_commit_end is not None
                        or len(self.assembler) > 0)

                # priority 1: shutdown
                if shutdown_task in done:
                    await self._drain()
                    return ExitIntent.PAUSE
                if resume_task is not None and resume_task in done:
                    resume_task = None
                # priority 2: flush results — the contiguous acked prefix
                # advances durable progress; a mid-window failure raises
                # after the prefix is persisted (minimal re-stream). Keyed
                # on ACTIONABLE completions (head done, or any failure):
                # a successful out-of-order completion pops nothing yet,
                # and handling it here would spin the loop against an
                # empty pop until the head ack resolves
                if self._ack_window.any_actionable():
                    intent = await self._handle_flush_result()
                    if intent is not None:
                        return intent
                    continue  # re-select; a deadline flush may now proceed
                # priority 3: batch deadline. During a live backlog the
                # deadline defers while the open run is still growing
                # toward the (grown) seal: a deadline flush would seal —
                # and decode — the run below the device threshold, pinning
                # the saturated data plane to host-size batches. Lag is
                # queue depth under saturation anyway; the moment the
                # backlog clears, deadlines fire normally again.
                if self._batch_deadline is not None \
                        and time.monotonic() >= self._batch_deadline:
                    if (self._backlog_now
                            and self.assembler.seal_rows > RUN_SEAL_ROWS
                            and self.assembler.row_events
                            < self.assembler.seal_rows
                            and self.assembler.size_bytes
                            < self._scaled_max_bytes()):
                        self._batch_deadline = time.monotonic() \
                            + self.config.batch.max_fill_ms / 1000
                    else:
                        self._maybe_dispatch_flush(force=True)
                # priority 4: message — then bulk-drain frames that are
                # already buffered: a full select per message costs tens of
                # µs of asyncio machinery, which would cap CDC throughput
                # at ~30k events/s
                if msg_task is not None and msg_task in done:
                    exc = msg_task.exception()
                    if exc is not None:
                        raise exc
                    frame = msg_task.result()
                    msg_task = None
                    intent = await self._handle_frame(frame)
                    if intent is not None:
                        return intent
                    while not (self.shutdown.is_triggered
                               or self._ack_window.any_actionable() or (
                               self.monitor is not None
                               and self.monitor.pressure)):
                        frames = self.stream.drain_spans(4096)
                        if not frames:
                            backlog_streak = 0
                            self._backlog_now = False
                            break
                        # sustained backlog → mega-batching: when the
                        # drain keeps coming back full, the stream is
                        # producing faster than the loop consumes; grow
                        # the seal one row bucket per two full windows so
                        # staged runs reach the measured device threshold
                        # (paced/idle traffic never fills a window, so
                        # lag-sensitive loads keep the small seal)
                        drained = sum(
                            len(it.payloads) if type(it) is FrameSpan
                            else 1 for it in frames)
                        self._backlog_now = drained >= 4096
                        if self._backlog_now:
                            backlog_streak += 1
                            # mega-batching only pays where a DEVICE exists
                            # to route the grown batch to: on the host-CPU
                            # backend each grown bucket is a fresh XLA
                            # compile + a larger host program — measured
                            # 5× e2e streaming LOSS (ops/engine
                            # .accelerator_backend)
                            if backlog_streak >= 2 and accelerator_backend():
                                self.assembler.grow_seal()
                        else:
                            backlog_streak = 0
                        intent = await self._handle_frames(frames)
                        if intent is not None:
                            return intent
                elif not done:
                    # idle timeout: proactive keepalive + idle sync
                    # processing; an idle stream also ends any backlog
                    # episode — seals shrink back to the latency-tuned size
                    backlog_streak = 0
                    self._backlog_now = False
                    self.assembler.reset_seal()
                    await self._send_status_update()
                    if isinstance(self.ctx, ApplyContext):
                        await self._process_syncing_tables(
                            self.state.received_lsn)
                # priority 5: coordination wakes — immediate handoff
                # processing (no keepalive wait)
                if coord_task is not None and coord_task in done:
                    coord_task = None
                    coord_event.clear()
                    await self._process_syncing_tables(
                        self.state.received_lsn)
                if catchup_future is not None and catchup_future.done():
                    catchup_future = None  # disarm; target readable from ctx
                    intent = await self._check_catchup(self.state.received_lsn)
                    if intent is not None:
                        return intent
                if self._next_schema_cleanup is not None \
                        and time.monotonic() >= self._next_schema_cleanup:
                    self._next_schema_cleanup = time.monotonic() \
                        + self.config.schema_cleanup_interval_s
                    await self._run_schema_cleanup()
        finally:
            # an error/cancellation exit can leave in-flight writes
            # running (a supervision restart cancels THIS loop while a
            # write sits in a stalled destination call for seconds more)
            # — cancel the whole window with the select tasks; resume
            # re-streams from durable progress (which only ever covered
            # the contiguous acked prefix). drain_cancelled keeps a
            # hard-kill cancel landing mid-drain lethal.
            from .shutdown import drain_cancelled

            await drain_cancelled(msg_task, shutdown_task, resume_task,
                                  coord_task, *self._ack_window.tasks())
            # cancelled window entries will never deliver: abandon their
            # pending decodes so staging arenas / window slots /
            # admission tickets return instead of leaking with the
            # discarded events (the leak probe in chaos counts them)
            self._ack_window.abandon_payloads()
            if self._lease is not None:
                self._lease.release()
            self.assembler.close()  # stop the decode pipeline's worker
            await self.stream.close()

    # -- frame handling ---------------------------------------------------------

    async def _handle_frames(self, items: list) -> ExitIntent | None:
        """Bulk path for a drained window of FrameSpans + control frames
        (stream.drain_spans). Spans — the overwhelming majority of CDC
        traffic — append into the assembler with per-SPAN bookkeeping
        (ownership check, LSN watermarks, flush check) instead of
        per-frame Python; control and keepalive frames take the per-frame
        slow path, which doubles as the barrier bounding every span (so
        ownership and current_commit_lsn are constants within one). This
        is what lifts end-to-end CDC from the tens of µs/event the
        per-frame machinery costs (reference loop: apply.rs:1280-1336
        runs it in compiled Rust; here the span batching amortizes it
        instead)."""
        st = self.state
        tpu = self.config.batch.batch_engine is BatchEngine.TPU
        span_t = FrameSpan
        for item in items:
            if type(item) is not span_t:
                intent = await self._handle_frame(item)
                if intent is not None:
                    return intent
                continue
            lsns = item.start_lsns
            st.server_end_lsn = max(st.server_end_lsn, item.end_lsn)
            st.received_lsn = max(st.received_lsn, lsns[-1])
            relid = item.relid
            if not await self._table_owned(relid):
                continue
            schema = self.cache.get(relid)
            if schema is None:
                raise EtlError(ErrorKind.SCHEMA_NOT_FOUND,
                               f"no RELATION seen for table {relid}")
            payloads = item.payloads
            if tpu:
                nbytes = self.assembler.push_raw_rows(
                    payloads, schema, lsns, int(st.current_commit_lsn),
                    st.tx_ordinal)
                st.tx_ordinal += len(payloads)
                st.tx_bytes += nbytes
            else:
                # CPU engine: expand the span through the per-message
                # oracle path (host-parsed events, reference per-tuple
                # architecture)
                commit_lsn = st.current_commit_lsn
                for payload, lsn in zip(payloads, lsns):
                    msg = pgoutput.decode_logical_message(payload)
                    self.assembler.push_row_message(
                        msg, payload, schema, Lsn(lsn), commit_lsn,
                        st.tx_ordinal)
                    st.tx_ordinal += 1
                    st.tx_bytes += len(payload)
            if self._batch_deadline is None:
                self._batch_deadline = time.monotonic() \
                    + self.config.batch.max_fill_ms / 1000
            self._maybe_dispatch_flush()
        return None

    async def _handle_frame(self, frame) -> ExitIntent | None:
        # chaos stall mode: a wedged frame read — the loop stops beating
        # entirely and only the watchdog's hang detection recovers it.
        # Pre-guarded: this runs per frame, and the disarmed cost must
        # stay one dict check, not a coroutine allocation.
        if failpoints.stalls_armed():
            await failpoints.stall_point(failpoints.APPLY_FRAME_READ)
        if isinstance(frame, pgoutput.PrimaryKeepalive):
            self.state.server_end_lsn = max(self.state.server_end_lsn,
                                            frame.end_lsn)
            self.state.received_lsn = max(self.state.received_lsn,
                                          frame.end_lsn)
            if frame.reply_requested:
                await self._send_status_update()
            if isinstance(self.ctx, ApplyContext):
                await self._process_syncing_tables(frame.end_lsn)
            else:
                return await self._check_catchup(frame.end_lsn)
            return None
        assert isinstance(frame, pgoutput.XLogData)
        self.state.server_end_lsn = max(self.state.server_end_lsn,
                                        frame.end_lsn)
        self.state.received_lsn = max(self.state.received_lsn, frame.start_lsn)
        await self._handle_message(frame.start_lsn, frame.payload)
        self._maybe_dispatch_flush()
        # commit-boundary coordination
        if frame.payload[:1] == b"C":
            if isinstance(self.ctx, ApplyContext):
                await self._process_syncing_tables(
                    self.state.last_commit_end_lsn or frame.start_lsn)
            else:
                return await self._check_catchup(
                    self.state.last_commit_end_lsn or frame.start_lsn)
        return None

    async def _handle_message(self, start_lsn: Lsn, payload: bytes) -> None:
        st = self.state
        # TPU-engine fast path for row messages: the batch engine needs
        # only (kind, relid, raw payload) — the native framer re-parses the
        # tuple data on the staging path, so a full host-side
        # decode_logical_message here would parse every tuple twice and cap
        # CDC throughput at the Python parse rate
        if payload[:1] in (b"I", b"U", b"D") \
                and self.config.batch.batch_engine is BatchEngine.TPU:
            relid = int.from_bytes(payload[1:5], "big")
            if not await self._table_owned(relid):
                return
            schema = self.cache.get(relid)
            if schema is None:
                raise EtlError(ErrorKind.SCHEMA_NOT_FOUND,
                               f"no RELATION seen for table {relid}")
            self.assembler.push_raw_row(payload, schema, start_lsn,
                                        st.current_commit_lsn, st.tx_ordinal)
            st.tx_ordinal += 1
            st.tx_bytes += len(payload)
            if self.assembler.size_bytes and self._batch_deadline is None:
                self._batch_deadline = time.monotonic() \
                    + self.config.batch.max_fill_ms / 1000
            return
        msg = pgoutput.decode_logical_message(payload)
        tpu = self.config.batch.batch_engine is BatchEngine.TPU
        if isinstance(msg, pgoutput.BeginMessage):
            st.current_commit_lsn = msg.final_lsn
            st.tx_ordinal = 0
            st.tx_bytes = 0
            st.in_transaction = True
            # TPU engine: Begin/Commit are NOT run barriers — device
            # batches span transactions (each row carries its own
            # commit_lsn/tx_ordinal), so decode calls happen per FLUSH,
            # not per transaction. Sealing here would cap CDC throughput
            # at the per-transaction device-dispatch rate. Durability
            # still advances only at commit boundaries via
            # batch_commit_end (apply.rs:1932-1945 carries the commit LSN
            # separately from the batch for the same reason).
            if not tpu:
                self.assembler.push_control(
                    event_codec.decode_begin(msg, start_lsn))
        elif isinstance(msg, pgoutput.CommitMessage):
            ev = event_codec.decode_commit(msg, start_lsn)
            if not tpu:
                self.assembler.push_control(ev)
            elif self._batch_deadline is None:
                # no assembler event marks this boundary, so arm the
                # deadline: an empty commit window still needs the
                # force-flush to advance durable progress (see
                # _maybe_dispatch_flush)
                self._batch_deadline = time.monotonic() \
                    + self.config.batch.max_fill_ms / 1000
            st.in_transaction = False
            st.last_commit_end_lsn = ev.end_lsn
            st.batch_commit_end = ev.end_lsn
            # commit watermark for size-bounded flush splitting: a prefix
            # flush covering everything assembled so far may claim
            # durability at this commit end (runtime/assembler.py)
            self.assembler.note_commit_end(ev.end_lsn)
            registry.counter_inc(ETL_TRANSACTIONS_TOTAL)
            # owned-row payload bytes only (tx_bytes definition) — control
            # messages don't count toward transaction size
            registry.histogram_observe(ETL_TRANSACTION_SIZE_BYTES,
                                       st.tx_bytes)
            # commit fast path: while the write window has room, flushing
            # AT the commit boundary cuts p50 replication lag by the whole
            # fill window (an idle pipeline has nothing to batch FOR) and
            # — on destinations with real ack latency — keeps up to
            # write_window commits' writes overlapping their ack round
            # trips instead of serializing one per round trip. Once the
            # window fills, later commits coalesce into full batches, so
            # saturated throughput is unaffected (at window=1 this is
            # exactly the old idle-commit fast path).
            # Keyed on ROW events, not len(assembler): commits of
            # unowned-table transactions (whose CPU-engine Begin/Commit
            # controls still land in the assembler) stay on the deadline
            # path — an immediate flush per such commit would write
            # durable progress per commit instead of per fill window.
            # (suppressed during a live backlog: the fast flush exists to
            # cut IDLE lag, and here it would seal a growing mega run)
            if self.assembler.row_events and not self._backlog_now:
                self._maybe_dispatch_flush(force=True)  # no-op when blocked
        elif isinstance(msg, pgoutput.RelationMessage):
            schema = event_codec.schema_from_relation_message(msg)
            prev = self.cache.get(msg.relation_id)
            self.cache.set(schema)
            if await self._table_owned(msg.relation_id) \
                    and (prev is None or prev != schema):
                self.assembler.push_control(RelationEvent(
                    start_lsn, st.current_commit_lsn, schema))
        elif isinstance(msg, (pgoutput.InsertMessage, pgoutput.UpdateMessage,
                              pgoutput.DeleteMessage)):
            if not await self._table_owned(msg.relation_id):
                return
            schema = self.cache.get(msg.relation_id)
            if schema is None:
                raise EtlError(ErrorKind.SCHEMA_NOT_FOUND,
                               f"no RELATION seen for table {msg.relation_id}")
            self.assembler.push_row_message(
                msg, payload, schema, start_lsn, st.current_commit_lsn,
                st.tx_ordinal)
            st.tx_ordinal += 1
            st.tx_bytes += len(payload)
        elif isinstance(msg, pgoutput.TruncateMessage):
            schemas = []
            for rid in msg.relation_ids:
                if await self._table_owned(rid):
                    sch = self.cache.get(rid)
                    if sch is not None:
                        schemas.append(sch)
            if schemas:
                self.assembler.push_control(TruncateEvent(
                    start_lsn, st.current_commit_lsn, st.tx_ordinal,
                    msg.options, tuple(schemas)))
                st.tx_ordinal += 1
        elif isinstance(msg, pgoutput.LogicalMessage):
            if msg.prefix == event_codec.DDL_MESSAGE_PREFIX:
                ev = event_codec.decode_schema_change(
                    msg, start_lsn, st.current_commit_lsn)
                if ev.new_schema is not None:
                    await self.store.store_table_schema(
                        ev.new_schema, int(start_lsn))
                if await self._table_owned(ev.table_id):
                    self.assembler.push_control(ev)
        # Origin/Type messages are ignored
        if self.assembler.size_bytes and self._batch_deadline is None:
            self._batch_deadline = time.monotonic() \
                + self.config.batch.max_fill_ms / 1000

    # -- batching / flush -------------------------------------------------------

    def _scaled_max_bytes(self) -> int:
        """Size-flush threshold, scaled with seal growth: the static cap
        is tuned for latency-sized batches and would otherwise seal mega
        runs at ~max_size_bytes of payload — below the device threshold —
        no matter how far the seal grew. Memory stays bounded by the
        growth cap (MEGA/RUN = 16×) and the backpressure monitor."""
        return self.config.batch.max_size_bytes \
            * max(1, self.assembler.seal_rows // RUN_SEAL_ROWS)

    def _breaker_open(self) -> bool:
        """True when the destination's circuit breaker is OPEN (shedding).
        Reads through the SupervisedDestination wrapper when present;
        plain destinations have no breaker."""
        from ..supervision.breaker import breaker_is_open

        return breaker_is_open(self.destination)

    def _flush_threshold(self) -> int:
        """The size bound of the NEXT flush: the scaled cap, shrunk by
        the per-stream budget share (batch_budget.rs:72-96)."""
        threshold = self._scaled_max_bytes()
        if self._lease is not None:
            threshold = min(threshold, self._lease.ideal_batch_bytes())
        return threshold

    def _dispatch_blocked(self) -> bool:
        """A new flush must not dispatch right now: the write window is
        at capacity, or the breaker is open while earlier acks are still
        settling — in-flight writes may yet succeed, so the window drains
        before the breaker sheds a fresh call (which would fail the
        worker and cancel them). Once the window is empty the dispatch
        proceeds and the breaker's fast-fail becomes worker backoff, the
        existing shedding path. The byte-cap check sees the PROSPECTIVE
        flush size (≤ threshold — flush_bounded cuts there), not the
        whole assembler backlog: judging a 60 MiB backlog against the
        window's byte cap would collapse the window to one-in-flight
        exactly when the backlog is largest."""
        nbytes = min(self.assembler.size_bytes, self._flush_threshold())
        if not self._ack_window.can_dispatch(nbytes):
            return True
        return not self._ack_window.is_empty and self._breaker_open()

    @flush_path
    def _maybe_dispatch_flush(self, force: bool = False) -> None:
        """Dispatch as many flushes as the window accepts: one for a
        `force` trigger (deadline, commit fast path, catchup drain) plus
        size-triggered ones while the assembler still holds a full
        batch. With a size-bounded split in effect (write_window > 1) a
        drained backlog becomes a sequence of ≤ threshold-byte batches
        the window pipelines."""
        dispatched = False
        while not self._dispatch_blocked():
            if not self._dispatch_one(force and not dispatched):
                return
            dispatched = True

    def _dispatch_one(self, force: bool) -> bool:
        if len(self.assembler) == 0:
            # TPU engine: commits are not assembler events, so a commit
            # window whose owned-row set is EMPTY (unowned tables,
            # mid-sync traffic) still must advance durable progress —
            # otherwise batch_commit_end never clears, _is_idle() stays
            # false, and the slot's confirmed_flush pins while source WAL
            # retention grows. Dispatch an event-less flush through the
            # normal write-window machinery (one per fill window,
            # amortized like any other deadline flush).
            if not (force and self.state.batch_commit_end is not None):
                return False
        # budget-aware threshold: under many active streams the per-stream
        # share shrinks below the static cap (batch_budget.rs:72-96) —
        # flushes happen mid-transaction with the commit LSN carried
        # separately (apply.rs:1932-1945), so splitting huge transactions
        # is safe for durability accounting
        threshold = self._flush_threshold()
        if not force and self.assembler.size_bytes < threshold:
            return False
        # size-bounded flush: flush a WAL-ordered prefix of ≤ threshold
        # bytes — a drained backlog then dispatches as a sequence of
        # bounded batches the write window pipelines, instead of one
        # backlog-sized write whose single ack serializes everything
        # behind it (and whose payload can exceed what a destination
        # accepts per request). max_size_bytes is now a real per-write
        # bound, not just a flush trigger; the delivered event stream is
        # byte-identical at every window depth (asserted by bench.py
        # --ack-latency). The commit watermark (`covered`) — not the raw
        # batch_commit_end — is what a PREFIX flush may claim durability
        # at; `remaining` is the highest boundary still awaiting a later
        # flush.
        before_bytes = self.assembler.size_bytes
        events, covered, remaining = \
            self.assembler.flush_bounded(max_bytes=threshold)
        batch_bytes = before_bytes - self.assembler.size_bytes
        commit_end = covered
        self.state.batch_commit_end = remaining
        if len(self.assembler) > 0:
            # a remainder stays assembled: keep it on the normal fill
            # cadence (the dispatch loop may also flush it immediately
            # when the size threshold still holds and the window has
            # room)
            self._batch_deadline = time.monotonic() \
                + self.config.batch.max_fill_ms / 1000
        else:
            self._batch_deadline = None

        # transactional commit seam (docs/destinations.md exactly-once):
        # when the destination opts in, the flush ships its WAL
        # coordinate range alongside the data so the sink records both
        # atomically — a blind re-stream's rows then dedup sink-side and
        # restart recovery can trim the re-stream window to the unacked
        # suffix. The range is derived from the SAME payload the write
        # carries (CoalescedBatch / row-event coordinates), with the
        # commit watermark `covered` as the resume anchor.
        commit_range = None
        if events and self.destination.supports_transactional_commit():
            from ..destinations.base import CommitRange

            commit_range = CommitRange.from_events(
                events, commit_end_lsn=commit_end)

        async def submit():
            if not events:
                return None  # commit-boundary-only flush: no destination
            # columnar write seam: DecodedBatchEvents reach the
            # destination as batches (columnar-native writers encode them
            # column-at-a-time; others fall back to the row path via the
            # base-class shim). The ack window owns the durability wait
            # (etl-lint rule 17): submissions stay in WAL order, only the
            # ack round trips overlap. The poison isolator sits between
            # the flush and the destination: a PERMANENT (poison-kind)
            # write failure bisects to the poison rows and dead-letters
            # them, quarantined tables' events park — transient failures
            # pass through to the worker-retry path unchanged.
            if self._poison is not None:
                return await self._poison.submit(events,
                                                 commit=commit_range)
            if commit_range is not None:
                return await self.destination.write_event_batches_committed(
                    events, commit_range)
            return await self.destination.write_event_batches(events)

        def on_durable() -> None:
            # billing/egress accounting rides durable acks (egress.rs:1-20)
            record_egress(pipeline_id=self.config.pipeline_id,
                          destination=getattr(
                              self.destination, "telemetry_name",
                              type(self.destination).__name__),
                          bytes_processed=batch_bytes, kind="streaming")

        registry.counter_inc(ETL_APPLY_LOOP_BATCHES_TOTAL)
        registry.counter_inc(ETL_APPLY_LOOP_EVENTS_TOTAL, len(events))
        self._ack_window.dispatch(
            submit, commit_end_lsn=commit_end, n_events=len(events),
            nbytes=batch_bytes, on_durable=on_durable if events else None,
            payload=events, commit_range=commit_range)
        return True

    @flush_path
    async def _apply_flush_result(self) -> bool:
        """Consume the contiguous acked prefix of the write window;
        advance durable progress over it. Returns True if progress
        advanced (a commit boundary was covered). A mid-window failure
        raises AFTER the durable prefix is persisted, so the restart
        re-streams only the unacked suffix (bounded-dup budget grows by
        at most the window size)."""
        done, failure = self._ack_window.pop_ready()
        advanced = False
        for entry in done:
            self._delivered_events += entry.n_events
            if entry.commit_end_lsn is None:
                continue
            self.state.durable_lsn = max(self.state.durable_lsn,
                                         entry.commit_end_lsn)
            advanced = True
        if advanced:
            failpoints.fail_point(failpoints.ON_PROGRESS_STORE)
            await self.store.update_durable_progress(
                self.ctx.progress_key, self.state.durable_lsn)
            if failure is None:
                # NO standby status when a failure was popped: the
                # failed entry is out of the window, so _is_idle() can
                # read True and the effective flush LSN would advance to
                # received_lsn — PAST the failed entry's undelivered WAL
                # — trimming the slot before the restart re-streams it
                # (found by the pipeline_pack_fault chaos scenario). The
                # durable-progress store write above is safe either way:
                # it only ever names acked commit ends.
                await self._send_status_update()
        if failure is not None:
            raise failure if isinstance(failure, EtlError) else EtlError(
                ErrorKind.DESTINATION_FAILED, str(failure))
        return advanced

    async def _handle_flush_result(self) -> ExitIntent | None:
        advanced = await self._apply_flush_result()
        if advanced:
            if isinstance(self.ctx, ApplyContext):
                await self._process_syncing_tables_after_flush()
            else:
                return await self._check_catchup(self.state.durable_lsn)
        return None

    @flush_path
    async def _drain(self) -> None:
        """Shutdown path: wait out every in-flight write, then stop
        without flushing the open batch (it re-streams on resume —
        at-least-once). A failed write ends the drain: everything past
        the durable prefix re-streams on resume."""
        while not self._ack_window.is_empty:
            await self._ack_window.wait_all()
            try:
                await self._handle_flush_result()
            except EtlError:
                return  # resume re-delivers from durable progress

    async def _run_schema_cleanup(self) -> None:
        """Prune schema versions no longer reachable by any decode: every
        event at or below the durable LSN is flushed, so only the newest
        version ≤ durable (plus anything newer) can still be consulted
        (reference hourly cleanup task, apply.rs:123,423-631,1607)."""
        from ..models.schema import SnapshotId

        failpoints.fail_point(failpoints.ON_SCHEMA_CLEANUP)
        if int(self.state.durable_lsn) == 0:
            return
        snapshot = SnapshotId(int(self.state.durable_lsn))
        for tid in await self.store.get_table_ids_with_schemas():
            await self.store.prune_schema_versions(tid, snapshot)

    def _is_idle(self) -> bool:
        """No open transaction, nothing assembled, an empty write window,
        no commit boundary awaiting durability (apply.rs:885-889). Only
        then may keepalive progress be reported as flushed."""
        return (not self.state.in_transaction
                and len(self.assembler) == 0
                and self._ack_window.is_empty
                and self.state.batch_commit_end is None)

    def _effective_flush_lsn(self) -> Lsn:
        """Flush LSN for standby feedback (apply.rs:891-912): when IDLE the
        last received LSN — so the slot advances past unpublished/keepalive
        WAL instead of pinning retention — otherwise the durable commit
        floor. Idle-only advances are deliberately NOT persisted as durable
        progress; monotonicity is enforced against the last report (a
        post-idle transaction would otherwise jump the LSN back)."""
        effective = self.state.received_lsn if self._is_idle() \
            else self.state.durable_lsn
        return max(effective, self.state.durable_lsn,
                   self.state.last_status_flush_lsn)

    async def _send_status_update(self) -> None:
        failpoints.fail_point(failpoints.ON_STATUS_UPDATE)
        registry.gauge_set(ETL_APPLY_LOOP_FLUSH_LAG_BYTES,
                           self.state.received_lsn - self.state.durable_lsn)
        registry.gauge_set(
            ETL_APPLY_LOOP_RECEIVED_LAG_BYTES,
            max(0, self.state.server_end_lsn - self.state.received_lsn))
        if isinstance(self.ctx, ApplyContext):
            # per-slot lag as a FIRST-CLASS series, on this loop's
            # existing cadence: the same received−durable number the
            # admission weight reads, labeled by shard so the autoscale
            # collector and an operator dashboard read the identical
            # gauge (table-sync loops deliberately excluded — their
            # transient catchup slots would clobber the shard series)
            shard_label = {"shard": str(self.config.shard or 0)}
            registry.gauge_set(
                ETL_SLOT_LAG_BYTES,
                max(0, int(self.state.received_lsn)
                    - int(self.state.durable_lsn)),
                labels=shard_label)
            registry.gauge_set(ETL_SHARD_DELIVERED_EVENTS,
                               self._delivered_events, labels=shard_label)
        flush = self._effective_flush_lsn()
        self.state.last_status_flush_lsn = flush
        await self.stream.send_status_update(
            written=self.state.received_lsn,
            flushed=flush,
            applied=flush)

    # -- table-sync coordination (apply context) --------------------------------

    async def _process_syncing_tables(self, current_lsn: Lsn) -> None:
        coord = self.ctx.coordination
        for tid, st in list(coord.syncing_table_states().items()):
            if st.type is TableStateType.SYNC_WAIT:
                target = max(st.lsn or Lsn.ZERO, current_lsn)
                await coord.set_catchup(tid, target)
                # the handoff wait parks this loop for as long as the
                # sync worker needs to reach its catchup target — keep
                # beating so the park never reads as a hang (the SYNC
                # WORKER's own watchdog covers a stall inside it)
                from ..supervision import beat_while_waiting

                result = await beat_while_waiting(
                    self._hb, coord.wait_for_sync_done_or_errored(tid))
                if result.type is TableStateType.SYNC_DONE:
                    # became SyncDone; Ready happens after a durable flush
                    # covering its LSN (or immediately if already covered)
                    await self._maybe_mark_ready(tid, result)
            elif st.type is TableStateType.SYNC_DONE:
                await self._maybe_mark_ready(tid, st)
            elif st.type in (TableStateType.INIT, TableStateType.DATA_SYNC,
                             TableStateType.FINISHED_COPY):
                await coord.ensure_worker(tid)

    async def _maybe_mark_ready(self, tid: TableId, st: TableState) -> None:
        done_lsn = st.lsn or Lsn.ZERO
        current = max(self.state.durable_lsn, self.state.received_lsn)
        if current >= done_lsn:
            await self.ctx.coordination.mark_ready(tid)
            self._invalidate_ownership(tid)

    async def _process_syncing_tables_after_flush(self) -> None:
        coord = self.ctx.coordination
        for tid, st in list(coord.syncing_table_states().items()):
            if st.type is TableStateType.SYNC_DONE:
                await self._maybe_mark_ready(tid, st)

    # -- catchup (table-sync context) --------------------------------------------

    async def _check_catchup(self, current_lsn: Lsn) -> ExitIntent | None:
        ctx = self.ctx
        assert isinstance(ctx, TableSyncContext)
        if not ctx.catchup_target.done():
            return None
        target = ctx.catchup_target.result()
        if current_lsn < target:
            return None
        # Reached the fence. Everything ≤ target MUST be durably flushed
        # before SyncDone is recorded — the apply worker takes over from
        # `target` believing this worker delivered durably up to it.
        while len(self.assembler) > 0 or not self._ack_window.is_empty:
            self._maybe_dispatch_flush(force=True)
            if not self._ack_window.is_empty:
                await self._ack_window.wait_all()
                await self._apply_flush_result()
        done_lsn = max(self.state.durable_lsn, target)
        await self.store.update_table_state(ctx.table_id,
                                            TableState.sync_done(done_lsn))
        return ExitIntent.COMPLETE
