"""Shutdown signaling: a watch-channel analogue on asyncio.

Reference parity: shutdown watch channel + `ShutdownResult`
(crates/etl/src/runtime/concurrency/{shutdown,signal}.rs). One tx side held
by the pipeline, many rx sides cloned into workers; `wait()` is cancel-safe
and level-triggered.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, TypeVar

T = TypeVar("T")


class ShutdownSignal:
    def __init__(self) -> None:
        self._event = asyncio.Event()

    def trigger(self) -> None:
        self._event.set()

    @property
    def is_triggered(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        await self._event.wait()  # etl-lint: ignore[unbounded-await] — this IS the shutdown race primitive the rule demands elsewhere


class ShutdownRequested(Exception):
    """Raised by `or_shutdown` when the signal wins the race."""


async def drain_cancelled(*tasks: "asyncio.Task | None") -> None:
    """Cancel-and-drain that never eats the CALLER's own cancellation.

    The naive idiom `t.cancel(); try: await t; except CancelledError:
    pass` has a liveness hole: if the caller is itself cancelled while
    parked on `await t`, its OWN CancelledError surfaces at that await
    and the except swallows it — the caller resumes as if nothing
    happened and survives the kill (the chaos runner's hard-kill found
    this: a cancel landing inside such a finally left the apply worker
    retrying forever). `asyncio.wait` never raises the drained tasks'
    exceptions, so the only CancelledError that can escape here is the
    caller's — exactly the one that must propagate."""
    pending = [t for t in tasks if t is not None]
    for t in pending:
        if not t.done():
            t.cancel()
    if pending:
        await asyncio.wait(pending)
        for t in pending:
            if not t.cancelled():
                t.exception()  # retrieved: no never-retrieved noise


async def or_shutdown(shutdown: ShutdownSignal, aw: Awaitable[T]) -> T:
    """Await `aw`, aborting with ShutdownRequested if shutdown triggers
    first. The pending awaitable is cancelled on abort."""
    task = asyncio.ensure_future(aw)
    sd = asyncio.ensure_future(shutdown.wait())
    try:
        done, _ = await asyncio.wait({task, sd},
                                     return_when=asyncio.FIRST_COMPLETED)
        if task in done:
            return task.result()
        raise ShutdownRequested()
    finally:
        await drain_cancelled(task, sd)
