"""Shutdown signaling: a watch-channel analogue on asyncio.

Reference parity: shutdown watch channel + `ShutdownResult`
(crates/etl/src/runtime/concurrency/{shutdown,signal}.rs). One tx side held
by the pipeline, many rx sides cloned into workers; `wait()` is cancel-safe
and level-triggered.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, TypeVar

T = TypeVar("T")


class ShutdownSignal:
    def __init__(self) -> None:
        self._event = asyncio.Event()

    def trigger(self) -> None:
        self._event.set()

    @property
    def is_triggered(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        await self._event.wait()


class ShutdownRequested(Exception):
    """Raised by `or_shutdown` when the signal wins the race."""


async def or_shutdown(shutdown: ShutdownSignal, aw: Awaitable[T]) -> T:
    """Await `aw`, aborting with ShutdownRequested if shutdown triggers
    first. The pending awaitable is cancelled on abort."""
    task = asyncio.ensure_future(aw)
    sd = asyncio.ensure_future(shutdown.wait())
    try:
        done, _ = await asyncio.wait({task, sd},
                                     return_when=asyncio.FIRST_COMPLETED)
        if task in done:
            return task.result()
        raise ShutdownRequested()
    finally:
        for t in (task, sd):
            if not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
