"""Pipeline orchestrator.

Reference parity: `Pipeline` (crates/etl/src/pipeline.rs:74) —
`new/start/wait/shutdown` (pipeline.rs:96,142,249,320) and
`initialize_table_states` (pipeline.rs:354): tables in the publication get
Init states if absent; tables no longer published are purged (state,
schemas, destination metadata, slot).
"""

from __future__ import annotations

import asyncio
import logging

from ..config.pipeline import BatchEngine, PipelineConfig
from ..models.errors import ErrorKind, EtlError
from ..postgres.slots import table_sync_slot_name
from ..postgres.source import ReplicationSource
from ..store.base import PipelineStore
from ..destinations.base import Destination
from .apply_worker import ApplyWorker
from .backpressure import BatchBudgetController, MemoryMonitor
from .shutdown import ShutdownSignal
from .state import TableState
from .table_cache import SharedTableCache
from .table_sync import TableSyncWorkerPool

logger = logging.getLogger("etl_tpu.pipeline")


class Pipeline:
    """One replication pipeline: publication → destination."""

    def __init__(self, *, config: PipelineConfig, store: PipelineStore,
                 destination: Destination, source_factory):
        config.validate()
        self.config = config
        self.store = store
        # set at start() for sharded pods: the adopted ShardIdentity
        # (shard, shard_count, epoch) — the raw store stays reachable
        # through `self.store._inner` only via the scoped view
        self.shard_identity = None
        self.destination = destination
        self.source_factory = source_factory  # () -> ReplicationSource
        self.shutdown_signal = ShutdownSignal()
        self.table_cache = SharedTableCache()
        self.pool: TableSyncWorkerPool | None = None
        self.apply_worker: ApplyWorker | None = None
        self._apply_task: asyncio.Task | None = None
        self.memory_monitor: MemoryMonitor | None = None
        self.batch_budget: BatchBudgetController | None = None
        # supervision tree (docs/supervision.md): liveness watchdogs over
        # every long-running component + the pipeline health state
        # machine the replicator's /health serves. Built here (not in
        # start) so /health can answer "starting" before start() runs.
        self.supervisor = None
        if config.supervision.enabled:
            from ..supervision import Supervisor

            self.supervisor = Supervisor(config.supervision)
        # what the workers actually write through: the configured
        # destination behind the supervision wrapper (per-op timeout
        # bound + circuit breaker + heartbeat); `destination` stays the
        # raw inner for tests and the maintenance agent
        self.active_destination: Destination = destination
        if self.supervisor is not None:
            from ..supervision import SupervisedDestination

            self.active_destination = SupervisedDestination(
                destination,
                timeout_s=config.destination_op_timeout_s,
                breaker=self.supervisor.breaker(
                    type(destination).__name__),
                heartbeat=self.supervisor.register("destination"))

    async def start(self) -> None:
        if self.config.shard is not None and self.shard_identity is None:
            # adopt the authoritative shard assignment and swap the store
            # for this pod's filtered, write-fenced view BEFORE anything
            # reads table states — init, the pool, and the apply worker
            # must all see only this shard's slice (docs/sharding.md)
            from ..sharding.runtime import resolve_shard_scope

            scoped = await resolve_shard_scope(self.store, self.config)
            self.store = scoped
            self.shard_identity = scoped.identity
            logger.info("shard scope: %s", scoped.identity.describe())
        source = self.source_factory()
        await source.connect()
        try:
            if self.config.run_source_migrations:
                # installs the supabase_etl_ddl event trigger so schema
                # changes flow through the WAL (pipeline.rs:153-164);
                # no-op on standbys and when already applied
                from ..postgres.migrations import run_source_migrations

                await run_source_migrations(source)
            await self._initialize_table_states(source)
            await self._install_row_filters(source)
        finally:
            await source.close()
        if self.supervisor is not None:
            self.supervisor.start()
        await self.active_destination.startup()
        if self.config.batch.batch_engine is BatchEngine.TPU:
            # warm the per-process device cost model OFF the event loop
            # now: the probe jit-compiles and moves 2x8 MiB over the link
            # (seconds on a tunnel-attached chip), and without prewarm it
            # would run synchronously inside the apply loop at first
            # DeviceDecoder construction, stalling keepalives for every
            # table (round-5 advisor finding, ops/engine.py)
            from ..ops import autotune, program_store

            await autotune.prewarm()
            # program prewarm (ops/program_store.py): enumerate the
            # SchemaStore's tables, resolve canonical layouts, and warm
            # the deduped host-program keys before the apply loop sees
            # traffic — disk hits load here (a warm restart reaches its
            # first durable batch with ZERO fresh XLA builds), cold keys
            # compile on the same background threads the streaming
            # decoders' nonblocking_compile path uses. Runs on the
            # executor, never on this loop.
            await program_store.prewarm_pipeline(self.store,
                                                 self.config.batch)
        # memory defense (reference pipeline.rs:168 MemoryMonitor::new +
        # batch_budget.rs): the monitor pauses WAL/COPY intake under RSS
        # pressure; the budget controller sizes batches by the active
        # stream count so concurrent copies don't multiply peak memory
        monitor_hb = self.supervisor.register("memory_monitor") \
            if self.supervisor is not None else None
        # the ctor's chain reads the cgroup limit via open(): a kernfs
        # read (microseconds, never blocks on I/O), once, at startup,
        # before any worker spawns
        self.memory_monitor = MemoryMonitor(  # etl-lint: ignore[blocking-call-in-async]
            self.config.backpressure, heartbeat=monitor_hb)
        self.memory_monitor.start()
        self.batch_budget = BatchBudgetController(
            self.config.backpressure, self.config.batch.max_size_bytes)
        self.pool = TableSyncWorkerPool(
            config=self.config, store=self.store,
            destination=self.active_destination,
            source_factory=self.source_factory,
            table_cache=self.table_cache, shutdown=self.shutdown_signal,
            monitor=self.memory_monitor, budget=self.batch_budget,
            supervisor=self.supervisor)
        await self.pool.refresh_states()
        self.apply_worker = ApplyWorker(
            config=self.config, store=self.store,
            destination=self.active_destination,
            source_factory=self.source_factory, pool=self.pool,
            table_cache=self.table_cache, shutdown=self.shutdown_signal,
            monitor=self.memory_monitor, budget=self.batch_budget,
            supervisor=self.supervisor)
        self._apply_task = self.apply_worker.spawn()

    async def _install_row_filters(self, source: ReplicationSource) -> None:
        """Discover the publication's row filters and install them on the
        shared table cache: RELATION messages carry no filter, so every
        decode view the apply loop builds re-attaches its table's
        predicate and the decoder fuses it into the device program
        (ops/predicate.py). Parsed ONCE here — never on the apply loop or
        per batch (etl-lint rule 13). Unsupported expressions degrade to
        server-side-only filtering with a log line; a failing catalog
        read is non-fatal for the same reason (pre-15 sources have no
        rowfilter column at all)."""
        from ..ops.predicate import RowFilterError, parse_row_filter
        from ..postgres.wire import PgServerError

        try:
            filters = await source.get_row_filters(
                self.config.publication_name)
        except (EtlError, PgServerError, ConnectionError, OSError):
            # catalog quirk (e.g. a pre-15 server behind a version probe
            # that lied): filtering falls back to the server side —
            # never fatal, but logged so the offload deployment notices
            logger.info("publication row-filter discovery failed; "
                        "client-side filtering disabled", exc_info=True)
            return
        parsed: dict = {}
        for tid, sql in filters.items():
            try:
                parsed[tid] = parse_row_filter(sql)
            except RowFilterError:
                logger.info(
                    "row filter %r on table %s is outside the client-side "
                    "envelope; relying on server-side filtering", sql, tid)
        if parsed:
            self.table_cache.set_row_predicates(parsed)
            logger.info("client-side row filters active for tables %s",
                        sorted(parsed))

    async def _initialize_table_states(self,
                                       source: ReplicationSource) -> None:
        pub = self.config.publication_name
        if not await source.publication_exists(pub):
            raise EtlError(ErrorKind.PUBLICATION_NOT_FOUND, pub)
        published = set(await source.get_publication_table_ids(pub))
        if self.shard_identity is not None:
            # this pod initialises (and may purge) only ITS slice of the
            # publication; sibling shards own the rest. The store view is
            # already filtered, so `known` below is owned tables only.
            smap = self.shard_identity.shard_map()
            published = {tid for tid in published
                         if smap.owns(tid, self.shard_identity.shard)}
        known = await self.store.get_table_states()
        for tid in published:
            if tid not in known:
                await self.store.update_table_state(tid, TableState.init())
        for tid in set(known) - published:
            logger.info("purging table %s (no longer in publication)", tid)
            await self.store.purge_table(tid)
            await source.delete_slot(
                table_sync_slot_name(self.config.pipeline_id, tid,
                                     self.config.shard))

    async def wait(self) -> None:
        """Wait until the apply worker stops (shutdown or fatal error)."""
        assert self._apply_task is not None, "pipeline not started"
        try:
            await self._apply_task
        except BaseException as e:
            # the apply worker exhausted its retries (or died on a
            # permanent error): the health surface must say FAULTED, not
            # keep serving the last degraded/healthy state
            if self.supervisor is not None \
                    and not isinstance(e, asyncio.CancelledError):
                self.supervisor.health.fault(f"apply worker failed: {e}")
            raise
        finally:
            # a fatal apply error must release table-sync workers parked on
            # catchup futures only the apply worker could resolve — trigger
            # shutdown so wait_all() cannot hang and the error propagates
            self.shutdown_signal.trigger()
            if self.pool is not None:
                await self.pool.wait_all()
            if self.memory_monitor is not None:
                await self.memory_monitor.stop()
            if self.supervisor is not None:
                await self.supervisor.stop()
            await self.active_destination.shutdown()

    def health_snapshot(self) -> dict:
        """The live supervision surface the replicator's /health/detail
        serves; minimal shape when supervision is disabled. Sharded pods
        always report their identity (shard/shard_count/epoch) so an
        operator can tell WHICH slice a degraded pod owns."""
        if self.supervisor is None:
            snap = {"state": "unsupervised", "started":
                    self._apply_task is not None}
        else:
            snap = self.supervisor.snapshot()
        if self.config.shard is not None:
            snap["shard"] = self.shard_identity.describe() \
                if self.shard_identity is not None else {
                    "shard": self.config.shard,
                    "shard_count": self.config.shard_count,
                    "epoch": None}  # not adopted yet (before start())
        return snap

    async def shutdown(self) -> None:
        self.shutdown_signal.trigger()

    async def shutdown_and_wait(self) -> None:
        await self.shutdown()
        await self.wait()
