"""Table-sync workers: initial copy + catchup + handoff.

Reference parity:
  - `start_table_sync` flow (crates/etl/src/replication/table_sync/mod.rs:97):
    drop pre-existing destination table (crash-consistency rationale at
    mod.rs:184-220), delete+create slot with snapshot, fetch schema inside
    the snapshot, copy, durability barrier, FinishedCopy → SyncWait →
    wait for Catchup → stream via ApplyLoop until SyncDone.
  - `TableSyncWorker` + pool (crates/etl/src/runtime/table_sync/):
    semaphore-bounded concurrency (permit count = max_table_sync_workers,
    pipeline.rs:201-202), panic containment → Errored, retry loop with
    store-backed state rollback (worker.rs:393-532), Notify-based state
    waits with no missed wakeups (worker.rs:211-264).

The pool implements `SyncCoordination` for the apply loop: the merged
store+memory state view (SyncWait/Catchup live only in memory,
lifecycle.rs:218-229), catchup fencing, and ready transitions.
"""

from __future__ import annotations

import asyncio
import traceback
from dataclasses import dataclass, field

from ..config.pipeline import PipelineConfig
from ..models.errors import (ErrorKind, EtlError, RetryKind, retry_directive)
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from ..postgres.slots import table_sync_slot_name
from ..postgres.source import ReplicationSource
from ..retry import RetryPolicy
from ..store.base import PipelineStore
from ..destinations.base import Destination
from ..telemetry.metrics import (ETL_WORKER_ERRORS_TOTAL,
                                 LABEL_WORKER_TYPE, registry)
from . import failpoints
from .apply_loop import ApplyLoop, ExitIntent, TableSyncContext
from .shutdown import ShutdownRequested, ShutdownSignal, or_shutdown
from .state import TableState, TableStateType
from .table_cache import SharedTableCache


@dataclass
class _WorkerHandle:
    table_id: TableId
    task: asyncio.Task
    catchup_target: "asyncio.Future[Lsn]"
    memory_state: TableState | None = None  # SyncWait/Catchup overlay
    done_event: asyncio.Event = field(default_factory=asyncio.Event)


class TableSyncWorkerPool:
    """Owns all table-sync workers of a pipeline; implements
    SyncCoordination for the apply loop."""

    def __init__(self, *, config: PipelineConfig, store: PipelineStore,
                 destination: Destination, source_factory,
                 table_cache: SharedTableCache, shutdown: ShutdownSignal,
                 monitor=None, budget=None, supervisor=None):
        self.config = config
        self.store = store
        self.destination = destination
        self.source_factory = source_factory  # () -> ReplicationSource
        self.cache = table_cache
        self.shutdown = shutdown
        self.monitor = monitor  # MemoryMonitor | None
        self.budget = budget  # BatchBudgetController | None
        self.supervisor = supervisor  # supervision.Supervisor | None
        self._permits = asyncio.Semaphore(config.max_table_sync_workers)
        # unified worker-scoped backoff (etl_tpu/retry.py), built once:
        # same schedule as the apply worker, jitter decorrelates herds
        # of failed tables retrying in lockstep
        self.retry_policy = RetryPolicy.from_config(config.table_retry)
        # pulsed on every cached state transition: the apply loop selects
        # on it so SyncWait/SyncDone handoffs process immediately instead
        # of waiting out the next keepalive (Postgres parity: tablesync
        # workers wake the apply worker; polling cost ~3 keepalive
        # intervals of pure latency per table handoff)
        self.state_changed = asyncio.Event()
        self._workers: dict[TableId, _WorkerHandle] = {}
        self._states_cache: dict[TableId, TableState] = {}
        # transition-maintained index of non-Ready, non-Errored tables:
        # the apply loop consults this every keepalive/commit, so it must
        # be O(#syncing), not O(#tables) (VERDICT r1 weak 7; reference
        # processes transitions with cached state, apply.rs:2874-3441)
        self._syncing: set[TableId] = set()
        self._retry_attempts: dict[TableId, int] = {}
        self._retry_tasks: dict[TableId, asyncio.Task] = {}

    # -- state view ------------------------------------------------------------

    def _merged_state(self, tid: TableId) -> TableState | None:
        h = self._workers.get(tid)
        if h is not None and h.memory_state is not None:
            return h.memory_state
        return self._states_cache.get(tid)

    async def refresh_states(self) -> None:
        self._states_cache = await self.store.get_table_states()
        self._syncing = {
            tid for tid, st in self._states_cache.items()
            if st.type is not TableStateType.READY and not st.is_errored}
        self._update_table_gauges()

    def _cache_state(self, tid: TableId, st: TableState | None) -> None:
        if st is None:
            self._states_cache.pop(tid, None)
            self._syncing.discard(tid)
        else:
            self._states_cache[tid] = st
            if st.type is TableStateType.READY or st.is_errored:
                self._syncing.discard(tid)
            else:
                self._syncing.add(tid)
        self._update_table_gauges()
        self.state_changed.set()

    def _update_table_gauges(self) -> None:
        from ..telemetry.metrics import (ETL_TABLES_ERRORED,
                                         ETL_TABLES_READY, ETL_TABLES_TOTAL,
                                         registry)

        states = self._states_cache
        registry.gauge_set(ETL_TABLES_TOTAL, len(states))
        registry.gauge_set(ETL_TABLES_READY, sum(
            1 for s in states.values() if s.type is TableStateType.READY))
        registry.gauge_set(ETL_TABLES_ERRORED, sum(
            1 for s in states.values() if s.is_errored))

    def table_state(self, tid: TableId) -> TableState | None:
        return self._merged_state(tid)

    def syncing_table_states(self) -> dict[TableId, TableState]:
        out = {}
        for tid in list(self._syncing):
            merged = self._merged_state(tid) or self._states_cache.get(tid)
            if merged is None or merged.type is TableStateType.READY \
                    or merged.is_errored:
                self._syncing.discard(tid)  # self-heal on missed transition
                continue
            out[tid] = merged
        return out

    async def _record_state(self, tid: TableId, st: TableState) -> None:
        if st.is_persistent:
            await self.store.update_table_state(tid, st)
        self._cache_state(tid, st)

    # -- SyncCoordination --------------------------------------------------------

    async def set_catchup(self, table_id: TableId, target: Lsn) -> None:
        h = self._workers.get(table_id)
        if h is None:
            return
        if not h.catchup_target.done():
            h.memory_state = TableState.catchup(target)
            self._cache_state(table_id, h.memory_state)
            h.catchup_target.set_result(target)

    async def wait_for_sync_done_or_errored(self,
                                            table_id: TableId) -> TableState:
        h = self._workers.get(table_id)
        if h is not None:
            await or_shutdown(self.shutdown, h.done_event.wait())
        st = await self.store.get_table_state(table_id)
        self._cache_state(table_id, st or TableState.init())
        return self._states_cache[table_id]

    async def mark_ready(self, table_id: TableId) -> None:
        await self._record_state(table_id, TableState.ready())
        # the table's sync slot + progress row are no longer needed
        h = self._workers.pop(table_id, None)

    async def ensure_worker(self, table_id: TableId) -> None:
        h = self._workers.get(table_id)
        if h is not None and not h.task.done():
            return
        handle = _WorkerHandle(
            table_id=table_id, task=None,  # type: ignore[arg-type]
            catchup_target=asyncio.get_event_loop().create_future())
        worker = TableSyncWorker(pool=self, handle=handle)
        handle.task = asyncio.ensure_future(worker.run())
        self._workers[table_id] = handle

    # -- lifecycle ----------------------------------------------------------------

    async def wait_all(self) -> None:
        # pending timed retries are moot once the pipeline stops
        for t in self._retry_tasks.values():
            if not t.done():
                t.cancel()
        tasks = [h.task for h in self._workers.values()
                 if h.task is not None and not h.task.done()]
        tasks += [t for t in self._retry_tasks.values() if not t.done()]
        self._retry_tasks.clear()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    def active_worker_count(self) -> int:
        return sum(1 for h in self._workers.values()
                   if h.task is not None and not h.task.done())


class TableSyncWorker:
    def __init__(self, *, pool: TableSyncWorkerPool, handle: _WorkerHandle):
        self.pool = pool
        self.h = handle
        self.tid = handle.table_id
        self.config = pool.config
        self.store = pool.store
        self.hb = None  # supervision.Heartbeat | None
        self._restart_requested: asyncio.Event | None = None

    # -- top level: permit + panic containment + retry -----------------------------

    async def run(self) -> None:
        pool = self.pool
        try:
            async with pool._permits:
                await self._run_guarded()
        except ShutdownRequested:
            pass
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # panic containment → Errored  # etl-lint: ignore[cancellation-swallow] — CancelledError re-raised above; containment mirrors reference worker.rs
            await self._mark_errored(e)
        finally:
            self.h.done_event.set()
            if self.hb is not None:
                self.hb.close()
                self.hb = None

    async def _run_guarded(self) -> None:
        try:
            await self._run_sync_supervised()
        except ShutdownRequested:
            raise
        except EtlError as e:
            await self._mark_errored(e)

    async def _run_sync_supervised(self) -> None:
        """Race the sync flow against the supervisor's restart request
        (same shape as ApplyWorker._run_once_supervised): a stall/hang
        detection cancels the flow mid-copy or mid-catchup and parks the
        table Errored with a TIMED retry — rollback + recopy rides the
        existing state machine."""
        if self.pool.supervisor is None:
            return await self._run_sync()
        self._restart_requested = asyncio.Event()
        self.hb = self.pool.supervisor.register(
            f"table_sync:{self.tid}", restartable=True,
            on_restart=self._restart_requested.set)
        run = asyncio.ensure_future(self._run_sync())
        trip = asyncio.ensure_future(self._restart_requested.wait())
        try:
            done, _ = await asyncio.wait({run, trip},
                                         return_when=asyncio.FIRST_COMPLETED)
            if run in done:
                return run.result()
            raise EtlError(
                ErrorKind.STALL_DETECTED,
                f"table-sync worker for table {self.tid} cancelled by the "
                f"supervision watchdog (stalled or hung)")
        finally:
            # drain_cancelled, NOT try/await/except: a hard-kill cancel
            # landing in this finally must still kill us
            from .shutdown import drain_cancelled

            await drain_cancelled(run, trip)

    async def _mark_errored(self, e: BaseException) -> None:
        if isinstance(e, EtlError):
            kind = retry_directive(e).kind
            reason = str(e)
        else:
            kind = RetryKind.TIMED
            reason = f"worker panicked: {e!r}\n{traceback.format_exc()}"
        attempts = self.pool._retry_attempts.get(self.tid, 0)
        if kind is RetryKind.TIMED \
                and attempts + 1 >= self.config.table_retry.max_attempts:
            kind = RetryKind.MANUAL  # escalation (worker.rs:393-532)
        self.pool._retry_attempts[self.tid] = attempts + 1
        registry.counter_inc(ETL_WORKER_ERRORS_TOTAL,
                             labels={LABEL_WORKER_TYPE: "table_sync"})
        st = TableState.errored(reason, retry_policy=kind,
                                retry_attempts=attempts + 1)
        await self.pool._record_state(self.tid, st)
        self.h.memory_state = None
        if kind is RetryKind.TIMED and not self.pool.shutdown.is_triggered:
            # keep a strong reference: the loop holds tasks weakly, and
            # wait_all() must be able to cancel pending retries at shutdown
            self.pool._retry_tasks[self.tid] = asyncio.ensure_future(
                self._timed_retry(attempts + 1))

    async def _timed_retry(self, attempt: int) -> None:
        try:
            delay = self.pool.retry_policy.delay(attempt - 1)
            try:
                await or_shutdown(self.pool.shutdown, asyncio.sleep(delay))
            except ShutdownRequested:
                return
            # rollback to a copy-safe state and respawn
            await self.pool._record_state(self.tid, TableState.init())
            self.pool._workers.pop(self.tid, None)
            await self.pool.ensure_worker(self.tid)
        finally:
            self.pool._retry_tasks.pop(self.tid, None)

    # -- the sync flow ---------------------------------------------------------------

    async def _run_sync(self) -> None:
        pool = self.pool
        store = self.store
        shutdown = pool.shutdown
        slot_name = table_sync_slot_name(self.config.pipeline_id, self.tid,
                                         self.config.shard)
        source: ReplicationSource = pool.source_factory()
        await source.connect()
        try:
            state = await store.get_table_state(self.tid) or TableState.init()
            if state.type is TableStateType.READY:
                return
            if state.type is TableStateType.SYNC_DONE:
                return  # apply worker completes the Ready transition

            if state.type in (TableStateType.INIT, TableStateType.DATA_SYNC,
                              TableStateType.ERRORED):
                consistent_point, schema = await self._copy_phase(
                    source, slot_name)
            else:  # FINISHED_COPY: crashed between copy and catchup →
                # the copy is durable; resume streaming from the slot
                slot = await source.get_slot(slot_name)
                if slot is None or slot.invalidated:
                    # slot lost: the copy cannot be fenced — full recopy
                    consistent_point, schema = await self._copy_phase(
                        source, slot_name)
                else:
                    consistent_point = slot.confirmed_flush_lsn
                    schema = await source.get_table_schema(
                        self.tid, self.config.publication_name)
                    self.pool.cache.set(schema)

            # FinishedCopy → SyncWait (memory-only) → wait for Catchup.
            # The park can last until the apply loop's next commit or
            # keepalive — keep beating so it never reads as a hang
            from ..supervision import beat_while_waiting

            self.h.memory_state = TableState.sync_wait(consistent_point)
            pool._cache_state(self.tid, self.h.memory_state)
            target = await or_shutdown(
                shutdown,
                beat_while_waiting(self.hb,
                                   asyncio.shield(self.h.catchup_target)))
            self.h.memory_state = TableState.catchup(target)
            pool._cache_state(self.tid, self.h.memory_state)

            if target <= consistent_point:
                # nothing to catch up: the snapshot already covers the target
                await store.update_table_state(
                    self.tid, TableState.sync_done(consistent_point))
            else:
                failpoints.fail_point(failpoints.BEFORE_STREAMING)
                stream = await source.start_replication(
                    slot_name, self.config.publication_name, consistent_point)
                ctx = TableSyncContext(
                    table_id=self.tid, progress_key=slot_name,
                    catchup_target=self.h.catchup_target)
                loop = ApplyLoop(
                    ctx=ctx, stream=stream, store=store,
                    destination=pool.destination, table_cache=pool.cache,
                    config=self.config, shutdown=shutdown,
                    start_lsn=consistent_point,
                    monitor=pool.monitor, budget=pool.budget,
                    heartbeat=self.hb, supervisor=pool.supervisor)
                intent = await loop.run()
                if intent is ExitIntent.PAUSE:
                    raise ShutdownRequested()
            # SyncDone recorded; cleanup this worker's resources
            await store.delete_durable_progress(slot_name)
            await source.delete_slot(slot_name)
            self.h.memory_state = None
            pool._cache_state(self.tid,
                              await store.get_table_state(self.tid))
            pool._retry_attempts.pop(self.tid, None)
        finally:
            await source.close()

    async def _copy_phase(self, source: ReplicationSource, slot_name: str
                          ) -> tuple[Lsn, ReplicatedTableSchema]:
        """Drop-recreate copy with snapshot fencing
        (reference table_sync/mod.rs:184-378)."""
        pool = self.pool
        store = self.store
        # 1. destination drop if a previous copy may have written rows.
        # Pass the prior stored schema: after a process restart the
        # destination's in-memory name mapping is empty and the drop would
        # silently no-op without it (schemas are only pruned below, in
        # prepare_table_for_copy, so the prior version is still readable)
        prior = await store.get_destination_metadata(self.tid)
        if prior is not None:
            prior_schema = await store.get_table_schema(self.tid)
            await pool.destination.drop_table(self.tid, prior_schema)
            await store.delete_destination_metadata(self.tid)
        # 2. fresh slot + snapshot
        await source.delete_slot(slot_name)
        await store.prepare_table_for_copy(self.tid)
        failpoints.fail_point(failpoints.BEFORE_SLOT_CREATION)
        created = await source.create_slot(slot_name)
        # 3. schema within the snapshot
        schema = await source.get_table_schema(
            self.tid, self.config.publication_name, created.snapshot_id)
        await store.store_table_schema(schema, 0)
        pool.cache.set(schema)
        # 4. record metadata BEFORE copying: a crash mid-copy (some batches
        # already durable at the destination) must leave a marker so the
        # next attempt drops the half-written table (mod.rs:184-220)
        from ..store.base import DestinationTableMetadata

        await store.update_destination_metadata(DestinationTableMetadata(
            table_id=self.tid,
            destination_table_name=str(schema.name)))
        # 5. copy, then record FinishedCopy
        await self._copy_table(source, schema, created.snapshot_id)
        try:
            from ..telemetry.metrics import (
                ETL_TABLE_COPY_END_TO_END_LAG_BYTES, registry)

            wal_now = await source.get_current_wal_lsn()
            registry.gauge_set(
                ETL_TABLE_COPY_END_TO_END_LAG_BYTES,
                max(0, int(wal_now) - int(created.consistent_point)))
        except EtlError:
            pass  # lag reporting must never fail a copy
        await store.update_table_state(self.tid, TableState.finished_copy())
        failpoints.fail_point(failpoints.AFTER_FINISHED_COPY)
        return created.consistent_point, schema

    async def _copy_table(self, source: ReplicationSource,
                          schema: ReplicatedTableSchema,
                          snapshot_id: str) -> None:
        """Single-connection copy; the CTID-partitioned parallel variant
        lives in runtime/copy.py and is used when the planner estimates
        enough rows."""
        from .copy import parallel_table_copy

        await parallel_table_copy(
            source_factory=self.pool.source_factory, primary_source=source,
            schema=schema, snapshot_id=snapshot_id, config=self.config,
            destination=self.pool.destination, shutdown=self.pool.shutdown,
            monitor=self.pool.monitor, budget=self.pool.budget,
            heartbeat=self.hb, supervisor=self.pool.supervisor)
