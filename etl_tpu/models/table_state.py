"""Table lifecycle state machine.

Reference parity: `TableState` with 8 states —
Init → DataSync → FinishedCopy → SyncWait → Catchup → SyncDone → Ready,
plus Errored{reason, solution, retry_policy}
(crates/etl/src/replication/state/lifecycle.rs:22,196). SyncWait and Catchup
are memory-only coordination states (lifecycle.rs:218-229): they are never
persisted; a restart collapses them back to FinishedCopy.

JSON (de)serialization mirrors the store row format (lifecycle.rs:122-164).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field

from ..models.errors import ErrorKind, EtlError, RetryKind
from ..models.lsn import Lsn


class TableStateType(enum.Enum):
    INIT = "init"
    DATA_SYNC = "data_sync"
    FINISHED_COPY = "finished_copy"
    SYNC_WAIT = "sync_wait"  # memory-only
    CATCHUP = "catchup"  # memory-only
    SYNC_DONE = "sync_done"
    READY = "ready"
    ERRORED = "errored"


# states that may be persisted to the state store
PERSISTENT_STATES = frozenset({
    TableStateType.INIT, TableStateType.DATA_SYNC,
    TableStateType.FINISHED_COPY, TableStateType.SYNC_DONE,
    TableStateType.READY, TableStateType.ERRORED,
})


@dataclass(frozen=True)
class TableState:
    type: TableStateType
    lsn: Lsn | None = None  # SyncWait: snapshot; Catchup: target; SyncDone: done
    # Errored payload:
    reason: str | None = None
    solution: str | None = None
    retry_policy: RetryKind | None = None
    retry_attempts: int = 0

    # -- constructors --------------------------------------------------------

    @classmethod
    def init(cls) -> "TableState":
        return cls(TableStateType.INIT)

    @classmethod
    def data_sync(cls) -> "TableState":
        return cls(TableStateType.DATA_SYNC)

    @classmethod
    def finished_copy(cls) -> "TableState":
        return cls(TableStateType.FINISHED_COPY)

    @classmethod
    def sync_wait(cls, snapshot_lsn: Lsn) -> "TableState":
        return cls(TableStateType.SYNC_WAIT, lsn=snapshot_lsn)

    @classmethod
    def catchup(cls, target_lsn: Lsn) -> "TableState":
        return cls(TableStateType.CATCHUP, lsn=target_lsn)

    @classmethod
    def sync_done(cls, done_lsn: Lsn) -> "TableState":
        return cls(TableStateType.SYNC_DONE, lsn=done_lsn)

    @classmethod
    def ready(cls) -> "TableState":
        return cls(TableStateType.READY)

    @classmethod
    def errored(cls, reason: str, *, solution: str | None = None,
                retry_policy: RetryKind = RetryKind.TIMED,
                retry_attempts: int = 0) -> "TableState":
        return cls(TableStateType.ERRORED, reason=reason, solution=solution,
                   retry_policy=retry_policy, retry_attempts=retry_attempts)

    # -- predicates ----------------------------------------------------------

    @property
    def is_persistent(self) -> bool:
        return self.type in PERSISTENT_STATES

    @property
    def is_errored(self) -> bool:
        return self.type is TableStateType.ERRORED

    @property
    def apply_worker_owns_table(self) -> bool:
        """Only Ready tables are applied by the apply worker; all other
        states are owned by (or waiting for) a table-sync worker
        (single-writer invariant, reference table_cache.rs:10-44)."""
        return self.type is TableStateType.READY

    # -- transitions ---------------------------------------------------------

    _VALID: dict[TableStateType, tuple[TableStateType, ...]] = None  # set below

    def can_transition_to(self, to: TableStateType) -> bool:
        if to is TableStateType.ERRORED or to is TableStateType.INIT:
            return True  # any state may error; INIT = full resync/rollback
        return to in _VALID_TRANSITIONS[self.type]

    def transition_to(self, new: "TableState") -> "TableState":
        if not self.can_transition_to(new.type):
            raise EtlError(
                ErrorKind.INVALID_STATE_TRANSITION,
                f"{self.type.value} → {new.type.value}")
        return new

    # -- serialization (persistent states only) ------------------------------

    def to_json(self) -> str:
        if not self.is_persistent:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"{self.type.value} is memory-only")
        doc: dict = {"state": self.type.value}
        if self.type is TableStateType.SYNC_DONE:
            doc["lsn"] = str(self.lsn)
        if self.type is TableStateType.ERRORED:
            doc.update(reason=self.reason, solution=self.solution,
                       retry_policy=(self.retry_policy or RetryKind.TIMED).value,
                       retry_attempts=self.retry_attempts)
        return json.dumps(doc)

    @classmethod
    def from_json(cls, raw: str) -> "TableState":
        try:
            doc = json.loads(raw)
            t = TableStateType(doc["state"])
            if t is TableStateType.SYNC_DONE:
                return cls.sync_done(Lsn(doc["lsn"]))
            if t is TableStateType.ERRORED:
                return cls.errored(
                    doc.get("reason") or "",
                    solution=doc.get("solution"),
                    retry_policy=RetryKind(doc.get("retry_policy", "timed")),
                    retry_attempts=doc.get("retry_attempts", 0))
            return cls(t)
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"bad table state row: {e}")


_VALID_TRANSITIONS: dict[TableStateType, tuple[TableStateType, ...]] = {
    TableStateType.INIT: (TableStateType.DATA_SYNC,),
    TableStateType.DATA_SYNC: (TableStateType.FINISHED_COPY,),
    TableStateType.FINISHED_COPY: (TableStateType.SYNC_WAIT,),
    TableStateType.SYNC_WAIT: (TableStateType.CATCHUP,),
    TableStateType.CATCHUP: (TableStateType.SYNC_DONE,),
    TableStateType.SYNC_DONE: (TableStateType.READY,),
    TableStateType.READY: (),
    TableStateType.ERRORED: (TableStateType.DATA_SYNC,),  # retry path
}
