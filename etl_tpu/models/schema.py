"""Table schemas, replication/identity masks, schema diffs.

Reference parity:
  - `TableId`/`TableName`/`ColumnSchema`/`TableSchema`
    (crates/etl-postgres/src/schema.rs:213-286)
  - `ReplicationMask`/`IdentityMask`/`ReplicatedTableSchema`
    (crates/etl/src/schema.rs:69,207,344) — bit-per-column masks over the
    schema's column order; the replicated view is the positional decode view
    used by pgoutput tuple decode.
  - `SchemaDiff`/`ColumnChange` (crates/etl/src/schema.rs:729-770).

TPU-first notes: masks are also exposed as numpy bool vectors
(`as_bool_array`) so publication column filtering can be applied on device
as a gather over replicated column indices.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Iterable, Sequence

import numpy as np

from .pgtypes import CellKind, kind_for_oid, type_name

TableId = int  # pg_class OID of the table
SnapshotId = int  # LSN of the DDL message creating a schema version (0 = initial)


@dataclass(frozen=True, slots=True)
class TableName:
    schema: str
    name: str

    def __str__(self) -> str:
        return f"{self.schema}.{self.name}"

    def quoted(self) -> str:
        s = self.schema.replace('"', '""')
        n = self.name.replace('"', '""')
        return f'"{s}"."{n}"'


@dataclass(frozen=True, slots=True)
class ColumnSchema:
    """One column. `primary_key_ordinal` is the 1-based position in the PK,
    or None (reference: ColumnSchema, etl-postgres/src/schema.rs:213)."""

    name: str
    type_oid: int
    modifier: int = -1
    nullable: bool = True
    primary_key_ordinal: int | None = None
    default_expression: str | None = None

    @property
    def kind(self) -> CellKind:
        return kind_for_oid(self.type_oid)

    @property
    def is_primary_key(self) -> bool:
        return self.primary_key_ordinal is not None

    @property
    def type_name(self) -> str:
        return type_name(self.type_oid)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "type_oid": self.type_oid,
            "modifier": self.modifier,
            "nullable": self.nullable,
            "primary_key_ordinal": self.primary_key_ordinal,
            "default_expression": self.default_expression,
        }

    @classmethod
    def from_json(cls, d: dict) -> "ColumnSchema":
        return cls(
            name=d["name"],
            type_oid=d["type_oid"],
            modifier=d.get("modifier", -1),
            nullable=d.get("nullable", True),
            primary_key_ordinal=d.get("primary_key_ordinal"),
            default_expression=d.get("default_expression"),
        )


@dataclass(frozen=True)
class TableSchema:
    id: TableId
    name: TableName
    columns: tuple[ColumnSchema, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "columns", tuple(self.columns))

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def primary_key_columns(self) -> list[ColumnSchema]:
        pk = [c for c in self.columns if c.is_primary_key]
        pk.sort(key=lambda c: c.primary_key_ordinal or 0)
        return pk

    def has_primary_key(self) -> bool:
        return any(c.is_primary_key for c in self.columns)

    def to_json(self) -> dict:
        return {
            "id": self.id,
            "schema": self.name.schema,
            "name": self.name.name,
            "columns": [c.to_json() for c in self.columns],
        }

    @classmethod
    def from_json(cls, d: dict) -> "TableSchema":
        return cls(
            id=d["id"],
            name=TableName(d["schema"], d["name"]),
            columns=tuple(ColumnSchema.from_json(c) for c in d["columns"]),
        )


class ColumnMask:
    """Immutable bit-per-column mask over a table schema's column order
    (reference: ReplicationMask/IdentityMask, crates/etl/src/schema.rs:69,207)."""

    __slots__ = ("_bits", "_n")

    def __init__(self, bits: Iterable[bool]):
        b = tuple(bool(x) for x in bits)
        self._bits = b
        self._n = len(b)

    @classmethod
    def all_set(cls, n: int) -> "ColumnMask":
        return cls([True] * n)

    @classmethod
    def from_column_names(cls, schema: TableSchema, names: Iterable[str]) -> "ColumnMask":
        wanted = set(names)
        return cls(c.name in wanted for c in schema.columns)

    @classmethod
    def from_bytes(cls, raw: bytes, n: int) -> "ColumnMask":
        # packed little-endian bit order, one bit per column
        return cls(bool(raw[i // 8] & (1 << (i % 8))) for i in range(n))

    def to_bytes(self) -> bytes:
        out = bytearray((self._n + 7) // 8)
        for i, b in enumerate(self._bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> bool:
        return self._bits[i]

    def __iter__(self):
        return iter(self._bits)

    def __eq__(self, other) -> bool:
        return isinstance(other, ColumnMask) and self._bits == other._bits

    def __hash__(self) -> int:
        return hash(self._bits)

    def __repr__(self) -> str:
        return "ColumnMask(" + ",".join("1" if b else "0" for b in self._bits) + ")"

    def count(self) -> int:
        return sum(self._bits)

    def indices(self) -> list[int]:
        return [i for i, b in enumerate(self._bits) if b]

    def as_bool_array(self) -> np.ndarray:
        return np.asarray(self._bits, dtype=np.bool_)


class ReplicatedTableSchema:
    """A table schema plus its replication & identity masks: the positional
    view that pgoutput tuples decode against (reference:
    crates/etl/src/schema.rs:344; ordering rationale at apply.rs:2386-2394 —
    pgoutput RELATION messages list only replicated columns, in schema order).
    """

    __slots__ = ("table_schema", "replication_mask", "identity_mask",
                 "row_predicate", "_replicated_columns", "_replicated_indices")

    def __init__(self, table_schema: TableSchema, replication_mask: ColumnMask,
                 identity_mask: ColumnMask, row_predicate=None):
        n = len(table_schema.columns)
        if len(replication_mask) != n or len(identity_mask) != n:
            raise ValueError("mask length != column count")
        self.table_schema = table_schema
        self.replication_mask = replication_mask
        self.identity_mask = identity_mask
        # publication row filter (ops/predicate.RowFilter | None): the
        # WHERE clause this table's publication carries. The decode engine
        # compiles it into the fused device program (coerce → filter →
        # transpose with in-kernel compaction); kept OUT of __eq__ —
        # schema-diff semantics compare the positional decode view, and a
        # filter change is a publication change, not a DDL change.
        self.row_predicate = row_predicate
        self._replicated_indices = replication_mask.indices()
        self._replicated_columns = tuple(
            table_schema.columns[i] for i in self._replicated_indices
        )

    def with_row_predicate(self, row_predicate) -> "ReplicatedTableSchema":
        """Copy with the publication row filter attached (None detaches).
        Identity-preserving when nothing changes — the table cache's
        `is`-based decoder reuse must survive RELATION re-sends."""
        if row_predicate is self.row_predicate:
            return self
        return ReplicatedTableSchema(self.table_schema, self.replication_mask,
                                     self.identity_mask, row_predicate)

    @classmethod
    def with_all_columns(cls, schema: TableSchema) -> "ReplicatedTableSchema":
        n = len(schema.columns)
        identity = ColumnMask(c.is_primary_key for c in schema.columns)
        if identity.count() == 0:
            identity = ColumnMask.all_set(n)  # replica identity full fallback
        return cls(schema, ColumnMask.all_set(n), identity)

    @property
    def id(self) -> TableId:
        return self.table_schema.id

    @property
    def name(self) -> TableName:
        return self.table_schema.name

    @property
    def replicated_columns(self) -> tuple[ColumnSchema, ...]:
        return self._replicated_columns

    @property
    def replicated_indices(self) -> list[int]:
        return self._replicated_indices

    def replicated_column_count(self) -> int:
        return len(self._replicated_columns)

    def identity_columns(self) -> list[ColumnSchema]:
        return [self.table_schema.columns[i] for i in self.identity_mask.indices()]

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ReplicatedTableSchema)
            and self.table_schema == other.table_schema
            and self.replication_mask == other.replication_mask
            and self.identity_mask == other.identity_mask
        )

    def to_json(self) -> dict:
        out = {
            "table": self.table_schema.to_json(),
            "replicated": self.replication_mask.indices(),
            "identity": self.identity_mask.indices(),
        }
        if self.row_predicate is not None:
            out["row_filter"] = self.row_predicate.to_json()
        return out

    @classmethod
    def from_json(cls, d: dict) -> "ReplicatedTableSchema":
        schema = TableSchema.from_json(d["table"])
        n = len(schema.columns)
        repl = set(d["replicated"])
        ident = set(d["identity"])
        pred = None
        if d.get("row_filter") is not None:
            from ..ops.predicate import RowFilter  # late: models←ops cycle

            pred = RowFilter.from_json(d["row_filter"])
        return cls(schema,
                   ColumnMask(i in repl for i in range(n)),
                   ColumnMask(i in ident for i in range(n)),
                   row_predicate=pred)

    def __repr__(self) -> str:
        return (f"ReplicatedTableSchema({self.table_schema.name}, "
                f"repl={self.replication_mask}, ident={self.identity_mask})")


@dataclass(frozen=True, slots=True)
class ColumnModification:
    """A changed column attribute (reference ColumnModification,
    crates/etl/src/schema.rs:745)."""

    name: str
    old: ColumnSchema
    new: ColumnSchema

    @property
    def type_changed(self) -> bool:
        return (self.old.type_oid, self.old.modifier) != (self.new.type_oid, self.new.modifier)

    @property
    def nullability_changed(self) -> bool:
        return self.old.nullable != self.new.nullable


@dataclass(frozen=True)
class SchemaDiff:
    """Column-level diff between two schema versions, for destination DDL
    (reference SchemaDiff, crates/etl/src/schema.rs:729-770)."""

    added: tuple[ColumnSchema, ...] = ()
    dropped: tuple[ColumnSchema, ...] = ()
    modified: tuple[ColumnModification, ...] = ()

    def is_empty(self) -> bool:
        return not (self.added or self.dropped or self.modified)

    @classmethod
    def between(cls, old: TableSchema, new: TableSchema) -> "SchemaDiff":
        old_by_name = {c.name: c for c in old.columns}
        new_by_name = {c.name: c for c in new.columns}
        added = tuple(c for c in new.columns if c.name not in old_by_name)
        dropped = tuple(c for c in old.columns if c.name not in new_by_name)
        modified = tuple(
            ColumnModification(name, old_by_name[name], new_by_name[name])
            for name in (set(old_by_name) & set(new_by_name))
            if old_by_name[name] != new_by_name[name]
        )
        return cls(added=added, dropped=dropped,
                   modified=tuple(sorted(modified, key=lambda m: m.name)))


def apply_column_changes(schema: TableSchema, new_columns: Sequence[ColumnSchema]) -> TableSchema:
    """New schema version with replaced column list (same id/name)."""
    return replace(schema, columns=tuple(new_columns))
