"""Postgres type registry: OIDs → logical cell kinds.

TPU-first divergence from the reference: the reference tags every value with
its type (`Cell` enum, crates/etl/src/data/cell.rs:19). Here values are plain
Python objects / columnar buffers and the *schema* carries the type, so that
batches can be staged to the device as homogeneous typed columns without
per-cell dispatch. `CellKind` is the logical type vocabulary shared by the
CPU codecs, the TPU decode kernels, and the destinations.
"""

from __future__ import annotations

from enum import IntEnum


class Oid:
    """Well-known pg_type OIDs (stable across all supported PG versions)."""

    BOOL = 16
    BYTEA = 17
    CHAR = 18
    NAME = 19
    INT8 = 20
    INT2 = 21
    INT4 = 23
    TEXT = 25
    OID = 26
    JSON = 114
    XML = 142
    FLOAT4 = 700
    FLOAT8 = 701
    BPCHAR = 1042
    VARCHAR = 1043
    DATE = 1082
    TIME = 1083
    TIMESTAMP = 1114
    TIMESTAMPTZ = 1184
    INTERVAL = 1186
    TIMETZ = 1266
    NUMERIC = 1700
    UUID = 2950
    JSONB = 3802

    # array element → array oid
    BOOL_ARRAY = 1000
    BYTEA_ARRAY = 1001
    CHAR_ARRAY = 1002
    NAME_ARRAY = 1003
    INT2_ARRAY = 1005
    INT4_ARRAY = 1007
    TEXT_ARRAY = 1009
    INT8_ARRAY = 1016
    FLOAT4_ARRAY = 1021
    FLOAT8_ARRAY = 1022
    OID_ARRAY = 1028
    BPCHAR_ARRAY = 1014
    VARCHAR_ARRAY = 1015
    DATE_ARRAY = 1182
    TIME_ARRAY = 1183
    TIMESTAMP_ARRAY = 1115
    TIMESTAMPTZ_ARRAY = 1185
    INTERVAL_ARRAY = 1187
    TIMETZ_ARRAY = 1270
    NUMERIC_ARRAY = 1231
    UUID_ARRAY = 2951
    JSON_ARRAY = 199
    JSONB_ARRAY = 3807


class CellKind(IntEnum):
    """Logical value types, mirroring the reference's Cell variants
    (crates/etl/src/data/cell.rs:19-58) minus the per-value tagging."""

    NULL = 0
    BOOL = 1
    STRING = 2
    I16 = 3
    I32 = 4
    U32 = 5
    I64 = 6
    F32 = 7
    F64 = 8
    NUMERIC = 9
    DATE = 10
    TIME = 11
    TIMETZ = 12
    TIMESTAMP = 13
    TIMESTAMPTZ = 14
    UUID = 15
    JSON = 16
    BYTES = 17
    ARRAY = 18
    INTERVAL = 19


# element-kind for arrays, by array OID
_ARRAY_ELEM: dict[int, tuple[int, CellKind]] = {
    Oid.BOOL_ARRAY: (Oid.BOOL, CellKind.BOOL),
    Oid.BYTEA_ARRAY: (Oid.BYTEA, CellKind.BYTES),
    Oid.CHAR_ARRAY: (Oid.CHAR, CellKind.STRING),
    Oid.NAME_ARRAY: (Oid.NAME, CellKind.STRING),
    Oid.INT2_ARRAY: (Oid.INT2, CellKind.I16),
    Oid.INT4_ARRAY: (Oid.INT4, CellKind.I32),
    Oid.TEXT_ARRAY: (Oid.TEXT, CellKind.STRING),
    Oid.INT8_ARRAY: (Oid.INT8, CellKind.I64),
    Oid.FLOAT4_ARRAY: (Oid.FLOAT4, CellKind.F32),
    Oid.FLOAT8_ARRAY: (Oid.FLOAT8, CellKind.F64),
    Oid.OID_ARRAY: (Oid.OID, CellKind.U32),
    Oid.BPCHAR_ARRAY: (Oid.BPCHAR, CellKind.STRING),
    Oid.VARCHAR_ARRAY: (Oid.VARCHAR, CellKind.STRING),
    Oid.DATE_ARRAY: (Oid.DATE, CellKind.DATE),
    Oid.TIME_ARRAY: (Oid.TIME, CellKind.TIME),
    Oid.TIMESTAMP_ARRAY: (Oid.TIMESTAMP, CellKind.TIMESTAMP),
    Oid.TIMESTAMPTZ_ARRAY: (Oid.TIMESTAMPTZ, CellKind.TIMESTAMPTZ),
    Oid.INTERVAL_ARRAY: (Oid.INTERVAL, CellKind.INTERVAL),
    Oid.TIMETZ_ARRAY: (Oid.TIMETZ, CellKind.TIMETZ),
    Oid.NUMERIC_ARRAY: (Oid.NUMERIC, CellKind.NUMERIC),
    Oid.UUID_ARRAY: (Oid.UUID, CellKind.UUID),
    Oid.JSON_ARRAY: (Oid.JSON, CellKind.JSON),
    Oid.JSONB_ARRAY: (Oid.JSONB, CellKind.JSON),
}

_SCALAR_KIND: dict[int, CellKind] = {
    Oid.BOOL: CellKind.BOOL,
    Oid.BYTEA: CellKind.BYTES,
    Oid.CHAR: CellKind.STRING,
    Oid.NAME: CellKind.STRING,
    Oid.INT8: CellKind.I64,
    Oid.INT2: CellKind.I16,
    Oid.INT4: CellKind.I32,
    Oid.TEXT: CellKind.STRING,
    Oid.OID: CellKind.U32,
    Oid.JSON: CellKind.JSON,
    Oid.XML: CellKind.STRING,
    Oid.FLOAT4: CellKind.F32,
    Oid.FLOAT8: CellKind.F64,
    Oid.BPCHAR: CellKind.STRING,
    Oid.VARCHAR: CellKind.STRING,
    Oid.DATE: CellKind.DATE,
    Oid.TIME: CellKind.TIME,
    Oid.TIMESTAMP: CellKind.TIMESTAMP,
    Oid.TIMESTAMPTZ: CellKind.TIMESTAMPTZ,
    Oid.INTERVAL: CellKind.INTERVAL,
    Oid.TIMETZ: CellKind.TIMETZ,
    Oid.NUMERIC: CellKind.NUMERIC,
    Oid.UUID: CellKind.UUID,
    Oid.JSONB: CellKind.JSON,
}

_NAMES: dict[int, str] = {
    Oid.BOOL: "bool", Oid.BYTEA: "bytea", Oid.CHAR: "char", Oid.NAME: "name",
    Oid.INT8: "int8", Oid.INT2: "int2", Oid.INT4: "int4", Oid.TEXT: "text",
    Oid.OID: "oid", Oid.JSON: "json", Oid.XML: "xml", Oid.FLOAT4: "float4",
    Oid.FLOAT8: "float8", Oid.BPCHAR: "bpchar", Oid.VARCHAR: "varchar",
    Oid.DATE: "date", Oid.TIME: "time", Oid.TIMESTAMP: "timestamp",
    Oid.TIMESTAMPTZ: "timestamptz", Oid.INTERVAL: "interval",
    Oid.TIMETZ: "timetz", Oid.NUMERIC: "numeric", Oid.UUID: "uuid",
    Oid.JSONB: "jsonb",
}


def kind_for_oid(oid: int) -> CellKind:
    """Logical kind for a pg_type OID; unknown OIDs decode as STRING, matching
    the reference's fall-through to `Cell::String` for unsupported types."""
    if oid in _ARRAY_ELEM:
        return CellKind.ARRAY
    return _SCALAR_KIND.get(oid, CellKind.STRING)


def array_element(oid: int) -> tuple[int, CellKind] | None:
    """(element oid, element kind) if `oid` is a supported array type."""
    return _ARRAY_ELEM.get(oid)


def is_array_oid(oid: int) -> bool:
    return oid in _ARRAY_ELEM


def type_name(oid: int) -> str:
    if oid in _ARRAY_ELEM:
        return "_" + _NAMES.get(_ARRAY_ELEM[oid][0], str(oid))
    return _NAMES.get(oid, f"oid:{oid}")
