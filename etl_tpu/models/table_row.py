"""Rows and columnar batches.

Reference parity: `TableRow`/`PartialTableRow`/`UpdatedTableRow`/`OldTableRow`
(crates/etl/src/data/table_row.rs:15,68,145,193) and `SizeHint`
(crates/etl/src/data/size.rs) used for batch byte budgeting.

TPU-first addition: `ColumnarBatch` — the typed columnar form produced by the
device decode path (and by CPU transpose), carried across the Destination
boundary so Arrow-native writers never re-serialize row-by-row. It converts
losslessly to a pyarrow RecordBatch.
"""

from __future__ import annotations

import datetime as dt
import sys
from dataclasses import dataclass, field
from typing import Any, Iterator, Sequence

import numpy as np

from .cell import (TOAST_UNCHANGED, PgInterval, PgNumeric, PgSpecialDate,
                   PgSpecialTimestamp, PgTimeTz)
from .pgtypes import CellKind
from .schema import ColumnSchema, ReplicatedTableSchema


def value_size_hint(v: Any) -> int:
    """Approximate in-memory size of a decoded value, for batch budgeting
    (reference SizeHint, crates/etl/src/data/size.rs). Cheap, not exact."""
    if v is None or v is TOAST_UNCHANGED:
        return 8
    if isinstance(v, bool):
        return 8
    if isinstance(v, int):
        return 16
    if isinstance(v, float):
        return 16
    if isinstance(v, str):
        return 48 + len(v)
    if isinstance(v, bytes):
        return 32 + len(v)
    if isinstance(v, (dt.datetime, dt.date, dt.time)):
        return 48
    if isinstance(v, PgNumeric):
        return 64
    if isinstance(v, (list, tuple)):
        return 16 + sum(value_size_hint(x) for x in v)
    if isinstance(v, dict):
        return 64 + sum(value_size_hint(k) + value_size_hint(x) for k, x in v.items())
    return 64


#: process-wide count of TableRow constructions (mutable cell so the hot
#: path pays one list-index increment, no attribute lookup on a registry).
#: The columnar egress path never builds rows, so bench.py --smoke asserts
#: this counter's delta over the streamed CDC window is ZERO — the row
#: path creeping back into egress fails CI instead of silently eating the
#: decode speedups (ROADMAP item 2).
_ROWS_CONSTRUCTED = [0]


def rows_constructed() -> int:
    """Monotonic count of TableRow/PartialTableRow constructions."""
    return _ROWS_CONSTRUCTED[0]


class TableRow:
    """One decoded row: positional values matching a ReplicatedTableSchema's
    replicated columns (reference TableRow, data/table_row.rs:15)."""

    __slots__ = ("values", "_size_hint")

    def __init__(self, values: Sequence[Any]):
        _ROWS_CONSTRUCTED[0] += 1
        self.values = list(values)
        self._size_hint: int | None = None

    def size_hint(self) -> int:
        if self._size_hint is None:
            self._size_hint = 16 + sum(value_size_hint(v) for v in self.values)
        return self._size_hint

    def __len__(self) -> int:
        return len(self.values)

    def __getitem__(self, i: int) -> Any:
        return self.values[i]

    def __iter__(self) -> Iterator[Any]:
        return iter(self.values)

    def __eq__(self, other) -> bool:
        return isinstance(other, TableRow) and self.values == other.values

    def __repr__(self) -> str:
        return f"TableRow({self.values!r})"


class PartialTableRow(TableRow):
    """A row where only identity columns are populated (DELETE old tuples /
    key-only old tuples; reference PartialTableRow, table_row.rs:68).
    Non-identity positions hold None and `present` marks real values."""

    __slots__ = ("present",)

    def __init__(self, values: Sequence[Any], present: Sequence[bool]):
        super().__init__(values)
        self.present = list(present)

    def __repr__(self) -> str:
        return f"PartialTableRow({self.values!r}, present={self.present!r})"


# dtypes for the dense device-decodable kinds
_NUMPY_DTYPE: dict[CellKind, np.dtype] = {
    CellKind.BOOL: np.dtype(np.bool_),
    CellKind.I16: np.dtype(np.int16),
    CellKind.I32: np.dtype(np.int32),
    CellKind.U32: np.dtype(np.uint32),
    CellKind.I64: np.dtype(np.int64),
    CellKind.F32: np.dtype(np.float32),
    CellKind.F64: np.dtype(np.float64),
    CellKind.DATE: np.dtype(np.int32),      # days since 1970-01-01
    CellKind.TIME: np.dtype(np.int64),      # microseconds since midnight
    CellKind.TIMESTAMP: np.dtype(np.int64),  # microseconds since epoch (naive)
    CellKind.TIMESTAMPTZ: np.dtype(np.int64),  # microseconds since epoch UTC
}


def dense_dtype(kind: CellKind) -> np.dtype | None:
    """numpy dtype for kinds the device decodes densely; None for object kinds
    (strings, bytes, json, numeric-exact, arrays) which stay host-side."""
    return _NUMPY_DTYPE.get(kind)


@dataclass
class Column:
    """One typed column of a batch: dense numpy data + validity, or a Python
    object list for host-side kinds. `toast_unchanged[i]` marks cells whose
    value pgoutput did not re-send (TOAST 'u' kind) — distinct from NULL so
    CDC destinations can skip instead of overwrite (reference TOAST handling,
    codec/event.rs)."""

    schema: ColumnSchema
    data: Any  # np.ndarray (dense) | pyarrow.Array (text) | list (object)
    validity: np.ndarray  # bool[n], True = value present (not NULL/unchanged)
    toast_unchanged: np.ndarray | None = None  # bool[n] or None if none set
    # Arrow-text columns may carry UNPARSED Postgres text for typed kinds
    # (numeric/uuid/json/…): exact for Arrow consumers, parsed lazily via
    # value(). None = data is already the final representation.
    lazy_text_oid: int | None = None

    def __len__(self) -> int:
        return len(self.validity)

    @property
    def is_dense(self) -> bool:
        return isinstance(self.data, np.ndarray)

    @property
    def is_arrow(self) -> bool:
        import pyarrow as pa

        return isinstance(self.data, pa.Array)

    def take(self, rows: np.ndarray) -> "Column":
        """Row-gather of this column by index array (the host half of
        publication row-filter compaction): dense data gathers as numpy,
        Arrow text via Arrow take (no python objects), object lists by
        comprehension."""
        if self.is_dense:
            data: Any = self.data[rows]
        elif self.is_arrow:
            import pyarrow as pa

            data = self.data.take(pa.array(rows, type=pa.int64()))
        else:
            data = [self.data[int(i)] for i in rows]
        toast = self.toast_unchanged[rows] \
            if self.toast_unchanged is not None else None
        return Column(self.schema, data, self.validity[rows], toast,
                      lazy_text_oid=self.lazy_text_oid)

    def value(self, i: int) -> Any:
        """Python value at row i regardless of storage form."""
        if self.is_toast_unchanged(i):
            return TOAST_UNCHANGED
        if not self.validity[i]:
            return None
        if self.is_dense:
            return _from_dense(self.schema.kind, self.data[i])
        if self.is_arrow:
            raw = self.data[i].as_py()
            if self.lazy_text_oid is not None:
                from ..postgres.codec.text import parse_cell_text

                return parse_cell_text(raw, self.lazy_text_oid)
            return raw
        return self.data[i]

    def is_toast_unchanged(self, i: int) -> bool:
        return self.toast_unchanged is not None and bool(self.toast_unchanged[i])


class ColumnarBatch:
    """Typed columnar rows for one table — the unit the TPU decode engine
    emits and Arrow-native destinations consume.

    `source_rows` (int64[num_rows] | None) is set by filtered decodes
    only: the staged-batch row index each surviving row came from, so
    consumers holding per-source-row side arrays (the assembler's LSN /
    change-type vectors) can compact them to match.

    `device_egress` (ops/egress.py DeviceEgress | None) is attached by
    unfiltered device decodes whose program rendered wire text in-fused:
    per-column destination-ready byte buffers the columnar encoders
    splice instead of re-rendering. Row-count-preserving only — `take`
    deliberately drops it (the buffers are positional)."""

    __slots__ = ("schema", "columns", "num_rows", "source_rows",
                 "device_egress")

    def __init__(self, schema: ReplicatedTableSchema, columns: list[Column]):
        self.schema = schema
        self.columns = columns
        self.num_rows = len(columns[0]) if columns else 0
        self.source_rows: np.ndarray | None = None
        self.device_egress = None
        for c in columns:
            if len(c) != self.num_rows:
                raise ValueError("ragged columnar batch")

    def take(self, rows: np.ndarray) -> "ColumnarBatch":
        """Row-gather into a new batch (column-at-a-time, no row
        objects); `source_rows` composes through the gather when set."""
        out = ColumnarBatch(self.schema, [c.take(rows) for c in self.columns])
        if self.source_rows is not None:
            out.source_rows = self.source_rows[rows]
        return out

    @classmethod
    def from_rows(cls, schema: ReplicatedTableSchema, rows: Sequence[TableRow]) -> "ColumnarBatch":
        """CPU transpose: list-of-rows → columns (the fallback for what the
        device path produces directly)."""
        return cls.from_cells(
            schema,
            [[r.values[j] for r in rows]
             for j in range(len(schema.replicated_columns))],
            len(rows))

    @classmethod
    def from_cells(cls, schema: ReplicatedTableSchema,
                   cells: Sequence[Sequence[Any]],
                   n: int) -> "ColumnarBatch":
        """Build a batch from per-COLUMN value lists (`cells[j][i]` = column
        j, row i) without ever materializing TableRow objects — the columnar
        form of `from_rows` used by the CPU-engine COPY path."""
        cols_schema = schema.replicated_columns
        columns: list[Column] = []
        for j, cs in enumerate(cols_schema):
            vals = cells[j]
            toast = np.asarray([v is TOAST_UNCHANGED for v in vals], dtype=np.bool_)
            validity = np.asarray(
                [v is not None and v is not TOAST_UNCHANGED for v in vals],
                dtype=np.bool_)
            toast_arr = toast if toast.any() else None
            dtype = dense_dtype(cs.kind)
            if dtype is not None:
                data = np.zeros(n, dtype=dtype)
                for i, v in enumerate(vals):
                    if validity[i]:
                        data[i] = _to_dense(cs.kind, v)
                columns.append(Column(cs, data, validity, toast_arr))
            else:
                columns.append(Column(
                    cs, [v if validity[i] else None for i, v in enumerate(vals)],
                    validity, toast_arr))
        return cls(schema, columns)

    @classmethod
    def concat(cls, batches: "Sequence[ColumnarBatch]") -> "ColumnarBatch":
        """Concatenate same-schema batches column-wise (the coalescing step
        of the columnar CDC write seam: consecutive same-table
        DecodedBatchEvents become ONE destination write). Dense columns
        concatenate as numpy arrays, Arrow text columns as chunk-combined
        Arrow arrays, object columns as list extend — no row objects."""
        if not batches:
            raise ValueError("concat of zero batches")
        if len(batches) == 1:
            return batches[0]
        first = batches[0]
        for b in batches[1:]:
            if b.schema is not first.schema and b.schema != first.schema:
                raise ValueError("concat across schemas")
        n = sum(b.num_rows for b in batches)
        columns: list[Column] = []
        for j, cs in enumerate(first.schema.replicated_columns):
            parts = [b.columns[j] for b in batches]
            validity = np.concatenate([c.validity for c in parts])
            toast = None
            if any(c.toast_unchanged is not None for c in parts):
                toast = np.concatenate([
                    c.toast_unchanged if c.toast_unchanged is not None
                    else np.zeros(len(c), dtype=np.bool_) for c in parts])
            if all(c.is_dense for c in parts):
                data: Any = np.concatenate([c.data for c in parts])
                lazy = None
            elif all(c.is_arrow for c in parts) and len(
                    {c.lazy_text_oid for c in parts}) == 1:
                import pyarrow as pa

                data = pa.chunked_array([c.data for c in parts]).combine_chunks()
                lazy = parts[0].lazy_text_oid
            else:
                # mixed storage (e.g. a fixed-up batch next to an Arrow
                # one): degrade to object values via each column's own
                # accessor — correctness over speed on this rare edge
                data = [c.value(i) for c in parts for i in range(len(c))]
                lazy = None
                validity = np.asarray(
                    [v is not None and v is not TOAST_UNCHANGED
                     for v in data], dtype=np.bool_)
            columns.append(Column(cs, data, validity, toast,
                                  lazy_text_oid=lazy))
        out = cls(first.schema, columns)
        assert out.num_rows == n
        return out

    def to_rows(self) -> list[TableRow]:
        return [TableRow([c.value(i) for c in self.columns])
                for i in range(self.num_rows)]

    def size_hint(self) -> int:
        total = 0
        for c in self.columns:
            if c.is_dense:
                total += c.data.nbytes + c.validity.nbytes
            else:
                total += sum(value_size_hint(v) for v in c.data)
        return total

    def to_arrow(self):
        """Convert to a pyarrow RecordBatch (zero-copy for dense columns).

        NUMERIC columns are emitted as Postgres text strings: exact at any
        precision and able to carry NaN/±Infinity, which Arrow decimal128
        cannot (same stance as the reference's BigQuery string encoding of
        numerics, bigquery/encoding.rs). TOAST-unchanged cells surface as
        nulls here; CDC writers that can skip columns should consult
        `Column.toast_unchanged` instead of using the Arrow form."""
        import pyarrow as pa

        arrays, names = [], []
        for c in self.columns:
            names.append(c.schema.name)
            mask = ~c.validity
            if c.is_arrow:
                arrays.append(c.data)
            elif c.schema.kind is CellKind.NUMERIC and not c.is_dense:
                # exact text form (numeric_mode="f64" stores dense floats
                # instead and takes the plain dense branch below)
                vals = [c.data[i].pg_text() if c.validity[i] else None
                        for i in range(self.num_rows)]
                arrays.append(pa.array(vals, type=pa.string()))
            elif c.schema.kind is CellKind.JSON:
                vals = [_json_text(c.data[i]) if c.validity[i] else None
                        for i in range(self.num_rows)]
                arrays.append(pa.array(vals, type=pa.string()))
            elif c.is_dense:
                kind = c.schema.kind
                if kind is CellKind.DATE:
                    arrays.append(pa.array(c.data, type=pa.date32(), mask=mask))
                elif kind is CellKind.TIME:
                    arrays.append(pa.array(c.data, type=pa.time64("us"), mask=mask))
                elif kind is CellKind.TIMESTAMP:
                    arrays.append(pa.array(c.data, type=pa.timestamp("us"), mask=mask))
                elif kind is CellKind.TIMESTAMPTZ:
                    arrays.append(pa.array(c.data, type=pa.timestamp("us", tz="UTC"), mask=mask))
                else:
                    arrays.append(pa.array(c.data, mask=mask))
            else:
                vals = [None if not c.validity[i] else _arrow_scalar(c.data[i])
                        for i in range(self.num_rows)]
                arrays.append(pa.array(vals))
        return pa.RecordBatch.from_arrays(arrays, names=names)


_EPOCH_DATE = dt.date(1970, 1, 1)
_EPOCH_DT = dt.datetime(1970, 1, 1)
_EPOCH_UTC = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)


_US = dt.timedelta(microseconds=1)

# Dense sentinel encodings and exact bounds of Python's datetime range in
# epoch microseconds / days. PUBLIC: the columnar destination encoders
# (bq_proto._column_cells, clickhouse._column_texts) import these so their
# special-value detection can never drift from what _from_dense decodes.
TS_INFINITY_US = 2**63 - 1
TS_NEG_INFINITY_US = -(2**63)
DATE_INFINITY_DAYS = 2**31 - 1
DATE_NEG_INFINITY_DAYS = -(2**31)
MIN_TS_US = -62_135_596_800_000_000  # 0001-01-01 00:00:00
MAX_TS_US = 253_402_300_799_999_999  # 9999-12-31 23:59:59.999999
MIN_DATE_DAYS = -719_162
MAX_DATE_DAYS = 2_932_896
# former private spellings (kept: ops/engine's CPU fixup imports one)
_MIN_TS_US = MIN_TS_US
_MAX_TS_US = MAX_TS_US
_MIN_DATE_DAYS = MIN_DATE_DAYS
_MAX_DATE_DAYS = MAX_DATE_DAYS


def _to_dense(kind: CellKind, v: Any):
    # integer arithmetic throughout: float total_seconds() corrupts µs
    # beyond 2^53 and overflows on the datetime.max infinity sentinel
    if kind is CellKind.DATE:
        if isinstance(v, PgSpecialDate):
            return v.days
        return (v - _EPOCH_DATE).days
    if kind is CellKind.TIME:
        return ((v.hour * 60 + v.minute) * 60 + v.second) * 1_000_000 + v.microsecond
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        if isinstance(v, PgSpecialTimestamp):
            return v.micros
        if v.tzinfo is None:
            return (v - _EPOCH_DT) // _US
        return (v - _EPOCH_UTC) // _US
    return v


def _from_dense(kind: CellKind, v):
    if kind is CellKind.DATE:
        days = int(v)
        if days == DATE_INFINITY_DAYS:
            return PgSpecialDate(days, "infinity")
        if days == DATE_NEG_INFINITY_DAYS:
            return PgSpecialDate(days, "-infinity")
        if not _MIN_DATE_DAYS <= days <= _MAX_DATE_DAYS:
            return PgSpecialDate(days, f"<out-of-range date {days}d>")
        return _EPOCH_DATE + dt.timedelta(days=days)
    if kind is CellKind.TIME:
        us = int(v)
        s, us = divmod(us, 1_000_000)
        h, rem = divmod(s, 3600)
        m, s = divmod(rem, 60)
        return dt.time(h, m, s, us)
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        us = int(v)
        tz_aware = kind is CellKind.TIMESTAMPTZ
        if us == TS_INFINITY_US:
            return PgSpecialTimestamp(us, "infinity", tz_aware=tz_aware)
        if us == TS_NEG_INFINITY_US:
            return PgSpecialTimestamp(us, "-infinity", tz_aware=tz_aware)
        if not _MIN_TS_US <= us <= _MAX_TS_US:
            return PgSpecialTimestamp(us, f"<out-of-range timestamp {us}us>",
                                      tz_aware=tz_aware)
        if tz_aware:
            return _EPOCH_UTC + dt.timedelta(microseconds=us)
        return _EPOCH_DT + dt.timedelta(microseconds=us)
    if kind is CellKind.BOOL:
        return bool(v)
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64):
        return int(v)
    # remaining dense kinds (floats; NUMERIC under numeric_mode="f64")
    return float(v)


def _json_text(v: Any) -> str:
    """Serialize a decoded JSON column value back to JSON text (Arrow/
    destination form). JSON_NULL is the literal `null`, distinct from SQL
    NULL which is an absent (masked) value."""
    import json

    from .cell import JSON_NULL

    if v is JSON_NULL:
        return "null"
    return json.dumps(v)


def _arrow_scalar(v: Any):
    import uuid as _uuid

    if isinstance(v, _uuid.UUID):
        # host-parsed UUID objects (the device path carries UUIDs as lazy
        # Arrow text and never reaches here): canonical string form, the
        # same rendering every destination uses
        return str(v)
    if isinstance(v, (PgSpecialDate, PgSpecialTimestamp)):
        return v.pg_text()
    if isinstance(v, PgTimeTz):
        return v.pg_text()
    if isinstance(v, PgInterval):
        return v.pg_text()
    if isinstance(v, dict):
        return _json_text(v)
    if v is TOAST_UNCHANGED:
        return None
    from .cell import JSON_NULL

    if v is JSON_NULL:
        return "null"
    return v
