"""Error taxonomy and retry directives.

Reference parity: ~60-variant `ErrorKind` (crates/etl/src/error.rs:85-210),
multi-error aggregation, and `RetryDirective::{Timed, Manual, NoRetry}`
produced by `build_error_handling_policy` (crates/etl/src/runtime/error_policy.rs)
and shared by the apply worker and table-sync workers.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, Sequence


class ErrorKind(enum.Enum):
    # --- source / connection class ---
    SOURCE_CONNECTION_FAILED = enum.auto()
    SOURCE_IO = enum.auto()
    SOURCE_QUERY_FAILED = enum.auto()
    SOURCE_AUTH_FAILED = enum.auto()
    SOURCE_TLS_FAILED = enum.auto()
    SOURCE_PROTOCOL_VIOLATION = enum.auto()
    SOURCE_UNSUPPORTED_VERSION = enum.auto()
    SOURCE_SHUTTING_DOWN = enum.auto()

    # --- replication class ---
    SLOT_NOT_FOUND = enum.auto()
    SLOT_ALREADY_EXISTS = enum.auto()
    SLOT_INVALIDATED = enum.auto()
    SLOT_IN_USE = enum.auto()
    SLOT_NAME_TOO_LONG = enum.auto()
    PUBLICATION_NOT_FOUND = enum.auto()
    PUBLICATION_TABLE_MISSING = enum.auto()
    REPLICATION_STREAM_FAILED = enum.auto()
    REPLICATION_MESSAGE_INVALID = enum.auto()
    SNAPSHOT_EXPORT_FAILED = enum.auto()
    WAL_DECODE_FAILED = enum.auto()

    # --- data / conversion class ---
    ROW_CONVERSION_FAILED = enum.auto()
    UNSUPPORTED_TYPE = enum.auto()
    NULL_CONSTRAINT_VIOLATION = enum.auto()
    INVALID_DATA = enum.auto()
    COPY_FORMAT_INVALID = enum.auto()

    # --- schema class ---
    SOURCE_REPLICA_IDENTITY = enum.auto()  # reference SourceReplicaIdentityError
    SCHEMA_NOT_FOUND = enum.auto()
    SCHEMA_MISMATCH = enum.auto()
    SCHEMA_CHANGE_UNSUPPORTED = enum.auto()
    MISSING_PRIMARY_KEY = enum.auto()
    SCHEMA_SNAPSHOT_INVALID = enum.auto()

    # --- state / store class ---
    STATE_STORE_FAILED = enum.auto()
    STATE_ROLLBACK_FAILED = enum.auto()
    INVALID_STATE_TRANSITION = enum.auto()
    STORE_SERIALIZATION_FAILED = enum.auto()
    PROGRESS_REGRESSION = enum.auto()
    # --- sharding class (etl_tpu/sharding, no reference counterpart) ---
    # a shard-scoped runtime touched a table the shard map assigns to a
    # different shard — a routing bug or a racing rebalance
    SHARD_NOT_OWNED = enum.auto()
    # the pod's adopted epoch no longer matches the store's authoritative
    # assignment: the coordinator flipped underneath a stale pod — the
    # pod must be rolled with the new topology, retrying in place is
    # useless (both kinds are MANUAL, not TIMED)
    SHARD_EPOCH_STALE = enum.auto()

    # --- destination class ---
    DESTINATION_FAILED = enum.auto()
    DESTINATION_CONNECTION_FAILED = enum.auto()
    DESTINATION_AUTH_FAILED = enum.auto()
    DESTINATION_SCHEMA_FAILED = enum.auto()
    DESTINATION_THROTTLED = enum.auto()
    DESTINATION_PAYLOAD_TOO_LARGE = enum.auto()
    # the destination REFUSED the payload (HTTP 4xx / gRPC
    # INVALID_ARGUMENT class): retrying the identical bytes can never
    # succeed — this is the poison-pill trigger signal the isolation
    # protocol (runtime/poison.py) keys on, distinct from
    # DESTINATION_FAILED (ambiguous, worker-retryable) and
    # DESTINATION_THROTTLED (capacity, writer-retryable)
    DESTINATION_REJECTED = enum.auto()
    # circuit breaker open: load shed before the call reaches the sink
    # (supervision/breaker.py) — retryable by the WORKER (whose backoff IS
    # the backpressure), never in place by a writer
    DESTINATION_UNAVAILABLE = enum.auto()

    # --- runtime class ---
    WORKER_PANICKED = enum.auto()
    WORKER_CANCELLED = enum.auto()
    SHUTDOWN_REQUESTED = enum.auto()
    TIMEOUT = enum.auto()
    MEMORY_PRESSURE_ABORT = enum.auto()
    BATCH_OVERFLOW = enum.auto()
    # liveness watchdog: the supervisor cancelled a component whose
    # heartbeat went stale (hang) or whose progress token froze while it
    # claimed work in flight (stall) — retryable: the worker re-streams
    # from durable progress like any transient failure
    STALL_DETECTED = enum.auto()

    # --- device (TPU) class — no reference counterpart ---
    DEVICE_DECODE_FAILED = enum.auto()
    DEVICE_UNAVAILABLE = enum.auto()
    DEVICE_STAGING_OVERFLOW = enum.auto()

    # --- config class ---
    CONFIG_INVALID = enum.auto()
    CONFIG_MISSING = enum.auto()

    # --- generic ---
    UNKNOWN = enum.auto()


class RetryKind(enum.Enum):
    """How a failure should be retried (reference RetryDirective,
    runtime/error_policy.rs)."""

    TIMED = "timed"  # automatic retry with backoff
    MANUAL = "manual"  # park as Errored until operator intervention
    NO_RETRY = "no_retry"  # fatal: propagate and stop the worker


@dataclass(frozen=True, slots=True)
class RetryDirective:
    kind: RetryKind
    # for TIMED: delay schedule handled by RetryConfig; attempts escalate to
    # MANUAL after max_attempts (reference table_sync/worker.rs:393-532)


class EtlError(Exception):
    """Framework error carrying one or more ErrorKinds (multi-error
    aggregation parity with reference error.rs `EtlError::Many`)."""

    def __init__(self, kind: ErrorKind, detail: str = "", *,
                 causes: Sequence["EtlError"] | None = None):
        self.kind = kind
        self.detail = detail
        self.causes: tuple[EtlError, ...] = tuple(causes or ())
        super().__init__(f"{kind.name}: {detail}" if detail else kind.name)

    def kinds(self) -> list[ErrorKind]:
        out = [self.kind]
        for c in self.causes:
            out.extend(c.kinds())
        return out

    @classmethod
    def many(cls, errors: Iterable["EtlError"]) -> "EtlError":
        errs = list(errors)
        if len(errs) == 1:
            return errs[0]
        return cls(ErrorKind.UNKNOWN, f"{len(errs)} errors: " +
                   "; ".join(str(e) for e in errs), causes=errs)

    def __repr__(self) -> str:
        return f"EtlError({self.kind.name}, {self.detail!r})"


def etl_error(kind: ErrorKind, detail: str = "") -> EtlError:
    return EtlError(kind, detail)


# kinds that indicate transient conditions worth automatic retry
_TIMED_KINDS = frozenset({
    ErrorKind.SOURCE_CONNECTION_FAILED,
    ErrorKind.SOURCE_IO,
    ErrorKind.SOURCE_QUERY_FAILED,
    ErrorKind.REPLICATION_STREAM_FAILED,
    ErrorKind.SNAPSHOT_EXPORT_FAILED,
    ErrorKind.SLOT_IN_USE,
    ErrorKind.STATE_STORE_FAILED,
    ErrorKind.DESTINATION_FAILED,
    ErrorKind.DESTINATION_CONNECTION_FAILED,
    ErrorKind.DESTINATION_THROTTLED,
    ErrorKind.DESTINATION_UNAVAILABLE,
    ErrorKind.TIMEOUT,
    ErrorKind.STALL_DETECTED,
    ErrorKind.WORKER_PANICKED,
    ErrorKind.DEVICE_UNAVAILABLE,
    ErrorKind.UNKNOWN,
})

# kinds that are permanent but operator-fixable: park the table, don't retry
_MANUAL_KINDS = frozenset({
    ErrorKind.SOURCE_AUTH_FAILED,
    ErrorKind.SOURCE_TLS_FAILED,
    ErrorKind.SOURCE_UNSUPPORTED_VERSION,
    ErrorKind.SLOT_INVALIDATED,
    ErrorKind.PUBLICATION_NOT_FOUND,
    ErrorKind.PUBLICATION_TABLE_MISSING,
    ErrorKind.MISSING_PRIMARY_KEY,
    ErrorKind.SOURCE_REPLICA_IDENTITY,
    ErrorKind.SCHEMA_MISMATCH,
    ErrorKind.SCHEMA_CHANGE_UNSUPPORTED,
    ErrorKind.SHARD_NOT_OWNED,
    ErrorKind.SHARD_EPOCH_STALE,
    ErrorKind.UNSUPPORTED_TYPE,
    ErrorKind.ROW_CONVERSION_FAILED,
    ErrorKind.INVALID_DATA,
    ErrorKind.COPY_FORMAT_INVALID,
    ErrorKind.DESTINATION_AUTH_FAILED,
    ErrorKind.DESTINATION_SCHEMA_FAILED,
    ErrorKind.DESTINATION_PAYLOAD_TOO_LARGE,
    ErrorKind.DESTINATION_REJECTED,
    ErrorKind.CONFIG_INVALID,
    ErrorKind.CONFIG_MISSING,
    ErrorKind.DEVICE_DECODE_FAILED,
})


# kinds a destination WRITE can raise that are PERMANENT for the exact
# payload written: retrying the identical bytes can never succeed, so
# the failure is attributable to the batch content (a poison pill), not
# to the destination's health. The isolation protocol
# (runtime/poison.py) triggers ONLY on these — transient kinds
# (throttle, connection, breaker-open DESTINATION_UNAVAILABLE) mean the
# destination is sick and bisecting would hammer a down service.
POISON_KINDS = frozenset({
    ErrorKind.DESTINATION_REJECTED,
    ErrorKind.DESTINATION_SCHEMA_FAILED,
    ErrorKind.DESTINATION_PAYLOAD_TOO_LARGE,
    ErrorKind.SCHEMA_MISMATCH,
    ErrorKind.ROW_CONVERSION_FAILED,
    ErrorKind.INVALID_DATA,
    ErrorKind.UNSUPPORTED_TYPE,
    ErrorKind.NULL_CONSTRAINT_VIOLATION,
})


def is_poison_error(error: BaseException) -> bool:
    """True when a destination-write failure is attributable to the
    PAYLOAD (permanent for those bytes — the poison-pill trigger), not
    to the destination's health. Aggregated errors are poison only if
    EVERY kind is: one transient cause means the whole write may
    succeed on retry, so isolation must not bisect."""
    if not isinstance(error, EtlError):
        return False
    kinds = set(error.kinds())
    return bool(kinds) and kinds <= POISON_KINDS


def retry_directive(error: EtlError) -> RetryDirective:
    """Map an error to its retry directive (reference
    build_error_handling_policy, runtime/error_policy.rs). Aggregated errors
    take the most conservative directive of their parts
    (NO_RETRY > MANUAL > TIMED)."""
    kinds = set(error.kinds())
    if ErrorKind.SHUTDOWN_REQUESTED in kinds or ErrorKind.WORKER_CANCELLED in kinds:
        return RetryDirective(RetryKind.NO_RETRY)
    if kinds & _MANUAL_KINDS:
        return RetryDirective(RetryKind.MANUAL)
    if kinds & _TIMED_KINDS:
        return RetryDirective(RetryKind.TIMED)
    return RetryDirective(RetryKind.TIMED)
