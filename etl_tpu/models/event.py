"""Replication events.

Reference parity: `Event` enum Begin/Commit/Insert/Update/Delete/Truncate/
Relation each carrying `start_lsn`, `commit_lsn`, `tx_ordinal` and its
`ReplicatedTableSchema` (crates/etl/src/event.rs:21-320);
`EventSequenceKey = commit_lsn/tx_ordinal` (event.rs:323).

TPU-first addition: `DecodedBatchEvent` — a run of same-table row changes
already decoded into a `ColumnarBatch` by the device engine, with per-row
change types and ordinals. The CPU path emits per-row events; the TPU path
emits batch events. Destinations accept both (destinations/base.py expands
batches for row-oriented writers).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Sequence, Union

import numpy as np

from .lsn import Lsn
from .schema import ReplicatedTableSchema, TableId
from .table_row import ColumnarBatch, PartialTableRow, TableRow


@dataclass(frozen=True, slots=True, order=True)
class EventSequenceKey:
    """Total order of row changes within the WAL stream: commit LSN of the
    owning transaction, then statement ordinal within it (event.rs:323)."""

    commit_lsn: Lsn
    tx_ordinal: int

    def with_ordinal(self, ordinal: int) -> str:
        """Hex sequence string used by CDC destinations (reference BigQuery
        `_CHANGE_SEQUENCE_NUMBER`, bigquery/core.rs:980-996)."""
        return f"{int(self.commit_lsn):016x}/{self.tx_ordinal:016x}/{ordinal:016x}"

    def __str__(self) -> str:
        return f"{self.commit_lsn}/{self.tx_ordinal}"


class ChangeType(enum.IntEnum):
    INSERT = 0
    UPDATE = 1
    DELETE = 2


@dataclass(slots=True)
class BeginEvent:
    start_lsn: Lsn
    commit_lsn: Lsn  # final LSN announced by the BEGIN message
    timestamp_us: int  # pg epoch-2000 micros converted to unix micros
    xid: int


@dataclass(slots=True)
class CommitEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    end_lsn: Lsn
    timestamp_us: int
    flags: int = 0


@dataclass(slots=True)
class RelationEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    schema: ReplicatedTableSchema


@dataclass(slots=True)
class InsertEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    tx_ordinal: int
    schema: ReplicatedTableSchema
    row: TableRow

    @property
    def sequence_key(self) -> EventSequenceKey:
        return EventSequenceKey(self.commit_lsn, self.tx_ordinal)


@dataclass(slots=True)
class UpdateEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    tx_ordinal: int
    schema: ReplicatedTableSchema
    row: TableRow
    # old identity values when replica identity produced them ('K'/'O' tuples);
    # merged-by-identity-mask semantics live in the codec (codec/event.rs:28-50)
    old_row: PartialTableRow | TableRow | None = None

    @property
    def sequence_key(self) -> EventSequenceKey:
        return EventSequenceKey(self.commit_lsn, self.tx_ordinal)


@dataclass(slots=True)
class DeleteEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    tx_ordinal: int
    schema: ReplicatedTableSchema
    old_row: PartialTableRow | TableRow

    @property
    def sequence_key(self) -> EventSequenceKey:
        return EventSequenceKey(self.commit_lsn, self.tx_ordinal)


@dataclass(slots=True)
class TruncateEvent:
    start_lsn: Lsn
    commit_lsn: Lsn
    tx_ordinal: int
    options: int  # bit 1: CASCADE, bit 2: RESTART IDENTITY
    schemas: tuple[ReplicatedTableSchema, ...]

    @property
    def cascade(self) -> bool:
        return bool(self.options & 1)

    @property
    def restart_identity(self) -> bool:
        return bool(self.options & 2)


@dataclass(slots=True)
class SchemaChangeEvent:
    """DDL logical message emitted by the source event trigger
    (reference: apply.rs:2160-2277 + migrations/source/...schema_change_messages.up.sql)."""

    start_lsn: Lsn
    commit_lsn: Lsn
    table_id: TableId
    new_schema: ReplicatedTableSchema | None  # None = table dropped


class DecodedBatchEvent:
    """TPU-path event: a contiguous same-table run of changes decoded on
    device into columnar form. `change_types[i]` and `tx_ordinals[i]` /
    `commit_lsns[i]` give each row its identity in the WAL order.

    `batch` / `old_batch` resolve lazily: the assembler hands the event an
    in-flight device decode (`pending`, an object with `.result()`), so the
    device works and the result streams back to the host while the apply
    loop keeps reading WAL — the decode completes inside the destination
    write that consumes it (the software-pipelining analogue of the
    reference's one-in-flight flush, apply.rs:1956-2023).

    Old-tuple identity (reference codec/event.rs:28-50): `old_rows[j]` is
    the row index whose update carried an old/key tuple (stored as row j of
    `old_batch`); `old_is_key[j]` distinguishes 'K' key tuples from 'O'
    full tuples. `delete_is_key[i]` is True when DELETE row i carried a 'K'
    tuple (identity columns only) rather than a full 'O' old row.
    """

    __slots__ = ("start_lsn", "commit_lsn", "schema", "change_types",
                 "commit_lsns", "tx_ordinals", "old_rows", "old_is_key",
                 "delete_is_key", "_batch", "_pending", "_old_batch",
                 "_old_pending")

    def __init__(self, start_lsn: Lsn, commit_lsn: Lsn,
                 schema: ReplicatedTableSchema, *,
                 change_types: np.ndarray, commit_lsns: np.ndarray,
                 tx_ordinals: np.ndarray,
                 batch: ColumnarBatch | None = None, pending=None,
                 old_batch: ColumnarBatch | None = None, old_pending=None,
                 old_rows: np.ndarray | None = None,
                 old_is_key: np.ndarray | None = None,
                 delete_is_key: np.ndarray | None = None):
        if batch is None and pending is None:
            raise ValueError("DecodedBatchEvent needs batch or pending")
        self.start_lsn = start_lsn
        self.commit_lsn = commit_lsn
        self.schema = schema
        self.change_types = change_types
        self.commit_lsns = commit_lsns
        self.tx_ordinals = tx_ordinals
        self.old_rows = old_rows if old_rows is not None \
            else np.zeros(0, dtype=np.int64)
        self.old_is_key = old_is_key if old_is_key is not None \
            else np.zeros(0, dtype=np.bool_)
        self.delete_is_key = delete_is_key
        self._batch = batch
        self._pending = pending
        self._old_batch = old_batch
        self._old_pending = old_pending

    @property
    def batch(self) -> ColumnarBatch:
        if self._batch is None:
            self._batch = self._pending.result()
            self._pending = None
            surv = getattr(self._batch, "source_rows", None)
            if surv is not None:
                # fused publication row filter: the decode compacted the
                # rows, so the per-row identity arrays compact in lockstep
                # the moment the batch resolves. Consumers read these
                # arrays only alongside the batch (CoalescedBatch /
                # expand_batch_events both resolve `batch` first);
                # event_size_hint deliberately reads the pre-filter arrays
                # — an overestimate, never a forced decode.
                self.change_types = self.change_types[surv]
                self.commit_lsns = np.asarray(self.commit_lsns)[surv]
                self.tx_ordinals = np.asarray(self.tx_ordinals)[surv]
        return self._batch

    @property
    def old_batch(self) -> ColumnarBatch | None:
        if self._old_batch is None and self._old_pending is not None:
            self._old_batch = self._old_pending.result()
            self._old_pending = None
        return self._old_batch

    def abandon(self) -> None:
        """Discard an event that will never be consumed (a hard-killed
        worker's flushed-but-undelivered write window): release the
        pending decode's pooled resources (staging arena, window slot,
        admission ticket) without paying the fetch. Resolved events
        already returned them; handles without an abandon hook (the
        serial `_PendingDecode`) hold no pooled resources."""
        for pending in (self._pending, self._old_pending):
            ab = getattr(pending, "abandon", None)
            if ab is not None:
                ab()
        self._pending = None
        self._old_pending = None

    def __len__(self) -> int:
        return len(self.change_types)


Event = Union[
    BeginEvent, CommitEvent, RelationEvent, InsertEvent, UpdateEvent,
    DeleteEvent, TruncateEvent, SchemaChangeEvent, DecodedBatchEvent,
]

ROW_EVENT_TYPES = (InsertEvent, UpdateEvent, DeleteEvent)


def event_size_hint(e: Event) -> int:
    """Byte-size estimate for batch budgeting (reference: size hints consumed
    by EventBatch, apply.rs:633)."""
    if isinstance(e, (InsertEvent, UpdateEvent)):
        base = 64 + e.row.size_hint()
        if isinstance(e, UpdateEvent) and e.old_row is not None:
            base += e.old_row.size_hint()
        return base
    if isinstance(e, DeleteEvent):
        return 64 + e.old_row.size_hint()
    if isinstance(e, DecodedBatchEvent):
        # don't force a lazy in-flight decode just for accounting
        base = 64 + e.change_types.nbytes + 16 * len(e)
        if e._batch is not None:
            base += e._batch.size_hint()
        return base
    return 64
