"""Core data model: LSNs, schemas+masks, cells, rows, events, errors."""

from .cell import (JSON_NULL, TOAST_UNCHANGED, PgInterval, PgNumeric, PgSpecialDate,
                   PgSpecialTimestamp, PgTimeTz, py_value_kind)
from .errors import (EtlError, ErrorKind, RetryDirective, RetryKind,
                     etl_error, retry_directive)
from .event import (BeginEvent, ChangeType, CommitEvent, DecodedBatchEvent,
                    DeleteEvent, Event, EventSequenceKey, InsertEvent,
                    RelationEvent, ROW_EVENT_TYPES, SchemaChangeEvent,
                    TruncateEvent, UpdateEvent, event_size_hint)
from .lsn import Lsn
from .pgtypes import CellKind, Oid, array_element, is_array_oid, kind_for_oid
from .schema import (ColumnMask, ColumnSchema, ColumnModification,
                     ReplicatedTableSchema, SchemaDiff, SnapshotId, TableId,
                     TableName, TableSchema, apply_column_changes)
from .table_row import (Column, ColumnarBatch, PartialTableRow, TableRow,
                        dense_dtype, value_size_hint)

__all__ = [n for n in dir() if not n.startswith("_")]
