"""Portable classification of Postgres column default expressions.

Reference parity: crates/etl-postgres/src/default_expression.rs (613 LoC).
The source's `pg_get_expr` output (captured into
`ColumnSchema.default_expression` by the schema queries and the DDL
trigger) is an arbitrary SQL expression. Destinations can only express a
conservative subset in their own DDL; everything else must be skipped
(the column arrives NULL-defaulted and rows carry explicit values, so
correctness is preserved — only destination-side `DEFAULT` convenience is
lost, exactly the reference's stance: "skipping unsupported source column
default", bigquery/schema.rs:33-36).

The parser is intentionally conservative (default_expression.rs:32-35):
 - normalization strips trailing `::type` casts and one layer of wrapping
   parens, iteratively;
 - `nextval(...)` (serial/identity), anything containing `select `, any
   remaining `::`, and `array[...]` are portability boundaries → None;
 - only single string/numeric/boolean literals classify, with type-shaped
   string literals (dates, times, timestamps, intervals, json) kept
   verbatim for typed rendering.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from .pgtypes import CellKind


class DefaultKind(enum.Enum):
    STRING = "string"
    NUMERIC = "numeric"
    BOOLEAN = "boolean"
    DATE = "date"
    TIME = "time"
    TIMETZ = "timetz"
    TIMESTAMP = "timestamp"
    TIMESTAMPTZ = "timestamptz"
    INTERVAL = "interval"
    JSON = "json"


@dataclass(frozen=True)
class DefaultExpression:
    """A classified, destination-expressible default. `text` holds the RAW
    value: for string-shaped kinds the UNESCAPED inner text (no quotes, PG
    ''-doubling undone), for numeric/boolean the bare literal. Quoting and
    escaping are DIALECT concerns applied at render time — Postgres
    ''-doubling is not valid GoogleSQL, and backslashes are escape
    characters in BigQuery/ClickHouse/Snowflake but not in Postgres."""

    kind: DefaultKind
    text: str


_TEXT_KINDS = frozenset({CellKind.STRING})
_NUMERIC_KINDS = frozenset({CellKind.I16, CellKind.I32, CellKind.I64,
                            CellKind.U32, CellKind.F32, CellKind.F64,
                            CellKind.NUMERIC})


# -- lexical helpers (default_expression.rs:226-400) -------------------------


def _string_literal_end(s: str, i: int) -> int | None:
    """Index after a single-quoted SQL literal starting at `i` ('' escapes),
    or None if unterminated / not a literal start."""
    if i >= len(s) or s[i] != "'":
        return None
    i += 1
    while i < len(s):
        if s[i] == "'":
            if i + 1 < len(s) and s[i + 1] == "'":
                i += 2
            else:
                return i + 1
        else:
            i += 1
    return None


def _is_string_literal(s: str) -> bool:
    end = _string_literal_end(s, 0)
    return end is not None and end == len(s)


def _is_numeric_literal(s: str) -> bool:
    i = 0
    if i < len(s) and s[i] in "+-":
        i += 1
    has_digit = has_dot = False
    for ch in s[i:]:
        if ch.isdigit():
            has_digit = True
        elif ch == "." and not has_dot:
            has_dot = True
        else:
            return False
    return has_digit


def _is_bool_literal(s: str) -> bool:
    return s.lower() in ("true", "false")


def _has_top_level_binary_operator(s: str) -> bool:
    i, depth = 0, 0
    n = len(s)
    while i < n:
        ch = s[i]
        if ch == "'":
            end = _string_literal_end(s, i)
            i = n if end is None else end
        elif ch == "(":
            depth += 1
            i += 1
        elif ch == ")":
            depth = max(0, depth - 1)
            i += 1
        elif ch in "+-" and depth == 0 and i == 0:
            i += 1  # leading sign
        elif ch in "+-*/%" and depth == 0:
            return True
        elif ch == "|" and depth == 0 and i + 1 < n and s[i + 1] == "|":
            return True
        else:
            i += 1
    return False


def _top_level_cast_start(s: str) -> int | None:
    i, depth, n = 0, 0, len(s)
    while i < n:
        ch = s[i]
        if ch == "'":
            end = _string_literal_end(s, i)
            i = n if end is None else end
        elif ch == "(":
            depth += 1
            i += 1
        elif ch == ")":
            depth = max(0, depth - 1)
            i += 1
        elif ch == ":" and depth == 0 and i + 1 < n and s[i + 1] == ":":
            return i
        else:
            i += 1
    return None


_CAST_NAME_CHARS = set("abcdefghijklmnopqrstuvwxyz"
                       "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
                       "_ \".[](),")


def _strip_cast(s: str) -> str:
    start = _top_level_cast_start(s)
    if start is None:
        return s
    type_name = s[start + 2 :].strip()
    subject = s[:start].strip()
    if type_name and all(c in _CAST_NAME_CHARS for c in type_name) \
            and not _has_top_level_binary_operator(subject):
        return subject
    return s


def _strip_outer_parens(s: str) -> str:
    s = s.strip()
    if not (s.startswith("(") and s.endswith(")")):
        return s
    i, depth, n = 0, 0, len(s)
    while i < n:
        ch = s[i]
        if ch == "'":
            end = _string_literal_end(s, i)
            i = n if end is None else end
        elif ch == "(":
            depth += 1
            i += 1
        elif ch == ")":
            depth -= 1
            if depth == 0 and i != n - 1:
                return s  # closes before the end: not a full wrap
            i += 1
        else:
            i += 1
    if depth != 0:
        return s
    return s[1:-1].strip()


def _normalize(s: str) -> str:
    s = s.strip()
    for _ in range(len(s) or 1):
        stripped = _strip_outer_parens(_strip_cast(s))
        if stripped == s or len(stripped) >= len(s):
            return s
        s = stripped
    return s


def _crosses_portability_boundary(s: str) -> bool:
    low = s.lower()
    return (low.startswith("nextval(")
            or "select " in low
            or "::" in s
            or low.startswith("array[")
            or low.startswith("array "))


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


# -- classification ----------------------------------------------------------


def parse_default_expression(expression: str | None,
                             kind: CellKind) -> DefaultExpression | None:
    """Classify a source default against the column's decoded kind.
    Returns None for anything outside the portable subset — the caller
    must then OMIT the destination-side default (must-backfill stance)."""
    if expression is None:
        return None
    s = _normalize(expression)
    if not s or s.lower() == "null":
        return None
    if _crosses_portability_boundary(s):
        return None
    if _is_string_literal(s):
        return _classify_string_literal(s, kind)
    if _is_numeric_literal(s):
        if kind in _TEXT_KINDS:
            return DefaultExpression(DefaultKind.STRING, s)
        if kind in _NUMERIC_KINDS:
            return DefaultExpression(DefaultKind.NUMERIC, s)
        return None
    if _is_bool_literal(s):
        if kind in _TEXT_KINDS:
            return DefaultExpression(DefaultKind.STRING, s)
        if kind is CellKind.BOOL:
            return DefaultExpression(DefaultKind.BOOLEAN, s)
        return None
    return None


_TYPED_STRING = {
    CellKind.DATE: DefaultKind.DATE,
    CellKind.TIME: DefaultKind.TIME,
    CellKind.TIMETZ: DefaultKind.TIMETZ,
    CellKind.TIMESTAMP: DefaultKind.TIMESTAMP,
    CellKind.TIMESTAMPTZ: DefaultKind.TIMESTAMPTZ,
    CellKind.INTERVAL: DefaultKind.INTERVAL,
    CellKind.JSON: DefaultKind.JSON,
}


def _classify_string_literal(s: str,
                             kind: CellKind) -> DefaultExpression | None:
    inner = _unquote(s)
    if kind is CellKind.BOOL:
        if _is_bool_literal(inner):
            return DefaultExpression(DefaultKind.BOOLEAN, inner.lower())
        return None
    if kind in _NUMERIC_KINDS:
        if _is_numeric_literal(inner):
            return DefaultExpression(DefaultKind.NUMERIC, inner)
        return None
    typed = _TYPED_STRING.get(kind)
    if typed is not None:
        return DefaultExpression(typed, inner)
    if kind in (CellKind.STRING, CellKind.UUID):
        return DefaultExpression(DefaultKind.STRING, inner)
    # ARRAY / BYTES / anything unmapped: a quoted literal would be
    # type-mismatched at the destination (e.g. STRING default on a BQ JSON
    # array column) — must-backfill, omit the default
    return None


# -- destination rendering ---------------------------------------------------


_STRING_SHAPED = frozenset({
    DefaultKind.STRING, DefaultKind.DATE, DefaultKind.TIME,
    DefaultKind.TIMETZ, DefaultKind.TIMESTAMP, DefaultKind.TIMESTAMPTZ,
    DefaultKind.INTERVAL, DefaultKind.JSON,
})


def _quote_for(dialect: str, inner: str) -> str:
    """Dialect-correct string literal: Postgres ''-doubling is NOT valid
    GoogleSQL, and backslash is an escape character in BigQuery /
    ClickHouse / Snowflake string literals (unlike standard-conforming
    Postgres), so the raw value is re-escaped per target."""
    if dialect in ("bigquery", "clickhouse"):
        return "'" + inner.replace("\\", "\\\\").replace("'", "\\'") + "'"
    if dialect == "snowflake":  # '' doubling; backslash still escapes
        return "'" + inner.replace("\\", "\\\\").replace("'", "''") + "'"
    # duckdb & other standard-conforming dialects: '' doubling only
    return "'" + inner.replace("'", "''") + "'"


def render_default_sql(expr: DefaultExpression, dialect: str) -> str | None:
    """SQL text for a destination `DEFAULT` clause, or None when the
    dialect cannot express the kind (reference render_default_expression,
    bigquery/schema.rs:58-100 and the clickhouse/snowflake analogues)."""
    k = expr.kind
    if k in (DefaultKind.NUMERIC, DefaultKind.BOOLEAN):
        return expr.text
    if k not in _STRING_SHAPED:
        return None
    lit = _quote_for(dialect, expr.text)
    if dialect == "bigquery":
        if k is DefaultKind.DATE:
            return f"DATE {lit}"
        if k is DefaultKind.TIME:
            return f"TIME {lit}"
        if k is DefaultKind.TIMESTAMP:
            return f"DATETIME {lit}"
        if k is DefaultKind.TIMESTAMPTZ:
            return f"TIMESTAMP {lit}"
        if k is DefaultKind.JSON:
            return f"JSON {lit}"
        return lit  # TIMETZ/INTERVAL carried as STRING columns
    if dialect == "clickhouse":
        return lit  # CH casts string literals to Date/DateTime columns
    if dialect == "snowflake":
        if k is DefaultKind.JSON:
            return None  # VARIANT defaults are not expressible in SF DDL
        return lit
    if dialect == "duckdb":
        return lit
    return None


def column_default_sql(column, dialect: str) -> str | None:
    """One-call helper: classify `column.default_expression` against
    `column.kind` and render for `dialect`; None == omit (backfill)."""
    expr = parse_default_expression(column.default_expression, column.kind)
    if expr is None:
        return None
    return render_default_sql(expr, dialect)
