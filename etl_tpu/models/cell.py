"""Cell value helpers: the Python value vocabulary for decoded columns.

Design: values are plain Python objects (None, bool, int, float,
decimal.Decimal, datetime.*, uuid.UUID, bytes, str, list) and the schema
carries the type (see models/pgtypes.py). This file provides the few value
types Python lacks natively, mirroring the reference's special codecs:

  - PgNumeric  → decimal.Decimal subclass keeping Postgres NaN semantics
                 (reference: crates/etl-postgres/src/numeric.rs, 967 LoC —
                 Python's Decimal already implements exact arbitrary
                 precision + NaN/±Infinity, so no hand-rolled codec needed)
  - PgTimeTz   → time-of-day with fixed UTC offset
                 (reference: crates/etl-postgres/src/time.rs)
  - PgInterval → months/days/microseconds triple (Postgres' interval model)

`py_value_kind` classifies a Python value back to a CellKind for schema
inference in tests and destinations.
"""

from __future__ import annotations

import datetime as dt
import decimal
import uuid
from dataclasses import dataclass

from .pgtypes import CellKind

Decimal = decimal.Decimal


class PgNumeric(Decimal):
    """Postgres NUMERIC. Subclass of Decimal; exists so destinations can
    distinguish 'came from a numeric column' and so NaN formats as the
    Postgres literal `NaN` rather than Python's `NaN` quirks."""

    __slots__ = ()

    def pg_text(self) -> str:
        if self.is_nan():
            return "NaN"
        if self.is_infinite():
            return "Infinity" if self > 0 else "-Infinity"
        return format(self, "f")


@dataclass(frozen=True, slots=True)
class PgTimeTz:
    """Time of day with a fixed UTC offset (reference PgTimeTz,
    crates/etl-postgres/src/time.rs)."""

    time: dt.time  # naive time-of-day
    offset_seconds: int  # seconds east of UTC (pg: +HH:MM:SS)

    def pg_text(self) -> str:
        t = self.time.isoformat()
        off = self.offset_seconds
        sign = "+" if off >= 0 else "-"
        off = abs(off)
        h, rem = divmod(off, 3600)
        m, s = divmod(rem, 60)
        out = f"{t}{sign}{h:02d}"
        if m or s:
            out += f":{m:02d}"
        if s:
            out += f":{s:02d}"
        return out


@dataclass(frozen=True, slots=True)
class PgInterval:
    """Postgres interval: months / days / microseconds are separate units
    (they do not normalize into each other)."""

    months: int = 0
    days: int = 0
    microseconds: int = 0

    def pg_text(self) -> str:
        parts = []
        if self.months:
            y, m = divmod(abs(self.months), 12)
            sign = "-" if self.months < 0 else ""
            if y:
                parts.append(f"{sign}{y} year" + ("s" if y != 1 else ""))
            if m:
                parts.append(f"{sign}{m} mon" + ("s" if m != 1 else ""))
        if self.days:
            parts.append(f"{self.days} day" + ("s" if abs(self.days) != 1 else ""))
        us = self.microseconds
        if us or not parts:
            neg = us < 0
            us = abs(us)
            h, rem = divmod(us, 3_600_000_000)
            mi, rem = divmod(rem, 60_000_000)
            s, frac = divmod(rem, 1_000_000)
            t = f"{'-' if neg else ''}{h:02d}:{mi:02d}:{s:02d}"
            if frac:
                t += f".{frac:06d}".rstrip("0")
            parts.append(t)
        return " ".join(parts)


@dataclass(frozen=True, slots=True)
class PgSpecialDate:
    """A date outside Python's datetime range (BC dates; Python MINYEAR=1
    while Postgres reaches 4713 BC). Carries the exact proleptic-Gregorian
    day count since 1970-01-01 (negative) plus the source text, so dense
    columnar staging and Arrow date32 output stay exact."""

    days: int
    text: str

    def pg_text(self) -> str:
        return self.text


@dataclass(frozen=True, slots=True)
class PgSpecialTimestamp:
    """A timestamp outside Python's datetime range (BC timestamps). Exact
    microseconds since the unix epoch (negative) plus source text."""

    micros: int
    text: str
    tz_aware: bool = False

    def pg_text(self) -> str:
        return self.text


_KIND_BY_PYTYPE = (
    (bool, CellKind.BOOL),
    (PgSpecialDate, CellKind.DATE),
    (PgSpecialTimestamp, CellKind.TIMESTAMP),
    (int, CellKind.I64),
    (float, CellKind.F64),
    (PgNumeric, CellKind.NUMERIC),
    (Decimal, CellKind.NUMERIC),
    (str, CellKind.STRING),
    (bytes, CellKind.BYTES),
    (dt.datetime, CellKind.TIMESTAMP),
    (dt.date, CellKind.DATE),
    (PgTimeTz, CellKind.TIMETZ),
    (dt.time, CellKind.TIME),
    (uuid.UUID, CellKind.UUID),
    (PgInterval, CellKind.INTERVAL),
    (list, CellKind.ARRAY),
)


def py_value_kind(value) -> CellKind:
    """Classify a decoded Python value back to its CellKind."""
    if value is None:
        return CellKind.NULL
    for pytype, kind in _KIND_BY_PYTYPE:
        if isinstance(value, pytype):
            if kind is CellKind.TIMESTAMP and value.tzinfo is not None:
                return CellKind.TIMESTAMPTZ
            return kind
    if isinstance(value, dict):
        return CellKind.JSON
    return CellKind.STRING


class ToastUnchanged:
    """Sentinel for a TOASTed value pgoutput did not re-send ('u' tuple kind;
    reference: codec/event.rs TOAST-unchanged handling). Singleton."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "TOAST_UNCHANGED"


TOAST_UNCHANGED = ToastUnchanged()


class JsonNull:
    """The JSON value `null` — a real value, distinct from SQL NULL
    (reference: Cell::Json(Value::Null) vs Cell::Null). Singleton."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "JSON_NULL"

    def __bool__(self) -> bool:
        return False


JSON_NULL = JsonNull()
