"""Postgres WAL log sequence numbers.

Reference: the Rust build uses `tokio_postgres::types::PgLsn` (a u64 with
an `X/Y` hex display form) throughout `crates/etl/src/replication/apply.rs`
and the store progress rows. Here an LSN is a plain int subclass so it is
hashable, ordered, JSON-serializable, and free to pass across the host/device
boundary as a uint64.
"""

from __future__ import annotations


class Lsn(int):
    """A 64-bit WAL position. Displays as Postgres' `XXXXXXXX/XXXXXXXX`."""

    __slots__ = ()

    ZERO: "Lsn"
    MAX: "Lsn"

    def __new__(cls, value: "int | str" = 0) -> "Lsn":
        if isinstance(value, str):
            value = cls._parse(value)
        if not 0 <= value <= 0xFFFF_FFFF_FFFF_FFFF:
            raise ValueError(f"LSN out of range: {value}")
        return super().__new__(cls, value)

    @staticmethod
    def _parse(text: str) -> int:
        hi, sep, lo = text.partition("/")
        if not sep:
            raise ValueError(f"invalid LSN {text!r}: missing '/'")
        try:
            return (int(hi, 16) << 32) | int(lo, 16)
        except ValueError as exc:
            raise ValueError(f"invalid LSN {text!r}") from exc

    def __str__(self) -> str:
        return f"{int(self) >> 32:X}/{int(self) & 0xFFFF_FFFF:X}"

    def __repr__(self) -> str:
        return f"Lsn({str(self)!r})"

    def __add__(self, other: int) -> "Lsn":
        return Lsn(int(self) + int(other))

    def __sub__(self, other: int) -> int:  # distance in bytes
        return int(self) - int(other)


Lsn.ZERO = Lsn(0)
Lsn.MAX = Lsn(0xFFFF_FFFF_FFFF_FFFF)
