"""Prototype: threaded decode pipeline vs current single-thread pipelining.

Worker thread: pack + dispatch + block + fetch. Main thread: stage + complete.
Fresh arrays every batch (no jax host-copy cache effects).
"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import bench as B


def main():
    payloads = B.build_workload(B.N_ROWS)
    schema = B.make_schema()
    from etl_tpu.ops import DeviceDecoder
    from etl_tpu.ops.wal import concat_payloads, stage_wal_batch

    buf, offs, lens = concat_payloads(payloads)
    decoder = DeviceDecoder(schema)

    def stage():
        return stage_wal_batch(buf, offs, lens, 4)

    # warm
    decoder.decode(stage().staged)

    # phase stamps on one fresh blocking decode
    wal = stage()
    t0 = time.perf_counter()
    widths = decoder._widths(wal.staged)
    t1 = time.perf_counter()
    packed, bad = decoder._device_call(wal.staged, widths)  # pack+dispatch
    t2 = time.perf_counter()
    packed.block_until_ready()
    t3 = time.perf_counter()
    packed_np = np.asarray(packed)
    t4 = time.perf_counter()
    batch = decoder._complete(wal.staged, widths, packed)
    t5 = time.perf_counter()
    print(f"widths={1e3*(t1-t0):.1f}ms pack+dispatch={1e3*(t2-t1):.1f}ms "
          f"block={1e3*(t3-t2):.1f}ms fetch={1e3*(t4-t3):.1f}ms "
          f"complete={1e3*(t5-t4):.1f}ms")

    n_batches = 10

    # current-style single-thread pipelining
    for trial in range(3):
        t0 = time.perf_counter()
        pending = []
        for _ in range(n_batches):
            wal = stage()
            pending.append(decoder.decode_async(wal.staged))
            if len(pending) >= 4:
                assert pending.pop(0).result().num_rows == B.N_ROWS
        for p in pending:
            p.result()
        dt = (time.perf_counter() - t0) / n_batches
        print(f"single-thread pipelined: {B.N_ROWS/dt:.0f} rows/s ({dt*1e3:.0f}ms/batch)")

    # threaded: worker does pack+dispatch+block+fetch
    ex = ThreadPoolExecutor(1)

    def device_work(staged):
        widths = decoder._widths(staged)
        packed, bad = decoder._device_call(staged, widths)
        packed.block_until_ready()
        return staged, widths, np.asarray(packed), bad

    for trial in range(3):
        t0 = time.perf_counter()
        futs = []
        done = 0
        for _ in range(n_batches):
            wal = stage()
            futs.append(ex.submit(device_work, wal.staged))
            if len(futs) >= 3:
                staged, widths, packed_np, bad = futs.pop(0).result()
                b = decoder._complete(staged, widths, packed_np, bad)
                assert b.num_rows == B.N_ROWS
                done += 1
        for f in futs:
            staged, widths, packed_np, bad = f.result()
            decoder._complete(staged, widths, packed_np, bad)
        dt = (time.perf_counter() - t0) / n_batches
        print(f"threaded pipelined: {B.N_ROWS/dt:.0f} rows/s ({dt*1e3:.0f}ms/batch)")

    ex.shutdown()


if __name__ == "__main__":
    main()
