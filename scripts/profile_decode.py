"""Profile the decode pipeline stage-by-stage on the real chip.

Not part of the test suite — a builder tool for finding the structural
bottleneck (upload vs compute vs fetch vs host work) behind bench.py.
"""
from __future__ import annotations

import time

import numpy as np

import bench as B


def timeit(fn, n=5):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), sorted(ts)[len(ts) // 2]


def main():
    import jax

    payloads = B.build_workload(B.N_ROWS)
    schema = B.make_schema()

    from etl_tpu.ops import DeviceDecoder
    from etl_tpu.ops.wal import concat_payloads, stage_wal_batch

    buf, offs, lens = concat_payloads(payloads)
    print("backend:", jax.default_backend())

    # raw link speed: upload and fetch of a plain array
    for mb in (4,):
        a = np.random.randint(0, 255, size=(mb * 1024 * 1024,), dtype=np.uint8)
        up_min, up_med = timeit(lambda: jax.device_put(a).block_until_ready())
        d = jax.device_put(a)
        fx_min, fx_med = timeit(lambda: np.asarray(d))
        print(f"link {mb}MiB: upload min={up_min*1e3:.1f}ms med={up_med*1e3:.1f}ms"
              f" ({mb/up_med:.1f}MB/s) fetch min={fx_min*1e3:.1f}ms "
              f"med={fx_med*1e3:.1f}ms ({mb/fx_med:.1f}MB/s)")
    # round-trip latency: tiny array
    t = np.zeros(8, dtype=np.uint8)
    lat_min, lat_med = timeit(lambda: np.asarray(jax.device_put(t)))
    print(f"latency tiny roundtrip: min={lat_min*1e3:.1f}ms med={lat_med*1e3:.1f}ms")

    decoder = DeviceDecoder(schema)

    # stage = frame + group
    st_min, st_med = timeit(lambda: stage_wal_batch(buf, offs, lens, 4))
    wal = stage_wal_batch(buf, offs, lens, 4)
    staged = wal.staged
    widths = decoder._widths(staged)
    print(f"stage_wal_batch: min={st_min*1e3:.1f}ms med={st_med*1e3:.1f}ms  widths={widths}")

    # host pack
    pk_min, pk_med = timeit(lambda: decoder._pack_host(staged, widths))
    bmat, lengths, nibble, bad = decoder._pack_host(staged, widths)
    print(f"pack_host: min={pk_min*1e3:.1f}ms med={pk_med*1e3:.1f}ms nibble={nibble} "
          f"bmat={bmat.shape} {bmat.nbytes/1e6:.2f}MB lengths={lengths.nbytes/1e6:.2f}MB")

    # device call (dispatch + wait)
    packed, _ = decoder._device_call(staged, widths)
    packed.block_until_ready()
    def full_call():
        p, _ = decoder._device_call(staged, widths)
        p.block_until_ready()
    dc_min, dc_med = timeit(full_call)
    print(f"pack+dispatch+devicewait: min={dc_min*1e3:.1f}ms med={dc_med*1e3:.1f}ms "
          f"out={packed.shape} {packed.size*4/1e6:.2f}MB")

    # fetch
    fx_min, fx_med = timeit(lambda: np.asarray(packed))
    print(f"fetch packed: min={fx_min*1e3:.1f}ms med={fx_med*1e3:.1f}ms")

    # complete (includes fetch + combine + object cols + arrow)
    cm_min, cm_med = timeit(lambda: decoder._complete(staged, widths, packed))
    print(f"complete: min={cm_min*1e3:.1f}ms med={cm_med*1e3:.1f}ms")

    # full blocking decode
    fd_min, fd_med = timeit(lambda: decoder.decode(stage_wal_batch(buf, offs, lens, 4).staged))
    print(f"full decode (blocking): min={fd_min*1e3:.1f}ms med={fd_med*1e3:.1f}ms "
          f"-> {B.N_ROWS/fd_med:.0f} rows/s blocking")

    # pipelined, as bench does
    rates, _ = B.bench_tpu(payloads, schema, B.N_ROWS)
    print(f"bench_tpu pipelined: peak={rates[-1]:.0f} "
          f"med={rates[len(rates) // 2]:.0f} rows/s")


if __name__ == "__main__":
    main()
