"""Fetch-path experiments: how do we get device results back faster?"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, n=5):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), sorted(ts)[n // 2]


def fresh(shape, dtype=jnp.int32):
    """A fresh on-device array with no cached host copy."""
    return jax.jit(lambda k: jax.random.randint(k, shape, 0, 100, dtype))(
        jax.random.PRNGKey(int(time.time() * 1e6) % 2**31))


def main():
    dev = jax.devices()[0]
    print("device:", dev, "memories:", [m.kind for m in dev.addressable_memories()])

    R = 262_144
    shape = (4, R)  # 4.19MB int32

    # 1. plain fetch of fresh arrays
    def plain():
        a = fresh(shape)
        a.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(a)
        return time.perf_counter() - t0
    ts = sorted(plain() for _ in range(5))
    print(f"plain fetch 4.19MB: med={ts[2]*1e3:.1f}ms ({4.19/ts[2]:.0f}MB/s)")

    # 2. parallel chunk fetch via threads
    ex = ThreadPoolExecutor(8)
    for nchunks in (2, 4, 8):
        def chunked():
            a = fresh(shape)
            a.block_until_ready()
            rows = np.array_split(np.arange(shape[0] * R), nchunks)
            flat = a.reshape(-1)
            parts = [flat[r[0]:r[-1] + 1] for r in rows]
            for p in parts:
                p.block_until_ready()
            t0 = time.perf_counter()
            list(ex.map(np.asarray, parts))
            return time.perf_counter() - t0
        ts = sorted(chunked() for _ in range(5))
        print(f"parallel fetch x{nchunks}: med={ts[2]*1e3:.1f}ms ({4.19/ts[2]:.0f}MB/s)")

    # 3. pinned_host output sharding
    try:
        from jax.sharding import SingleDeviceSharding
        host_shard = SingleDeviceSharding(dev, memory_kind="pinned_host")
        f = jax.jit(lambda k: jax.random.randint(k, shape, 0, 100, jnp.int32),
                    out_shardings=host_shard)
        def pinned():
            a = f(jax.random.PRNGKey(int(time.time() * 1e6) % 2**31))
            a.block_until_ready()
            t0 = time.perf_counter()
            np.asarray(a)
            return time.perf_counter() - t0
        ts = sorted(pinned() for _ in range(5))
        print(f"pinned_host out fetch: med={ts[2]*1e3:.1f}ms")
        # and total including compute
        def pinned_total():
            t0 = time.perf_counter()
            a = f(jax.random.PRNGKey(int(time.time() * 1e6) % 2**31))
            np.asarray(a)
            return time.perf_counter() - t0
        ts = sorted(pinned_total() for _ in range(5))
        print(f"pinned_host compute+fetch total: med={ts[2]*1e3:.1f}ms")
    except Exception as e:
        print("pinned_host failed:", repr(e))

    # 4. device_put round trip for size scaling: latency vs bandwidth
    for mb in (0.25, 1, 4, 16):
        n = int(mb * 1024 * 1024 // 4)
        def rt():
            a = fresh((n,))
            a.block_until_ready()
            t0 = time.perf_counter()
            np.asarray(a)
            return time.perf_counter() - t0
        ts = sorted(rt() for _ in range(5))
        print(f"fetch {mb}MB: med={ts[2]*1e3:.1f}ms ({mb/ts[2]:.0f}MB/s)")

    # 5. copy_to_host_async then asarray
    def async_fetch():
        a = fresh(shape)
        a.block_until_ready()
        t0 = time.perf_counter()
        a.copy_to_host_async()
        np.asarray(a)
        return time.perf_counter() - t0
    ts = sorted(async_fetch() for _ in range(5))
    print(f"copy_to_host_async+asarray: med={ts[2]*1e3:.1f}ms")

    # 6. does fetch overlap another fetch? two arrays, two threads
    def dual():
        a, b = fresh(shape), fresh(shape)
        a.block_until_ready(); b.block_until_ready()
        t0 = time.perf_counter()
        f1 = ex.submit(np.asarray, a)
        f2 = ex.submit(np.asarray, b)
        f1.result(); f2.result()
        return time.perf_counter() - t0
    ts = sorted(dual() for _ in range(5))
    print(f"2 arrays 2 threads (8.4MB): med={ts[2]*1e3:.1f}ms ({8.38/ts[2]:.0f}MB/s)")

    ex.shutdown()


if __name__ == "__main__":
    main()
