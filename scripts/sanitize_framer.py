#!/usr/bin/env python
"""Memory-safety harness for the native framer (VERDICT r2 weak: "no
TSAN-analogue for framer.c").

The framer parses UNTRUSTED walsender bytes in C, so the sanitizer run is
the safety net the reference gets from Rust's borrow checker + cargo-fuzz:
build `framer.c` with AddressSanitizer + UBSan (-fno-sanitize-recover:
any OOB read/write, overflow, or misaligned access ABORTS the child), then
hammer it with

  1. the structured-mutation framer fuzzer (testing/fuzz.py `framer`
     target — valid pgoutput streams + byte mutations + truncations), and
  2. the full differential test file (tests/test_native_framer.py), which
     also exercises etl_pack_bmat / etl_gather_string / nibble packing.

Exit 0 = no sanitizer findings. Run:  python scripts/sanitize_framer.py
[--seconds N] [--seed N]. CI-sized invocation lives in
tests/test_aux_subsystems.py.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
SRC = REPO / "etl_tpu" / "native" / "framer.c"


def build_asan_so(out_dir: Path) -> Path:
    so = out_dir / "_framer_asan.so"
    if so.exists() and so.stat().st_mtime >= SRC.stat().st_mtime:
        return so
    cc = os.environ.get("CC", "cc")
    subprocess.run(
        [cc, "-O1", "-g", "-fsanitize=address,undefined",
         "-fno-sanitize-recover=all", "-shared", "-fPIC",
         str(SRC), "-o", str(so)],
        check=True, capture_output=True, timeout=180)
    return so


def find_libasan() -> str:
    cc = os.environ.get("CC", "cc")
    out = subprocess.run([cc, "-print-file-name=libasan.so"],
                         capture_output=True, text=True, check=True)
    path = out.stdout.strip()
    if not path or path == "libasan.so":
        raise RuntimeError("libasan.so not found (gcc sanitizers missing)")
    return path


def run_child(so: Path, args: list[str], *, env_extra=None) -> int:
    env = dict(os.environ)
    env.update({
        # the .so's ASan runtime must be initialized before python itself
        "LD_PRELOAD": find_libasan(),
        "ETL_NATIVE_FRAMER_SO": str(so),
        # python leaks by design; abort only on real memory errors
        "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
        "PYTHONPATH": f"{REPO}{os.pathsep}" + os.environ.get(
            "PYTHONPATH", ""),
    })
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run([sys.executable, *args], env=env, cwd=str(REPO))
    return proc.returncode


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="sanitize_framer")
    p.add_argument("--seconds", type=float, default=10.0,
                   help="fuzz budget under the sanitizer")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--hammer", action="store_true",
                   help="(internal) run the pack/gather hammer in-process")
    args = p.parse_args(argv)
    if args.hammer:
        sys.path.insert(0, str(REPO))
        return hammer(args.seconds, args.seed)

    out_dir = Path(os.environ.get("TMPDIR", "/tmp")) / "etl_tpu_sanitize"
    out_dir.mkdir(parents=True, exist_ok=True)
    # exit 77 (the automake SKIP convention) when the toolchain cannot do
    # sanitizers (clang layouts differ, libasan not installed): callers
    # skip rather than fail a working build
    try:
        find_libasan()
        so = build_asan_so(out_dir)
    except (RuntimeError, subprocess.CalledProcessError) as e:
        print(f"SKIP: sanitizer toolchain unavailable: {e}",
              file=sys.stderr)
        return 77

    # 1. sanity: the child must actually load the instrumented lib (a
    # silent Python-fallback run would prove nothing)
    rc = run_child(so, ["-c", (
        "import etl_tpu.native as n; "
        "assert n.native_available(), n._build_error; "
        "print('sanitized framer loaded')")])
    if rc != 0:
        print("FAIL: instrumented framer did not load", file=sys.stderr)
        return rc or 1

    # 2. structured-mutation fuzz under ASan/UBSan
    fuzz_args = ["-m", "etl_tpu.testing.fuzz", "--target", "framer",
                 "--seconds", str(args.seconds)]
    if args.seed is not None:
        fuzz_args += ["--seed", str(args.seed)]
    rc = run_child(so, fuzz_args)
    if rc != 0:
        print("FAIL: sanitizer or fuzz failure in framer target",
              file=sys.stderr)
        return rc

    # 3. the pure-framer differential tests (the TestWalStaging class
    # compiles jax programs, which is impractically slow under ASan
    # interceptors — the C surface it exercises is covered by the hammer
    # below instead)
    rc = run_child(so, ["-m", "pytest", "tests/test_native_framer.py",
                        "-q", "--no-header", "-k", "TestFramer"])
    if rc != 0:
        print("FAIL: sanitizer or test failure in differential suite",
              file=sys.stderr)
        return rc

    # 4. direct hammer of the pack/gather entry points (numpy-only):
    # adversarial widths, truncated fields, and buffer-edge offsets
    hammer_args = ["scripts/sanitize_framer.py", "--hammer",
                   "--seconds", str(args.seconds)]
    if args.seed is not None:
        hammer_args += ["--seed", str(args.seed)]
    rc = run_child(so, hammer_args)
    if rc != 0:
        print("FAIL: sanitizer failure in pack/gather hammer",
              file=sys.stderr)
        return rc
    print("sanitize_framer: no findings "
          f"(fuzz {args.seconds:.0f}s + framer differentials + "
          f"pack/gather hammer under ASan+UBSan)")
    return 0


def hammer(seconds: float, seed: int | None) -> int:
    """Child mode: randomized pack_bmat / pack_bmat_nibble / gather_string
    calls over fuzz-framed batches, including adversarial gather widths and
    fields ending at the exact buffer boundary."""
    import random
    import time

    import numpy as np

    import etl_tpu.native as native
    from etl_tpu.postgres.codec import pgoutput

    assert native.native_available(), native._build_error
    rng = random.Random(seed if seed is not None else 20260729)
    deadline = time.monotonic() + seconds
    cases = 0
    while time.monotonic() < deadline:
        n_cols = rng.randint(1, 6)
        msgs = []
        for _ in range(rng.randint(1, 32)):
            fields = []
            for _c in range(n_cols):
                r = rng.random()
                if r < 0.15:
                    fields.append(None)
                else:
                    fields.append(str(rng.randrange(10 ** rng.randint(1, 12)))
                                  .encode())
            msgs.append(pgoutput.encode_insert(
                rng.randrange(1, 1 << 31), fields))
        buf = b"".join(msgs)
        lens = np.array([len(m) for m in msgs], dtype=np.int32)
        offs = np.zeros(len(msgs), dtype=np.int64)
        np.cumsum(lens[:-1], out=offs[1:])
        framed, bad = native.frame_pgoutput(np.frombuffer(buf, np.uint8),
                                            offs, lens, n_cols)
        R = framed.n_msgs
        data = framed.buf
        # adversarial dense-pack: widths both tighter and wider than the
        # real field lengths, including width 0 and 300 (> the 255 cap)
        dense = [c for c in range(n_cols) if rng.random() < 0.8]
        widths = [rng.choice((-7, 0, 1, 3, 12, 32, 300)) for _ in dense]
        tw = max(1, sum(min(w, 255) for w in widths))
        bmat = np.zeros((R, tw), dtype=np.uint8)
        lens_out = np.zeros((R, max(1, len(dense))), dtype=np.uint8)
        if dense:
            native.pack_bmat(data, framed.new_off, framed.new_len,
                             np.array(dense, np.int32),
                             np.array(widths, np.int32), bmat, lens_out)
            bad_rows = np.zeros(R, dtype=np.uint8)
            nib_tw = max(1, sum(min(w, 255) for w in widths) // 2 + 1)
            native.pack_bmat_nibble(data, framed.new_off, framed.new_len,
                                    np.array(dense, np.int32),
                                    np.array(widths, np.int32),
                                    np.zeros((R, nib_tw), np.uint8),
                                    lens_out, bad_rows)
        # string gather with deliberately small capacity (must truncate,
        # not overflow) and full capacity
        for cap in (3, 1 << 16):
            col = rng.randrange(n_cols)
            valid = (framed.new_flag[:, col] == native.FLAG_VALUE) \
                .astype(np.uint8)
            aoff = np.zeros(R + 1, dtype=np.int32)
            vals = np.zeros(cap, dtype=np.uint8)
            native.gather_string(data, framed.new_off, framed.new_len,
                                 valid, col, aoff, vals)
        cases += 1
    print(f"hammer: {cases} cases OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
