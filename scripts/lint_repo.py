#!/usr/bin/env python
"""Fast standalone etl-lint run over the repo (CI/pre-push entry point).

    python scripts/lint_repo.py                  # human output
    python scripts/lint_repo.py --json           # machine-readable findings
    python scripts/lint_repo.py --format=github  # ::error annotations for PRs
    python scripts/lint_repo.py --no-baseline    # include grandfathered debt
    python scripts/lint_repo.py --check-baseline # fail on stale suppressions
    python scripts/lint_repo.py --explain        # chain traces per violation
    python scripts/lint_repo.py --ir             # + IR tier (compiled-program
                                                 #   contracts incl. the forced
                                                 #   8-shard mesh subprocess)

Exit codes (the CI contract, identical for the AST and IR tiers):
0 clean after baseline, 1 findings (or stale suppressions under
--check-baseline), 2 analyzer error (parse failure, bad path, bad
baseline file, a program that fails to lower or a mesh subprocess that
dies) — a gate can distinguish "the tree is dirty" from "the analyzer
itself broke" and a workflow step can annotate PRs inline from the
github format. `--ir` expands to `--programs --mesh`: the full
contract surface (single-device + mesh variants) in one run.

Equivalent to `python -m etl_tpu.analysis etl_tpu/` but runnable from the
repo root without installing the package (it prepends the repo to
sys.path). The tier-1 suite runs the same analyzer in-process via
tests/test_static_analysis.py::TestCli::test_repo_wide_run_is_clean.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from etl_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    # --ir: the IR tier with full coverage (mesh variants included)
    if "--ir" in argv:
        argv = [a for a in argv if a != "--ir"] + ["--programs", "--mesh"]
    # default scan target: the package dir, pinned to THIS repo checkout
    if not any(not a.startswith("-") for a in argv):
        argv = [str(REPO_ROOT / "etl_tpu")] + argv
    sys.exit(main(argv))
