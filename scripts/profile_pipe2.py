"""Deep pipeline with copy_to_host_async after dispatch: sustained rows/s."""
from __future__ import annotations

import time

import numpy as np

import bench as B


def main():
    payloads = B.build_workload(B.N_ROWS)
    schema = B.make_schema()
    from etl_tpu.ops import DeviceDecoder
    from etl_tpu.ops.wal import concat_payloads, stage_wal_batch

    buf, offs, lens = concat_payloads(payloads)
    decoder = DeviceDecoder(schema)
    decoder.decode(stage_wal_batch(buf, offs, lens, 4).staged)  # warm

    n_batches = 10
    for depth in (4, 6):
        for trial in range(3):
            t0 = time.perf_counter()
            pending = []
            for _ in range(n_batches):
                wal = stage_wal_batch(buf, offs, lens, 4)
                staged = wal.staged
                widths = decoder._widths(staged)
                packed, bad = decoder._device_call(staged, widths)
                packed.copy_to_host_async()
                pending.append((staged, widths, packed, bad))
                if len(pending) >= depth:
                    s, w, p, b = pending.pop(0)
                    batch = decoder._complete(s, w, p, b)
                    assert batch.num_rows == B.N_ROWS
            for s, w, p, b in pending:
                decoder._complete(s, w, p, b)
            dt = (time.perf_counter() - t0) / n_batches
            print(f"depth={depth} async-copy pipeline: {B.N_ROWS/dt:.0f} rows/s "
                  f"({dt*1e3:.0f}ms/batch)")

    # how deep do in-flight fetches pipeline? N fresh outputs, async-copy all,
    # then asarray all: total vs N*single
    import jax
    import jax.numpy as jnp

    def fresh(shape):
        return jax.jit(lambda k: jax.random.randint(k, shape, 0, 100,
                                                    jnp.int32))(
            jax.random.PRNGKey(int(time.time() * 1e6) % 2**31))

    shape = (4, 262_144)
    for n in (1, 4):
        arrs = [fresh(shape) for _ in range(n)]
        for a in arrs:
            a.block_until_ready()
        t0 = time.perf_counter()
        for a in arrs:
            a.copy_to_host_async()
        for a in arrs:
            np.asarray(a)
        dt = time.perf_counter() - t0
        print(f"{n} concurrent async fetches of 4.19MB: {dt*1e3:.0f}ms total "
              f"({n*4.19/dt:.0f}MB/s)")


if __name__ == "__main__":
    main()
