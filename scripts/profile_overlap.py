"""Does copy_to_host_async overlap the transfer with host work?"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

import bench as B


def fresh(shape, dtype=jnp.int32):
    return jax.jit(lambda k: jax.random.randint(k, shape, 0, 100, dtype))(
        jax.random.PRNGKey(int(time.time() * 1e6) % 2**31))


def main():
    shape = (4, 262_144)

    # host work ~150ms: stage a wal batch repeatedly
    payloads = B.build_workload(B.N_ROWS)
    from etl_tpu.ops.wal import concat_payloads, stage_wal_batch
    buf, offs, lens = concat_payloads(payloads)

    def host_work(reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            stage_wal_batch(buf, offs, lens, 4)
        return time.perf_counter() - t0

    host_work(1)  # warm

    for reps in (0, 4, 10):
        tot, fetch_ts, hw_ts = [], [], []
        for _ in range(5):
            a = fresh(shape)
            a.block_until_ready()
            t0 = time.perf_counter()
            a.copy_to_host_async()
            hw = host_work(reps)
            t1 = time.perf_counter()
            np.asarray(a)
            t2 = time.perf_counter()
            tot.append(t2 - t0); fetch_ts.append(t2 - t1); hw_ts.append(hw)
        i = 2
        print(f"reps={reps}: total={sorted(tot)[i]*1e3:.0f}ms "
              f"host_work={sorted(hw_ts)[i]*1e3:.0f}ms "
              f"final_asarray={sorted(fetch_ts)[i]*1e3:.0f}ms")

    # overlap with another DISPATCH + device exec (does transfer overlap exec?)
    f = jax.jit(lambda x: (x * 3 + 1).sum(axis=0))
    big = fresh((64, 262_144))
    f(big).block_until_ready()
    tot = []
    for _ in range(5):
        a = fresh(shape)
        a.block_until_ready()
        t0 = time.perf_counter()
        a.copy_to_host_async()
        r = f(big)  # device busy
        hw = host_work(4)
        np.asarray(a)
        r.block_until_ready()
        tot.append(time.perf_counter() - t0)
    print(f"fetch + exec + hostwork concurrent: med={sorted(tot)[2]*1e3:.0f}ms")


if __name__ == "__main__":
    main()
