"""Is dispatch async on the axon tunnel? Does a worker thread overlap?"""
from __future__ import annotations

import threading
import time

import numpy as np

import bench as B


def main():
    import jax

    payloads = B.build_workload(B.N_ROWS)
    schema = B.make_schema()
    from etl_tpu.ops import DeviceDecoder
    from etl_tpu.ops.wal import concat_payloads, stage_wal_batch

    buf, offs, lens = concat_payloads(payloads)
    decoder = DeviceDecoder(schema)
    wal = stage_wal_batch(buf, offs, lens, 4)
    staged = wal.staged
    widths = decoder._widths(staged)
    specs = decoder._specs(staged, widths)
    bmat, lengths, nibble, bad = decoder._pack_host(staged, widths)
    decoder._device_call(staged, specs)[0].block_until_ready()  # warm
    fn = next(iter(decoder._fn_cache.values()))  # the program just used

    # dispatch-only vs blocked
    for label in ("dispatch-only", "dispatch+block"):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = fn(bmat, lengths)
            if label == "dispatch+block":
                out.block_until_ready()
            ts.append(time.perf_counter() - t0)
            out.block_until_ready()
        print(f"{label}: min={min(ts)*1e3:.1f}ms med={sorted(ts)[2]*1e3:.1f}ms")

    # two dispatches back-to-back then block both: does device pipeline?
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        a = fn(bmat, lengths)
        b = fn(bmat, lengths)
        a.block_until_ready(); b.block_until_ready()
        ts.append(time.perf_counter() - t0)
    print(f"2x dispatch then block: med={sorted(ts)[2]*1e3:.1f}ms")

    # worker-thread overlap: device call in thread while host packs
    def host_work():
        t0 = time.perf_counter()
        stage_wal_batch(buf, offs, lens, 4)
        decoder._pack_host(staged, widths)
        return time.perf_counter() - t0

    ts = []
    for _ in range(5):
        res = {}
        def dev():
            t0 = time.perf_counter()
            fn(bmat, lengths).block_until_ready()
            res["dev"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        th = threading.Thread(target=dev)
        th.start()
        hw = host_work()
        th.join()
        total = time.perf_counter() - t0
        ts.append((total, hw, res["dev"]))
    med = sorted(ts)[2]
    print(f"thread overlap: total={med[0]*1e3:.1f}ms host={med[1]*1e3:.1f}ms dev={med[2]*1e3:.1f}ms")

    # upload count probe: is lengths a separate transfer? time with lengths pre-placed
    dl = jax.device_put(lengths)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        fn(bmat, dl).block_until_ready()
        ts.append(time.perf_counter() - t0)
    print(f"dispatch+block, lengths pre-placed: med={sorted(ts)[2]*1e3:.1f}ms")

    db = jax.device_put(bmat)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        fn(db, dl).block_until_ready()
        ts.append(time.perf_counter() - t0)
    print(f"dispatch+block, all pre-placed: med={sorted(ts)[2]*1e3:.1f}ms")


if __name__ == "__main__":
    main()
