"""etl-lint: fixture expectations, baseline round-trip, CLI contract,
and the tier-1 repo-wide enforcement run.

Fixture files under tests/fixtures/lint/ mirror the package layout
(runtime/, ops/, destinations/) so path-scoped rules apply exactly as
they do on the real tree. Each declares its expected finding counts in
`# expect: <rule>=<n>` header lines; absent rules expect zero.
"""

from __future__ import annotations

import json
import re
from collections import Counter
from pathlib import Path

import pytest

from etl_tpu.analysis import analyze_source, baseline as baseline_mod
from etl_tpu.analysis.cli import main as cli_main
from etl_tpu.analysis.findings import Finding, canonical_path
from etl_tpu.analysis.rules import (RULE_NAMES, analyze_paths,
                                    repo_package_dir)

FIXTURES = Path(__file__).parent / "fixtures" / "lint"
_EXPECT_RE = re.compile(r"^#\s*expect:\s*([a-z-]+)=(\d+)\s*$", re.M)


def fixture_files() -> list[Path]:
    return sorted(FIXTURES.rglob("*.py"))


def expected_counts(source: str) -> Counter:
    return Counter({rule: int(n)
                    for rule, n in _EXPECT_RE.findall(source)})


def lint_fixture(path: Path) -> list[Finding]:
    rel = path.relative_to(FIXTURES).as_posix()
    return analyze_source(path.read_text(), rel)


class TestFixtures:
    @pytest.mark.parametrize("path", fixture_files(),
                             ids=lambda p: p.relative_to(FIXTURES).as_posix())
    def test_fixture_expectations(self, path: Path) -> None:
        source = path.read_text()
        got = Counter(f.rule for f in lint_fixture(path))
        assert got == expected_counts(source), \
            [f.render() for f in lint_fixture(path)]

    def test_every_rule_has_positive_and_negative_coverage(self) -> None:
        """Acceptance criterion: rules 1-6 each have at least one fixture
        that triggers them and at least one CLEAN fixture whose path the
        rule actually applies to (a clean fixture outside a rule's path
        scope proves nothing about that rule)."""
        from etl_tpu.analysis.rules import default_rules

        positive: set[str] = set()
        negative: set[str] = set()
        for path in fixture_files():
            rel = path.relative_to(FIXTURES).as_posix()
            counts = expected_counts(path.read_text())
            positive |= {r for r, n in counts.items() if n > 0}
            if sum(counts.values()) == 0:
                negative |= {r.name for r in default_rules()
                             if r.applies_to(rel)}
        assert positive == set(RULE_NAMES), \
            f"rules without a positive fixture: " \
            f"{set(RULE_NAMES) - positive}"
        assert negative == set(RULE_NAMES), \
            f"rules without an in-scope clean fixture: " \
            f"{set(RULE_NAMES) - negative}"

    def test_regression_engine_autotune_probe_pattern(self) -> None:
        """device-sync-in-async catches the jit-compiling autotune probe
        written directly into async code. (The round-5 advisor's actual
        bug fired through a sync call chain the lexical rule cannot see;
        that fix is guarded by test_pipeline_start_awaits_prewarm.)"""
        findings = lint_fixture(FIXTURES / "runtime" / "bad_autotune_probe.py")
        details = {f.detail for f in findings
                   if f.rule == "device-sync-in-async"}
        assert "autotune.resolve_device_min_rows" in details
        assert "np.asarray" in details

    def test_pipeline_start_awaits_prewarm(self) -> None:
        """The engine.py:340 fix itself: Pipeline.start() must await
        autotune.prewarm() so first-decoder construction on the event
        loop hits the per-process cost-model cache instead of running
        the jit-compiling probe synchronously (round-5 advisor)."""
        import ast as ast_mod

        src = (repo_package_dir() / "runtime" / "pipeline.py").read_text()
        tree = ast_mod.parse(src)
        start = next(
            n for cls in ast_mod.walk(tree)
            if isinstance(cls, ast_mod.ClassDef) and cls.name == "Pipeline"
            for n in cls.body
            if isinstance(n, ast_mod.AsyncFunctionDef) and n.name == "start")
        awaited = {
            n.value.func.attr for n in ast_mod.walk(start)
            if isinstance(n, ast_mod.Await)
            and isinstance(n.value, ast_mod.Call)
            and isinstance(n.value.func, ast_mod.Attribute)}
        assert "prewarm" in awaited, \
            "Pipeline.start() no longer awaits autotune.prewarm()"

    def test_raise_after_nested_callable_still_counts(self) -> None:
        """has_raise must not stop at a nested lambda/def that appears
        before the raise in walk order (code-review finding)."""
        src = ("import asyncio\n\n\n"
               "async def f(task, x):\n"
               "    try:\n"
               "        await task\n"
               "    except asyncio.CancelledError:\n"
               "        for h in (lambda: 1,):\n"
               "            if x:\n"
               "                raise\n")
        assert analyze_source(src, "runtime/x.py") == []

    def test_async_method_match_is_per_class(self) -> None:
        """A sync `self.flush()` must not be flagged because an UNRELATED
        class in the module has `async def flush` (code-review finding)."""
        src = ("class A:\n"
               "    def flush(self):\n"
               "        pass\n"
               "\n"
               "    def run(self):\n"
               "        self.flush()\n"
               "\n\n"
               "class B:\n"
               "    async def flush(self):\n"
               "        pass\n")
        assert analyze_source(src, "runtime/x.py") == []
        # ...but the same call IS flagged within the defining class
        src_bad = src.replace("class B:", "class C:\n"
                              "    async def go(self):\n"
                              "        pass\n\n"
                              "    def stop(self):\n"
                              "        self.go()\n\n\nclass B:")
        assert [f.rule for f in analyze_source(src_bad, "runtime/x.py")] \
            == ["unawaited-coroutine"]

    def test_inline_suppression_is_rule_specific(self) -> None:
        src = ("import time\n\n\n"
               "async def f():\n"
               "    time.sleep(1)  # etl-lint: ignore[orphaned-task]\n")
        findings = analyze_source(src, "runtime/x.py")
        # the ignore names a different rule -> the finding stays
        assert [f.rule for f in findings] == ["blocking-call-in-async"]

    def test_suppression_in_string_literal_is_inert(self) -> None:
        """Only COMMENT tokens suppress — quoting the ignore syntax in a
        string on the finding's line must not mask it (code-review
        finding)."""
        src = ("import time\n\n\n"
               "async def f():\n"
               "    time.sleep(1); s = \"use # etl-lint: "
               "ignore[blocking-call-in-async] to suppress\"\n"
               "    return s\n")
        findings = analyze_source(src, "runtime/x.py")
        assert [f.rule for f in findings] == ["blocking-call-in-async"]


class TestFingerprints:
    def test_canonical_path_strips_package_prefix(self) -> None:
        assert canonical_path("/root/repo/etl_tpu/runtime/x.py") \
            == "runtime/x.py"
        assert canonical_path("etl_tpu/runtime/x.py") == "runtime/x.py"
        assert canonical_path("runtime/x.py") == "runtime/x.py"

    def test_fingerprint_survives_line_drift(self) -> None:
        src = "import time\n\n\nasync def f():\n    time.sleep(1)\n"
        shifted = "\n\n# a new header comment\n" + src
        fp = [f.fingerprint for f in analyze_source(src, "runtime/x.py")]
        fp2 = [f.fingerprint
               for f in analyze_source(shifted, "runtime/x.py")]
        assert fp == fp2 and len(fp) == 1


class TestBaseline:
    SRC = "import time\n\n\nasync def f():\n    time.sleep(1)\n"

    def findings(self) -> list[Finding]:
        return analyze_source(self.SRC, "runtime/x.py")

    def test_round_trip_add_suppress_remove(self, tmp_path: Path) -> None:
        findings = self.findings()
        assert findings, "fixture must produce a finding"
        bl_file = tmp_path / "baseline.json"

        # add: grandfather the finding
        baseline_mod.save(findings, bl_file,
                          reasons={findings[0].fingerprint: "grandfathered"})
        allowed = baseline_mod.load(bl_file)
        violations, stale = baseline_mod.apply(findings, allowed)
        assert violations == [] and stale == {}

        # the finding gets fixed: the entry goes stale, nothing fails
        violations, stale = baseline_mod.apply([], allowed)
        assert violations == []
        assert stale == {findings[0].fingerprint: 1}

        # remove: saving over the fixed state prunes the entry
        baseline_mod.save([], bl_file)
        assert baseline_mod.load(bl_file) == {}

    def test_new_debt_never_hides_behind_old_debt(self,
                                                  tmp_path: Path) -> None:
        findings = self.findings()
        bl_file = tmp_path / "baseline.json"
        baseline_mod.save(findings, bl_file)
        allowed = baseline_mod.load(bl_file)
        # a SECOND occurrence of the same fingerprint appears lower in
        # the file -> only the new one is a violation
        doubled = analyze_source(
            self.SRC + "\n\nasync def g():\n    time.sleep(2)\n",
            "runtime/x.py")
        assert len(doubled) == 2
        violations, _ = baseline_mod.apply(doubled, allowed)
        assert len(violations) == 1
        assert violations[0].line == max(f.line for f in doubled)

    def test_missing_baseline_file_allows_nothing(self,
                                                  tmp_path: Path) -> None:
        assert baseline_mod.load(tmp_path / "absent.json") == {}


class TestCli:
    def test_bad_fixtures_exit_nonzero(self, capsys) -> None:
        rc = cli_main([str(FIXTURES), "--no-baseline", "-q"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "bad_blocking_sleep.py" in out

    def test_repo_wide_run_is_clean(self, capsys) -> None:
        """Tier-1 enforcement: the analyzer over the whole package with
        the shipped baseline must be violation-free — every rule is live
        for all future PRs."""
        rc = cli_main([str(repo_package_dir())])
        out = capsys.readouterr()
        assert rc == 0, out.out + out.err
        assert "stale" not in out.err, \
            f"baseline has stale entries, prune them:\n{out.err}"

    def test_json_output_shape(self, capsys) -> None:
        rc = cli_main([str(FIXTURES / "runtime" / "bad_orphaned_task.py"),
                       "--no-baseline", "--json"])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["baselined"] == 0
        assert {v["rule"] for v in data["violations"]} == {"orphaned-task"}
        for key in ("fingerprint", "path", "line", "scope", "detail"):
            assert key in data["violations"][0]

    def test_update_baseline_then_clean(self, tmp_path, capsys) -> None:
        target = str(FIXTURES / "runtime" / "bad_cancellation.py")
        bl = tmp_path / "bl.json"
        assert cli_main([target, "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        assert cli_main([target, "--baseline", str(bl), "-q"]) == 0
        capsys.readouterr()

    def test_scoped_update_preserves_out_of_scope_entries(
            self, tmp_path, capsys) -> None:
        """--update-baseline over a subtree must not destroy grandfathered
        entries (and reasons) for files it never scanned (code-review
        finding)."""
        bl = tmp_path / "bl.json"
        pkg = repo_package_dir()
        # full-tree baseline first
        assert cli_main([str(pkg), "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        full = baseline_mod.load(bl)
        assert any(fp.split("|")[1].startswith("api/") for fp in full)
        # scoped update over testing/ only: api/ entries must survive
        assert cli_main([str(pkg / "testing"), "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        scoped = baseline_mod.load(bl)
        assert scoped == full
        # and the whole tree still passes against it
        assert cli_main([str(pkg), "--baseline", str(bl), "-q"]) == 0
        capsys.readouterr()

    def test_subdir_scan_matches_full_scan_fingerprints(self) -> None:
        """Scanning a package subtree produces the same fingerprints as
        the full scan reaching the same files."""
        pkg = repo_package_dir()
        sub = {f.fingerprint for f in analyze_paths([str(pkg / "api")])}
        full = {f.fingerprint for f in analyze_paths([str(pkg)])
                if f.path.startswith("api/")}
        assert sub == full and sub

    def test_list_rules(self, capsys) -> None:
        assert cli_main(["--list-rules"]) == 0
        assert set(capsys.readouterr().out.split()) == set(RULE_NAMES)

    def test_syntax_error_exits_two(self, tmp_path, capsys) -> None:
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        assert cli_main([str(bad)]) == 2
        capsys.readouterr()

    def test_nonexistent_path_exits_two(self, tmp_path, capsys) -> None:
        # a typo'd CI path must not silently scan nothing and pass
        assert cli_main([str(tmp_path / "nope")]) == 2
        capsys.readouterr()


class TestAnalyzePaths:
    def test_directory_scan_matches_per_file(self) -> None:
        per_dir = analyze_paths([str(FIXTURES)])
        per_file = [f for p in fixture_files() for f in lint_fixture(p)]
        assert sorted(f.fingerprint for f in per_dir) \
            == sorted(f.fingerprint for f in per_file)

    def test_single_file_arg_keeps_path_scope_and_fingerprint(self) -> None:
        """Scanning one file must apply the same path-scoped rules and
        produce the same fingerprints as the directory scan, so per-file
        editor/pre-commit runs agree with the baseline (code-review
        finding: the old base=parent collapsed api/db.py to db.py)."""
        target = repo_package_dir() / "api" / "db.py"
        per_file = analyze_paths([str(target)])
        assert any(f.rule == "blocking-call-in-async"
                   and f.path == "api/db.py" for f in per_file), \
            [f.render() for f in per_file]


class TestRuntimeFixes:
    """The satellite fixes the analyzer forced, verified behaviorally."""

    async def test_autotune_prewarm_runs_off_loop_and_caches(self) -> None:
        from etl_tpu.ops import autotune

        model = await autotune.prewarm()
        # CPU backend (conftest pins JAX_PLATFORMS=cpu): no separate
        # accelerator -> probe resolves to None and is cached
        assert model is None
        assert autotune._MEASURED is not None
        assert await autotune.prewarm() is None

    def _probe_batch(self):
        from etl_tpu.models import (ColumnSchema, Oid,
                                    ReplicatedTableSchema, TableName,
                                    TableSchema)
        from etl_tpu.ops.staging import stage_copy_chunk

        schema = ReplicatedTableSchema.with_all_columns(TableSchema(
            1, TableName("etl", "lint_probe"),
            tuple(ColumnSchema(f"c{i}", Oid.INT8) for i in range(4))))
        line = b"\t".join(str(100 + i).encode() for i in range(4))
        return schema, stage_copy_chunk((line + b"\n") * 64, 4)

    def test_probe_decode_with_telemetry_off_leaves_counters_alone(self):
        """Satellite: autotune's warm+reps probe decodes must not skew
        the decode-routing share metrics."""
        from etl_tpu.ops.engine import DeviceDecoder
        from etl_tpu.telemetry.metrics import (
            ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
            ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
            ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL, registry)

        names = (ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
                 ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
                 ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL)
        schema, staged = self._probe_batch()

        def routed_total() -> float:
            return sum(registry.get_counter(n) for n in names)

        silent = DeviceDecoder(schema, device_min_rows=1 << 30, mesh=None,
                               telemetry=False)
        before = routed_total()
        silent.decode(staged)
        assert routed_total() == before, \
            "telemetry=False decode must not touch routing counters"

        loud = DeviceDecoder(schema, device_min_rows=1 << 30, mesh=None)
        loud.decode(staged)
        assert routed_total() == before + staged.n_rows

    def test_string_column_decodes_via_arrow_gather(self):
        """Satellite: the unreachable per-row STRING branch is gone;
        STRING still decodes correctly through the lazy Arrow path."""
        from etl_tpu.models import (ColumnSchema, Oid,
                                    ReplicatedTableSchema, TableName,
                                    TableSchema)
        from etl_tpu.ops.engine import DeviceDecoder
        from etl_tpu.ops.staging import stage_copy_chunk

        schema = ReplicatedTableSchema.with_all_columns(TableSchema(
            1, TableName("etl", "lint_str"),
            (ColumnSchema("s", Oid.TEXT),)))
        staged = stage_copy_chunk(b"hello\nworld\n\\N\n", 1)
        batch = DeviceDecoder(schema, mesh=None).decode(staged)
        rows = [r.values[0] for r in batch.to_rows()]
        assert rows == ["hello", "world", None]

    def test_fault_injecting_destination_holds_release_task(self):
        """Satellite (orphaned-task): the HOLD release task is owned by a
        TaskSet, not a bare ensure_future."""
        from etl_tpu.destinations.memory import FaultInjectingDestination, \
            MemoryDestination

        dest = FaultInjectingDestination(MemoryDestination())
        assert hasattr(dest, "_tasks")

    async def test_shutdown_resolves_held_acks(self):
        """A HOLD ack outstanding at shutdown must resolve (with an
        error), not hang the consumer forever (code-review finding on
        the TaskSet fix)."""
        import asyncio

        from etl_tpu.destinations.memory import (FaultAction,
                                                 FaultInjectingDestination,
                                                 FaultKind,
                                                 MemoryDestination)
        from etl_tpu.models.errors import EtlError

        dest = FaultInjectingDestination(MemoryDestination())
        dest.script("write_events", FaultAction(FaultKind.HOLD))
        ack = await dest.write_events([])
        await dest.shutdown()
        with pytest.raises(EtlError):
            await asyncio.wait_for(ack.wait_durable(), timeout=5)

    async def test_hold_write_racing_shutdown_still_resolves(self):
        """A HOLD write whose writer task hasn't registered its ack yet
        when shutdown() sweeps must still resolve, not hang (code-review
        finding on the sweep)."""
        import asyncio

        from etl_tpu.destinations.memory import (FaultAction,
                                                 FaultInjectingDestination,
                                                 FaultKind,
                                                 MemoryDestination)
        from etl_tpu.models.errors import EtlError

        dest = FaultInjectingDestination(MemoryDestination())
        dest.script("write_events", FaultAction(FaultKind.HOLD))
        writer = asyncio.create_task(dest.write_events([]))
        await dest.shutdown()  # may complete before the writer starts
        ack = await writer
        with pytest.raises(EtlError):
            await asyncio.wait_for(ack.wait_durable(), timeout=5)
