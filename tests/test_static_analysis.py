"""etl-lint: fixture expectations, baseline round-trip, CLI contract,
the interprocedural pass (call graph, contexts, CFG rules), and the
tier-1 repo-wide enforcement + determinism runs.

Fixture files under tests/fixtures/lint/ mirror the package layout
(runtime/, ops/, destinations/) so path-scoped rules apply exactly as
they do on the real tree. Each declares its expected finding counts in
`# expect: <rule>=<n>` header lines; absent rules expect zero. The tree
is analyzed as ONE project (cross-module chains resolve, anchored in
the ENTRY module's file), then findings are grouped per file against
each file's own expectations.
"""

from __future__ import annotations

import json
import re
from collections import Counter
from pathlib import Path

import pytest

from etl_tpu.analysis import analyze_source, baseline as baseline_mod
from etl_tpu.analysis.cli import main as cli_main
from etl_tpu.analysis.findings import Finding, canonical_path
from etl_tpu.analysis.rules import (INTERPROC_RULE_NAMES, RULE_NAMES,
                                    analyze_paths, repo_package_dir)

FIXTURES = Path(__file__).parent / "fixtures" / "lint"
_EXPECT_RE = re.compile(r"^#\s*expect:\s*([a-z-]+)=(\d+)\s*$", re.M)

#: path-head scopes of the whole-program rules (interproc.py has no
#: per-module Rule objects, so negative coverage is computed from these)
from etl_tpu.analysis.concurrency import CONCURRENCY_RULE_SCOPES

_INTERPROC_SCOPES = {
    "arena-lease-leak": None,  # everywhere
    "donated-buffer-use": None,
    "lock-held-across-await": ("runtime", "destinations", "postgres",
                               "store", "supervision", "api", "ops"),
    "lock-order-inversion": None,
    "unsynchronized-shared-mutation": CONCURRENCY_RULE_SCOPES,
    "loop-state-from-thread": CONCURRENCY_RULE_SCOPES,
    "coordinator-store-bypass": None,  # follows the domain, not the path
}


def fixture_files() -> list[Path]:
    return sorted(FIXTURES.rglob("*.py"))


def expected_counts(source: str) -> Counter:
    return Counter({rule: int(n)
                    for rule, n in _EXPECT_RE.findall(source)})


_PROJECT_RUN: "list[Finding] | None" = None


def project_findings() -> list[Finding]:
    """One whole-tree analysis of the fixture project, cached — the
    cross-module fixtures only resolve when scanned together."""
    global _PROJECT_RUN
    if _PROJECT_RUN is None:
        _PROJECT_RUN = analyze_paths([str(FIXTURES)])
    return _PROJECT_RUN


def lint_fixture(path: Path) -> list[Finding]:
    rel = path.relative_to(FIXTURES).as_posix()
    return [f for f in project_findings() if f.path == canonical_path(rel)]


class TestFixtures:
    @pytest.mark.parametrize("path", fixture_files(),
                             ids=lambda p: p.relative_to(FIXTURES).as_posix())
    def test_fixture_expectations(self, path: Path) -> None:
        source = path.read_text()
        got = Counter(f.rule for f in lint_fixture(path))
        assert got == expected_counts(source), \
            [f.render() for f in lint_fixture(path)]

    def test_every_rule_has_positive_and_negative_coverage(self) -> None:
        """Acceptance criterion: every rule — lexical AND whole-program —
        has at least one fixture that triggers it and at least one CLEAN
        fixture whose path the rule actually applies to (a clean fixture
        outside a rule's path scope proves nothing about that rule)."""
        from etl_tpu.analysis.rules import default_rules

        positive: set[str] = set()
        negative: set[str] = set()
        for path in fixture_files():
            rel = path.relative_to(FIXTURES).as_posix()
            counts = expected_counts(path.read_text())
            positive |= {r for r, n in counts.items() if n > 0}
            if sum(counts.values()) == 0:
                negative |= {r.name for r in default_rules()
                             if r.applies_to(rel)}
                head = rel.split("/", 1)[0]
                negative |= {r for r, scopes in _INTERPROC_SCOPES.items()
                             if scopes is None or head in scopes}
        assert positive == set(RULE_NAMES), \
            f"rules without a positive fixture: " \
            f"{set(RULE_NAMES) - positive}"
        assert negative == set(RULE_NAMES), \
            f"rules without an in-scope clean fixture: " \
            f"{set(RULE_NAMES) - negative}"

    def test_regression_engine_autotune_probe_pattern(self) -> None:
        """device-sync-in-async catches the jit-compiling autotune probe
        written directly into async code. (The round-5 advisor's actual
        bug fired through a sync call chain the lexical rule cannot see;
        that fix is guarded by test_pipeline_start_awaits_prewarm.)"""
        findings = lint_fixture(FIXTURES / "runtime" / "bad_autotune_probe.py")
        details = {f.detail for f in findings
                   if f.rule == "device-sync-in-async"}
        assert "autotune.resolve_device_min_rows" in details
        assert "np.asarray" in details

    def test_pipeline_start_awaits_prewarm(self) -> None:
        """The engine.py:340 fix itself: Pipeline.start() must await
        autotune.prewarm() so first-decoder construction on the event
        loop hits the per-process cost-model cache instead of running
        the jit-compiling probe synchronously (round-5 advisor)."""
        import ast as ast_mod

        src = (repo_package_dir() / "runtime" / "pipeline.py").read_text()
        tree = ast_mod.parse(src)
        start = next(
            n for cls in ast_mod.walk(tree)
            if isinstance(cls, ast_mod.ClassDef) and cls.name == "Pipeline"
            for n in cls.body
            if isinstance(n, ast_mod.AsyncFunctionDef) and n.name == "start")
        awaited = {
            n.value.func.attr for n in ast_mod.walk(start)
            if isinstance(n, ast_mod.Await)
            and isinstance(n.value, ast_mod.Call)
            and isinstance(n.value.func, ast_mod.Attribute)}
        assert "prewarm" in awaited, \
            "Pipeline.start() no longer awaits autotune.prewarm()"

    def test_raise_after_nested_callable_still_counts(self) -> None:
        """has_raise must not stop at a nested lambda/def that appears
        before the raise in walk order (code-review finding)."""
        src = ("import asyncio\n\n\n"
               "async def f(task, x):\n"
               "    try:\n"
               "        await task\n"
               "    except asyncio.CancelledError:\n"
               "        for h in (lambda: 1,):\n"
               "            if x:\n"
               "                raise\n")
        assert analyze_source(src, "runtime/x.py") == []

    def test_async_method_match_is_per_class(self) -> None:
        """A sync `self.flush()` must not be flagged because an UNRELATED
        class in the module has `async def flush` (code-review finding)."""
        src = ("class A:\n"
               "    def flush(self):\n"
               "        pass\n"
               "\n"
               "    def run(self):\n"
               "        self.flush()\n"
               "\n\n"
               "class B:\n"
               "    async def flush(self):\n"
               "        pass\n")
        assert analyze_source(src, "runtime/x.py") == []
        # ...but the same call IS flagged within the defining class
        src_bad = src.replace("class B:", "class C:\n"
                              "    async def go(self):\n"
                              "        pass\n\n"
                              "    def stop(self):\n"
                              "        self.go()\n\n\nclass B:")
        assert [f.rule for f in analyze_source(src_bad, "runtime/x.py")] \
            == ["unawaited-coroutine"]

    def test_inline_suppression_is_rule_specific(self) -> None:
        src = ("import time\n\n\n"
               "async def f():\n"
               "    time.sleep(1)  # etl-lint: ignore[orphaned-task]\n")
        findings = analyze_source(src, "runtime/x.py")
        # the ignore names a different rule -> the finding stays
        assert [f.rule for f in findings] == ["blocking-call-in-async"]

    def test_suppression_in_string_literal_is_inert(self) -> None:
        """Only COMMENT tokens suppress — quoting the ignore syntax in a
        string on the finding's line must not mask it (code-review
        finding)."""
        src = ("import time\n\n\n"
               "async def f():\n"
               "    time.sleep(1); s = \"use # etl-lint: "
               "ignore[blocking-call-in-async] to suppress\"\n"
               "    return s\n")
        findings = analyze_source(src, "runtime/x.py")
        assert [f.rule for f in findings] == ["blocking-call-in-async"]


class TestFingerprints:
    def test_canonical_path_strips_package_prefix(self) -> None:
        assert canonical_path("/root/repo/etl_tpu/runtime/x.py") \
            == "runtime/x.py"
        assert canonical_path("etl_tpu/runtime/x.py") == "runtime/x.py"
        assert canonical_path("runtime/x.py") == "runtime/x.py"

    def test_fingerprint_survives_line_drift(self) -> None:
        src = "import time\n\n\nasync def f():\n    time.sleep(1)\n"
        shifted = "\n\n# a new header comment\n" + src
        fp = [f.fingerprint for f in analyze_source(src, "runtime/x.py")]
        fp2 = [f.fingerprint
               for f in analyze_source(shifted, "runtime/x.py")]
        assert fp == fp2 and len(fp) == 1


class TestBaseline:
    SRC = "import time\n\n\nasync def f():\n    time.sleep(1)\n"

    def findings(self) -> list[Finding]:
        return analyze_source(self.SRC, "runtime/x.py")

    def test_round_trip_add_suppress_remove(self, tmp_path: Path) -> None:
        findings = self.findings()
        assert findings, "fixture must produce a finding"
        bl_file = tmp_path / "baseline.json"

        # add: grandfather the finding
        baseline_mod.save(findings, bl_file,
                          reasons={findings[0].fingerprint: "grandfathered"})
        allowed = baseline_mod.load(bl_file)
        violations, stale = baseline_mod.apply(findings, allowed)
        assert violations == [] and stale == {}

        # the finding gets fixed: the entry goes stale, nothing fails
        violations, stale = baseline_mod.apply([], allowed)
        assert violations == []
        assert stale == {findings[0].fingerprint: 1}

        # remove: saving over the fixed state prunes the entry
        baseline_mod.save([], bl_file)
        assert baseline_mod.load(bl_file) == {}

    def test_new_debt_never_hides_behind_old_debt(self,
                                                  tmp_path: Path) -> None:
        findings = self.findings()
        bl_file = tmp_path / "baseline.json"
        baseline_mod.save(findings, bl_file)
        allowed = baseline_mod.load(bl_file)
        # a SECOND occurrence of the same fingerprint appears lower in
        # the file -> only the new one is a violation
        doubled = analyze_source(
            self.SRC + "\n\nasync def g():\n    time.sleep(2)\n",
            "runtime/x.py")
        assert len(doubled) == 2
        violations, _ = baseline_mod.apply(doubled, allowed)
        assert len(violations) == 1
        assert violations[0].line == max(f.line for f in doubled)

    def test_missing_baseline_file_allows_nothing(self,
                                                  tmp_path: Path) -> None:
        assert baseline_mod.load(tmp_path / "absent.json") == {}


class TestCli:
    def test_bad_fixtures_exit_nonzero(self, capsys) -> None:
        rc = cli_main([str(FIXTURES), "--no-baseline", "-q"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "bad_blocking_sleep.py" in out

    def test_repo_wide_run_is_clean(self, capsys) -> None:
        """Tier-1 enforcement: the analyzer over the whole package with
        the shipped baseline must be violation-free — every rule is live
        for all future PRs."""
        rc = cli_main([str(repo_package_dir())])
        out = capsys.readouterr()
        assert rc == 0, out.out + out.err
        assert "stale" not in out.err, \
            f"baseline has stale entries, prune them:\n{out.err}"

    def test_json_output_shape(self, capsys) -> None:
        rc = cli_main([str(FIXTURES / "runtime" / "bad_orphaned_task.py"),
                       "--no-baseline", "--json"])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["baselined"] == 0
        assert {v["rule"] for v in data["violations"]} == {"orphaned-task"}
        for key in ("fingerprint", "path", "line", "scope", "detail"):
            assert key in data["violations"][0]

    def test_update_baseline_then_clean(self, tmp_path, capsys) -> None:
        target = str(FIXTURES / "runtime" / "bad_cancellation.py")
        bl = tmp_path / "bl.json"
        assert cli_main([target, "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        assert cli_main([target, "--baseline", str(bl), "-q"]) == 0
        capsys.readouterr()

    def test_scoped_update_preserves_out_of_scope_entries(
            self, tmp_path, capsys) -> None:
        """--update-baseline over a subtree must not destroy grandfathered
        entries (and reasons) for files it never scanned (code-review
        finding)."""
        bl = tmp_path / "bl.json"
        pkg = repo_package_dir()
        # full-tree baseline first
        assert cli_main([str(pkg), "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        full = baseline_mod.load(bl)
        assert any(fp.split("|")[1].startswith("api/") for fp in full)
        # scoped update over testing/ only: api/ entries must survive
        assert cli_main([str(pkg / "testing"), "--baseline", str(bl),
                         "--update-baseline", "-q"]) == 0
        scoped = baseline_mod.load(bl)
        assert scoped == full
        # and the whole tree still passes against it
        assert cli_main([str(pkg), "--baseline", str(bl), "-q"]) == 0
        capsys.readouterr()

    def test_subdir_scan_matches_full_scan_fingerprints(self) -> None:
        """Scanning a package subtree produces the same fingerprints as
        the full scan reaching the same files."""
        pkg = repo_package_dir()
        sub = {f.fingerprint for f in analyze_paths([str(pkg / "api")])}
        full = {f.fingerprint for f in analyze_paths([str(pkg)])
                if f.path.startswith("api/")}
        assert sub == full and sub

    def test_list_rules(self, capsys) -> None:
        assert cli_main(["--list-rules"]) == 0
        assert set(capsys.readouterr().out.split()) == set(RULE_NAMES)

    def test_syntax_error_exits_two(self, tmp_path, capsys) -> None:
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        assert cli_main([str(bad)]) == 2
        capsys.readouterr()

    def test_nonexistent_path_exits_two(self, tmp_path, capsys) -> None:
        # a typo'd CI path must not silently scan nothing and pass
        assert cli_main([str(tmp_path / "nope")]) == 2
        capsys.readouterr()


class TestAnalyzePaths:
    def test_directory_scan_supersets_per_file(self) -> None:
        """Single-file runs see a subset of the project run: lexical
        findings (and single-module chains) agree exactly; what the
        directory run ADDS is precisely the cross-module chain findings
        a per-file run cannot resolve."""
        per_dir = analyze_paths([str(FIXTURES)])
        per_file = [
            f for p in fixture_files()
            for f in analyze_source(p.read_text(),
                                    p.relative_to(FIXTURES).as_posix())]
        dir_fps = Counter(f.fingerprint for f in per_dir)
        file_fps = Counter(f.fingerprint for f in per_file)
        assert all(dir_fps[fp] >= n for fp, n in file_fps.items()), \
            "per-file findings missing from the directory run"
        only_dir = +(dir_fps - file_fps)
        cross_module = {f.fingerprint for f in per_dir
                        if len(set(p for p, _l in f.chain_sites)) > 1}
        assert set(only_dir) <= cross_module, \
            "directory-only findings must all be cross-module chains"
        assert only_dir, "cross-module fixtures must add chain findings"

    def test_single_file_arg_keeps_path_scope_and_fingerprint(self) -> None:
        """Scanning one file must apply the same path-scoped rules and
        produce the same fingerprints as the directory scan, so per-file
        editor/pre-commit runs agree with the baseline (code-review
        finding: the old base=parent collapsed api/db.py to db.py)."""
        target = repo_package_dir() / "api" / "db.py"
        per_file = analyze_paths([str(target)])
        assert any(f.rule == "blocking-call-in-async"
                   and f.path == "api/db.py" for f in per_file), \
            [f.render() for f in per_file]


class TestRuntimeFixes:
    """The satellite fixes the analyzer forced, verified behaviorally."""

    async def test_autotune_prewarm_runs_off_loop_and_caches(self) -> None:
        from etl_tpu.ops import autotune

        model = await autotune.prewarm()
        # CPU backend (conftest pins JAX_PLATFORMS=cpu): no separate
        # accelerator -> probe resolves to None and is cached
        assert model is None
        assert autotune._MEASURED is not None
        assert await autotune.prewarm() is None

    def _probe_batch(self):
        from etl_tpu.models import (ColumnSchema, Oid,
                                    ReplicatedTableSchema, TableName,
                                    TableSchema)
        from etl_tpu.ops.staging import stage_copy_chunk

        schema = ReplicatedTableSchema.with_all_columns(TableSchema(
            1, TableName("etl", "lint_probe"),
            tuple(ColumnSchema(f"c{i}", Oid.INT8) for i in range(4))))
        line = b"\t".join(str(100 + i).encode() for i in range(4))
        return schema, stage_copy_chunk((line + b"\n") * 64, 4)

    def test_probe_decode_with_telemetry_off_leaves_counters_alone(self):
        """Satellite: autotune's warm+reps probe decodes must not skew
        the decode-routing share metrics."""
        from etl_tpu.ops.engine import DeviceDecoder
        from etl_tpu.telemetry.metrics import (
            ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
            ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
            ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL, registry)

        names = (ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
                 ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
                 ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL)
        schema, staged = self._probe_batch()

        def routed_total() -> float:
            return sum(registry.get_counter(n) for n in names)

        silent = DeviceDecoder(schema, device_min_rows=1 << 30, mesh=None,
                               telemetry=False)
        before = routed_total()
        silent.decode(staged)
        assert routed_total() == before, \
            "telemetry=False decode must not touch routing counters"

        loud = DeviceDecoder(schema, device_min_rows=1 << 30, mesh=None)
        loud.decode(staged)
        assert routed_total() == before + staged.n_rows

    def test_string_column_decodes_via_arrow_gather(self):
        """Satellite: the unreachable per-row STRING branch is gone;
        STRING still decodes correctly through the lazy Arrow path."""
        from etl_tpu.models import (ColumnSchema, Oid,
                                    ReplicatedTableSchema, TableName,
                                    TableSchema)
        from etl_tpu.ops.engine import DeviceDecoder
        from etl_tpu.ops.staging import stage_copy_chunk

        schema = ReplicatedTableSchema.with_all_columns(TableSchema(
            1, TableName("etl", "lint_str"),
            (ColumnSchema("s", Oid.TEXT),)))
        staged = stage_copy_chunk(b"hello\nworld\n\\N\n", 1)
        batch = DeviceDecoder(schema, mesh=None).decode(staged)
        rows = [r.values[0] for r in batch.to_rows()]
        assert rows == ["hello", "world", None]

    def test_fault_injecting_destination_holds_release_task(self):
        """Satellite (orphaned-task): the HOLD release task is owned by a
        TaskSet, not a bare ensure_future."""
        from etl_tpu.destinations.memory import FaultInjectingDestination, \
            MemoryDestination

        dest = FaultInjectingDestination(MemoryDestination())
        assert hasattr(dest, "_tasks")

    async def test_shutdown_resolves_held_acks(self):
        """A HOLD ack outstanding at shutdown must resolve (with an
        error), not hang the consumer forever (code-review finding on
        the TaskSet fix)."""
        import asyncio

        from etl_tpu.destinations.memory import (FaultAction,
                                                 FaultInjectingDestination,
                                                 FaultKind,
                                                 MemoryDestination)
        from etl_tpu.models.errors import EtlError

        dest = FaultInjectingDestination(MemoryDestination())
        dest.script("write_events", FaultAction(FaultKind.HOLD))
        ack = await dest.write_events([])
        await dest.shutdown()
        with pytest.raises(EtlError):
            await asyncio.wait_for(ack.wait_durable(), timeout=5)

    async def test_hold_write_racing_shutdown_still_resolves(self):
        """A HOLD write whose writer task hasn't registered its ack yet
        when shutdown() sweeps must still resolve, not hang (code-review
        finding on the sweep)."""
        import asyncio

        from etl_tpu.destinations.memory import (FaultAction,
                                                 FaultInjectingDestination,
                                                 FaultKind,
                                                 MemoryDestination)
        from etl_tpu.models.errors import EtlError

        dest = FaultInjectingDestination(MemoryDestination())
        dest.script("write_events", FaultAction(FaultKind.HOLD))
        writer = asyncio.create_task(dest.write_events([]))
        await dest.shutdown()  # may complete before the writer starts
        ack = await writer
        with pytest.raises(EtlError):
            await asyncio.wait_for(ack.wait_durable(), timeout=5)


class TestInterproc:
    """Call-graph / context-propagation edge cases (PR 5 satellite)."""

    def test_nested_sync_in_async_in_sync(self) -> None:
        """A sync def nested in an async def nested in a sync def: the
        blocking call fires only when the async layer CALLS the inner
        sync def directly (on the loop) — with the chain as proof."""
        src = ("import time\n\n\n"
               "def outer():\n"
               "    async def middle():\n"
               "        def inner():\n"
               "            time.sleep(1)\n"
               "        inner()\n"
               "    return middle\n")
        findings = analyze_source(src, "runtime/x.py")
        chains = [f for f in findings
                  if f.rule == "blocking-call-in-async" and f.chain]
        assert len(chains) == 1, [f.render() for f in findings]
        assert chains[0].chain == ("outer.middle", "outer.middle.inner")
        assert chains[0].detail == "time.sleep"

    def test_executor_lambda_is_not_an_edge(self) -> None:
        """Handing a lambda/function REFERENCE to run_in_executor is the
        sanctioned off-loop idiom — no call edge, no finding."""
        src = ("import time\n\n\n"
               "async def f(loop):\n"
               "    def work():\n"
               "        time.sleep(5)\n"
               "    await loop.run_in_executor(None, work)\n"
               "    await loop.run_in_executor(None, lambda: time.sleep(1))\n")
        assert analyze_source(src, "runtime/x.py") == []

    def test_import_aliased_decorator_resolves(self) -> None:
        src = ("from etl_tpu.analysis.annotations import hot_loop as hl\n"
               "import jax\n\n\n"
               "@hl\n"
               "def dispatch(v):\n"
               "    return jax.device_get(v)\n")
        findings = analyze_source(src, "ops/x.py")
        assert [f.rule for f in findings] == ["hot-loop-host-transfer"], \
            [f.render() for f in findings]

    def test_cyclic_call_graph_terminates_with_shortest_chain(self) -> None:
        src = ("import time\n\n\n"
               "def a(n):\n"
               "    time.sleep(1)\n"
               "    return b(n - 1)\n\n\n"
               "def b(n):\n"
               "    return a(n) if n else 0\n\n\n"
               "async def entry():\n"
               "    return a(3)\n")
        findings = analyze_source(src, "runtime/x.py")
        chains = [f for f in findings if f.chain]
        assert len(chains) == 1
        assert chains[0].chain == ("entry", "a")  # shortest witness

    def test_chain_trace_renders_resolvable_locations(self) -> None:
        src = ("import time\n\n\n"
               "def helper():\n"
               "    time.sleep(1)\n\n\n"
               "async def entry():\n"
               "    helper()\n")
        (finding,) = analyze_source(src, "runtime/x.py")
        assert finding.chain == ("entry", "helper")
        assert finding.chain_text() == "entry → helper: time.sleep"
        explain = finding.explain()
        # one resolvable path:line per hop: the entry's call site, then
        # the sink's own line inside the helper
        assert "runtime/x.py:9: entry" in explain
        assert "runtime/x.py:5: helper" in explain
        assert "sink: time.sleep" in explain
        assert finding.line == 9  # anchored at the entry's call site

    def test_self_method_resolution_through_base_class(self) -> None:
        src = ("import time\n\n\n"
               "class Base:\n"
               "    def slow(self):\n"
               "        time.sleep(1)\n\n\n"
               "class Worker(Base):\n"
               "    async def run(self):\n"
               "        self.slow()\n")
        findings = analyze_source(src, "runtime/x.py")
        assert [f.rule for f in findings] == ["blocking-call-in-async"]
        assert findings[0].chain == ("Worker.run", "Base.slow")

    def test_constructor_edge_reaches_init(self) -> None:
        src = ("import sqlite3\n\n\n"
               "class Db:\n"
               "    def __init__(self, path):\n"
               "        self.conn = sqlite3.connect(path)\n\n\n"
               "async def open_db(path):\n"
               "    return Db(path)\n")
        findings = analyze_source(src, "runtime/x.py")
        assert [f.chain for f in findings] == [("open_db", "Db.__init__")]

    def test_unresolved_receiver_is_not_traversed(self) -> None:
        """obj.method() on an unknown receiver: no edge, no finding —
        the documented precision limit."""
        src = ("async def f(obj):\n"
               "    obj.anything()\n")
        assert analyze_source(src, "runtime/x.py") == []


class TestMultilineSuppression:
    def test_ignore_on_first_line_covers_continuation(self) -> None:
        """Satellite: a suppression on the statement's first line covers
        findings the AST anchors on continuation lines."""
        src = ("import time\n\n\n"
               "async def f(x):\n"
               "    y = (x +\n"
               "         time.sleep(1))\n")
        findings = analyze_source(src, "runtime/x.py")
        assert len(findings) == 1 and findings[0].line == 6
        suppressed = src.replace(
            "y = (x +", "y = (x +  # etl-lint: ignore[blocking-call-in-async]")
        assert analyze_source(suppressed, "runtime/x.py") == []

    def test_compound_header_suppression_does_not_blanket_body(self) -> None:
        """An ignore on a `with`/`if` header line must NOT suppress
        findings inside the body — only header continuation lines."""
        src = ("import time\n\n\n"
               "async def f(x):  # etl-lint: ignore[blocking-call-in-async]\n"
               "    time.sleep(1)\n")
        findings = analyze_source(src, "runtime/x.py")
        assert [f.line for f in findings] == [5]

    def test_suppression_on_continuation_line_still_works(self) -> None:
        src = ("import time\n\n\n"
               "async def f(x):\n"
               "    y = (x +\n"
               "         time.sleep(1))"
               "  # etl-lint: ignore[blocking-call-in-async]\n")
        assert analyze_source(src, "runtime/x.py") == []


class TestCheckBaseline:
    def test_detects_stale_baseline_entry(self, tmp_path, capsys) -> None:
        target = tmp_path / "runtime"
        target.mkdir()
        (target / "clean.py").write_text("def f():\n    return 1\n")
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"version": 1, "entries": {
            "blocking-call-in-async|runtime/clean.py|f|time.sleep":
                {"count": 1, "reason": "gone"}}}))
        rc = cli_main([str(tmp_path), "--baseline", str(bl),
                       "--check-baseline", "-q"])
        out = capsys.readouterr().out
        assert rc == 1 and "stale baseline entry" in out

    def test_detects_unused_inline_ignore(self, tmp_path, capsys) -> None:
        target = tmp_path / "runtime"
        target.mkdir()
        (target / "mod.py").write_text(
            "def f():\n"
            "    return 1  # etl-lint: ignore[orphaned-task]\n")
        rc = cli_main([str(tmp_path), "--check-baseline", "--baseline",
                       str(tmp_path / "none.json"), "-q"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "ignore[orphaned-task] suppresses nothing" in out

    def test_used_ignore_and_live_baseline_pass(self, tmp_path,
                                                capsys) -> None:
        target = tmp_path / "runtime"
        target.mkdir()
        (target / "mod.py").write_text(
            "import time\n\n\n"
            "async def f():\n"
            "    time.sleep(1)  # etl-lint: ignore[blocking-call-in-async]\n")
        rc = cli_main([str(tmp_path), "--check-baseline", "--baseline",
                       str(tmp_path / "none.json"), "-q"])
        assert rc == 0, capsys.readouterr().out
        capsys.readouterr()

    def test_shipped_baseline_is_live(self, capsys) -> None:
        """The committed baseline has no dead entries and every inline
        ignore in the tree still suppresses something."""
        rc = cli_main([str(repo_package_dir()), "--check-baseline", "-q"])
        out = capsys.readouterr()
        assert rc == 0, out.out + out.err


class TestCliFormats:
    def test_github_format_emits_workflow_commands(self, capsys) -> None:
        rc = cli_main([str(FIXTURES), "--no-baseline",
                       "--format=github", "-q"])
        assert rc == 1
        out = capsys.readouterr().out
        assert out.startswith("::error file=")
        assert "title=etl-lint blocking-call-in-async" in out
        assert "\n\n" not in out.strip()  # one annotation per line

    def test_callgraph_dump(self, capsys) -> None:
        rc = cli_main([str(FIXTURES), "--callgraph"])
        assert rc == 0
        out = capsys.readouterr().out
        assert ("runtime/bad_transitive_blocking.py::pump_with_helper_sleep"
                " -> runtime/helpers_blocking.py::do_backoff") in out

    def test_explain_prints_chain_hops(self, capsys) -> None:
        rc = cli_main([str(FIXTURES), "--no-baseline", "--explain", "-q"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "sink: time.sleep" in out
        assert "runtime/helpers_blocking.py:" in out

    def test_json_includes_chain(self, capsys) -> None:
        rc = cli_main([str(FIXTURES / "runtime"), "--no-baseline", "--json"])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        chained = [v for v in data["violations"] if v["chain"]]
        assert chained and all("chain_sites" in v for v in chained)


class TestTier1Enforcement:
    def test_repo_wide_interproc_run_is_deterministic(self) -> None:
        """Tier-1: two full interprocedural runs produce byte-identical
        findings AND chain traces — BFS order, lock-pair ordering, and
        dataflow worklists are all deterministic."""
        pkg = str(repo_package_dir())
        one = analyze_paths([pkg])
        two = analyze_paths([pkg])
        key = [(f.fingerprint, f.line, f.col, f.chain, f.chain_sites,
                f.message) for f in one]
        assert key == [(f.fingerprint, f.line, f.col, f.chain,
                        f.chain_sites, f.message) for f in two]
        assert one, "repo-wide run found nothing: analyzer broken"

    def test_arena_lease_is_a_context_manager(self) -> None:
        """Drive-by: the `with pool.lease()` form the arena-lease-leak
        rule sanctions releases on exceptions for real."""
        from etl_tpu.ops.staging import StagingArenaPool

        pool = StagingArenaPool()
        with pytest.raises(RuntimeError):
            with pool.lease() as lease:
                lease.take((8,), "uint8")
                assert pool.outstanding == 1
                raise RuntimeError("boom")
        assert pool.outstanding == 0


class TestReviewRegressions:
    """Fixes from the PR-5 review pass, pinned."""

    def test_donated_rebind_idiom_is_safe(self) -> None:
        """`buf = step(buf)` rebinds the name to the jit OUTPUT buffer —
        the canonical donation idiom must not stay tainted."""
        src = ("import jax\n\n"
               "step = jax.jit(lambda b: b, donate_argnums=(0,))\n\n\n"
               "def loop(buf):\n"
               "    buf = step(buf)\n"
               "    return buf.sum()\n")
        assert analyze_source(src, "ops/x.py") == []

    def test_nested_finally_release_is_clean(self) -> None:
        """An inner finally's exit must route through the OUTER finally,
        not straight to EXIT past the release."""
        src = ("def f(pool, work, log):\n"
               "    lease = pool.lease()\n"
               "    try:\n"
               "        try:\n"
               "            work()\n"
               "        finally:\n"
               "            log()\n"
               "    finally:\n"
               "        lease.release()\n")
        assert analyze_source(src, "ops/x.py") == []

    def test_wait_for_wrapped_await_keeps_the_edge(self, tmp_path) -> None:
        """The unbounded-await rule tells authors to wrap awaits in
        asyncio.wait_for — complying must not hide the callee from the
        transitive blocking rule. The helper lives OUTSIDE the
        event-loop scopes (it is not its own entry for rule 1), so the
        sink is only reachable through the wrapped await edge."""
        import ast as ast_mod

        from etl_tpu.analysis.callgraph import Project

        src = ("import asyncio\n"
               "import time\n\n\n"
               "async def helper():\n"
               "    time.sleep(1)\n\n\n"
               "async def entry():\n"
               "    await asyncio.wait_for(helper(), 5)\n")
        # the call-graph layer: helper() inside wait_for is awaited
        proj = Project.build([("runtime/x.py", src, ast_mod.parse(src))])
        entry = proj.modules["runtime/x.py"].functions["entry"]
        helper_site = next(s for s in entry.calls if s.lexical == "helper")
        assert helper_site.awaited and helper_site.resolved is not None
        # end to end: an ops/ coroutine awaited via wait_for from
        # runtime/ still produces the chain finding
        (tmp_path / "ops").mkdir()
        (tmp_path / "runtime").mkdir()
        (tmp_path / "ops" / "helpers.py").write_text(
            "import time\n\n\nasync def drain():\n    time.sleep(1)\n")
        (tmp_path / "runtime" / "worker.py").write_text(
            "import asyncio\n\nfrom ..ops.helpers import drain\n\n\n"
            "async def entry():\n"
            "    await asyncio.wait_for(drain(), 5)\n")
        findings = analyze_paths([str(tmp_path)])
        chains = [f for f in findings if f.chain]
        assert [c.chain for c in chains] == [("entry", "drain")], \
            [f.render() for f in findings]

    def test_lease_container_handoff_escapes(self) -> None:
        """`self._pending.append(lease)` / `q.put_nowait(lease)` hand
        ownership to a later consumer — not leaks."""
        src = ("def f(self, pool, q):\n"
               "    lease = pool.lease()\n"
               "    self._pending.append(lease)\n\n\n"
               "def g(pool, q):\n"
               "    lease = pool.lease()\n"
               "    q.put_nowait(lease)\n")
        assert analyze_source(src, "ops/x.py") == []

    def test_github_annotation_path_only_prefixes_package_files(
            self, capsys, monkeypatch) -> None:
        import os

        monkeypatch.chdir(Path(__file__).resolve().parent.parent)
        rc = cli_main([str(FIXTURES), "--no-baseline",
                       "--format=github", "-q"])
        assert rc == 1
        out = capsys.readouterr().out
        # fixture files are NOT under etl_tpu/ — no bogus prefix
        assert "file=etl_tpu/runtime/bad_" not in out
        assert "file=runtime/bad_" in out
