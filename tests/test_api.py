"""Control-plane API tests: CRUD, tenant isolation, encryption at rest,
lifecycle orchestration against a fake k8s API (reference strategy:
per-route suites + mock K8sClient, SURVEY §4.7)."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from etl_tpu.api.app import ApiState, build_app
from etl_tpu.api.crypto import ConfigCipher, EncryptionKey
from etl_tpu.api.orchestrator import (K8sOrchestrator, Orchestrator,
                                      ReplicatorSpec, ReplicatorStatus)
from etl_tpu.testing.fake_http import RecordingHttpServer


class StubOrchestrator(Orchestrator):
    def __init__(self):
        self.calls = []
        self.specs = []
        self.running = set()

    async def start_pipeline(self, spec):
        self.calls.append(("start", spec.pipeline_id, spec.config))
        self.specs.append(spec)
        self.running.add(spec.pipeline_id)

    async def stop_pipeline(self, pipeline_id):
        self.calls.append(("stop", pipeline_id))
        self.running.discard(pipeline_id)

    async def status(self, pipeline_id):
        state = "running" if pipeline_id in self.running else "stopped"
        return ReplicatorStatus(pipeline_id, state)


_BACKEND = "sqlite"


@pytest.fixture(autouse=True, params=["sqlite", "postgres"])
def api_backend(request):
    """Every API test runs against BOTH storage backends (VERDICT r3
    #10 — the reference API owns a Postgres database; the suite must
    prove the same statement set works over the wire-client pool)."""
    global _BACKEND
    _BACKEND = request.param
    yield request.param
    _BACKEND = "sqlite"


async def _make_db(tmp_path):
    """(db-or-path, extra-cleanup) for the active backend."""
    if _BACKEND == "postgres":
        from etl_tpu.api.db import PostgresApiDb
        from etl_tpu.config import PgConnectionConfig
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.fake_pg_server import FakePgServer

        server = FakePgServer(FakeDatabase())
        await server.start()
        db = PostgresApiDb(PgConnectionConfig(
            host="127.0.0.1", port=server.port,
            name="postgres", username="etl"))
        return db, server.stop
    return str(tmp_path / "api.db"), None


async def make_client(tmp_path, orchestrator=None, api_key=None):
    db, cleanup = await _make_db(tmp_path)
    state = ApiState(db, ConfigCipher(EncryptionKey.generate()),
                     orchestrator or StubOrchestrator(), api_key=api_key)
    app = build_app(state)
    if cleanup is not None:
        async def _stop(_app):
            await cleanup()

        app.on_cleanup.append(_stop)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, state


H = {"tenant_id": "acme"}


async def setup_pipeline(client):
    await client.post("/v1/tenants", json={"id": "acme", "name": "Acme"})
    src = await (await client.post(
        "/v1/sources", headers=H,
        json={"name": "prod-db",
              "config": {"host": "db", "port": 5432, "name": "app",
                         "username": "etl", "password": "s3cret-password-42"}})).json()
    dst = await (await client.post(
        "/v1/destinations", headers=H,
        json={"name": "lake", "config": {"type": "lake",
                                         "warehouse_path": "/tmp/wh"}})).json()
    resp = await client.post(
        "/v1/pipelines", headers=H,
        json={"source_id": src["id"], "destination_id": dst["id"],
              "publication_name": "pub"})
    return (await resp.json())["id"]


class TestCrudAndTenancy:
    async def test_full_crud(self, tmp_path):
        client, state = await make_client(tmp_path)
        try:
            pid = await setup_pipeline(client)
            resp = await client.get(f"/v1/pipelines/{pid}", headers=H)
            doc = await resp.json()
            assert doc["publication_name"] == "pub"
            resp = await client.get("/v1/sources/1", headers=H)
            src = await resp.json()
            # secrets are MASKED on read (ADVICE r1: never echo decrypted
            # credentials); non-secret fields stay readable
            assert src["config"]["password"] == "********"
            assert src["config"]["host"] == "db"
            # raw row on disk is encrypted
            raw = (await state.db.run(
                "SELECT config_enc FROM api_sources"))[0][0]
            assert "s3cret-password-42" not in raw
            env = json.loads(raw)
            assert set(env) == {"key_id", "nonce", "ciphertext"}
        finally:
            await client.close()

    async def test_tenant_isolation(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            pid = await setup_pipeline(client)
            other = {"tenant_id": "rival"}
            assert (await client.get(f"/v1/pipelines/{pid}",
                                     headers=other)).status == 404
            assert (await client.get("/v1/sources/1",
                                     headers=other)).status == 404
            listing = await (await client.get("/v1/pipelines",
                                              headers=other)).json()
            assert listing == []
        finally:
            await client.close()

    async def test_missing_tenant_header(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            assert (await client.get("/v1/sources")).status == 401
            assert (await client.get(
                "/v1/sources", headers={"tenant_id": "x; DROP"})).status == 401
        finally:
            await client.close()

    async def test_validation_errors(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            assert (await client.post(
                "/v1/tenants", json={"id": "acme", "name": "B"})).status == 409
            assert (await client.post(
                "/v1/pipelines", headers=H, json={})).status == 400
            assert (await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": 99, "destination_id": 99,
                      "publication_name": "p"})).status == 404
        finally:
            await client.close()


class TestLifecycle:
    async def test_start_stop_status(self, tmp_path):
        orch = StubOrchestrator()
        client, _ = await make_client(tmp_path, orch)
        try:
            pid = await setup_pipeline(client)
            resp = await client.post(f"/v1/pipelines/{pid}/start", headers=H)
            assert resp.status == 202
            # the orchestrator received the assembled, DECRYPTED config
            op, opid, config = orch.calls[0]
            assert (op, opid) == ("start", pid)
            assert config["pg_connection"]["password"] == "s3cret-password-42"
            assert config["destination"]["type"] == "lake"
            assert config["publication_name"] == "pub"
            st = await (await client.get(f"/v1/pipelines/{pid}/status",
                                         headers=H)).json()
            assert st["state"] == "running"
            await client.post(f"/v1/pipelines/{pid}/stop", headers=H)
            st = await (await client.get(f"/v1/pipelines/{pid}/status",
                                         headers=H)).json()
            assert st["state"] == "stopped"
        finally:
            await client.close()

    async def test_replication_status_and_rollback(self, tmp_path):
        from etl_tpu.models.errors import RetryKind
        from etl_tpu.runtime.state import TableState, TableStateType
        from etl_tpu.store.sql import SqliteStore

        store_path = str(tmp_path / "pipe.db")
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            src = await (await client.post(
                "/v1/sources", headers=H,
                json={"name": "s", "config": {
                    "host": "db", "port": 5432, "name": "app",
                    "username": "etl"}})).json()
            dst = await (await client.post(
                "/v1/destinations", headers=H,
                json={"name": "d", "config": {"type": "memory"}})).json()
            resp = await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": src["id"], "destination_id": dst["id"],
                      "publication_name": "pub", "store_path": store_path})
            pid = (await resp.json())["id"]
            # seed the pipeline's durable store
            store = SqliteStore(store_path, pid)
            await store.connect()
            await store.update_table_state(101, TableState.ready())
            await store.update_table_state(102, TableState.errored(
                "boom", retry_policy=RetryKind.MANUAL, retry_attempts=5))
            await store.close()

            doc = await (await client.get(
                f"/v1/pipelines/{pid}/replication-status",
                headers=H)).json()
            by_id = {t["table_id"]: t for t in doc["tables"]}
            assert by_id[101]["state"] == "ready"
            assert by_id[102]["state"] == "errored"
            assert by_id[102]["retry_policy"] == "manual"

            doc = await (await client.post(
                f"/v1/pipelines/{pid}/rollback-tables", headers=H,
                json={})).json()
            assert doc["rolled_back"] == [102]  # only errored tables
            store = SqliteStore(store_path, pid)
            await store.connect()
            st = await store.get_table_state(102)
            assert st.type is TableStateType.INIT
            await store.close()
        finally:
            await client.close()


class TestK8sOrchestrator:
    async def test_resource_creation(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            spec = ReplicatorSpec(pipeline_id=7, tenant_id="acme",
                                  config={"pipeline_id": 7,
                                          "publication_name": "pub",
                                          "pg_connection": {
                                              "host": "db",
                                              "password": "hunter2"}})
            await orch.start_pipeline(spec)
            paths = server.paths()
            assert "POST /api/v1/namespaces/etl/secrets" in paths
            assert "POST /api/v1/namespaces/etl/configmaps" in paths
            assert "POST /apis/apps/v1/namespaces/etl/statefulsets" in paths
            sts = [r for r in server.requests
                   if r.path.endswith("/statefulsets")][0].json
            assert sts["metadata"]["name"] == "etl-replicator-7"
            assert sts["metadata"]["labels"]["tenant_id"] == "acme"
            # credentials live in the Secret as APP_ env names; the
            # ConfigMap's config document carries NO secret values
            secret = [r for r in server.requests
                      if r.path.endswith("/secrets")][0].json
            assert secret["stringData"] == {
                "APP_PG_CONNECTION__PASSWORD": "hunter2"}
            cm = [r for r in server.requests
                  if r.path.endswith("/configmaps")][0].json
            # key must be base.yaml — the name the config loader reads
            assert "publication_name: pub" in cm["data"]["base.yaml"]
            assert "hunter2" not in cm["data"]["base.yaml"]
            container = sts["spec"]["template"]["spec"]["containers"][0]
            assert container["envFrom"] == [
                {"secretRef": {"name": "etl-replicator-7-secrets"}}]
            await orch.stop_pipeline(7)
            deletes = [p for p in server.paths() if p.startswith("DELETE")]
            # stop is a PAUSE: workload resources go (sts, secret,
            # configmap); the warehouse PVC and the maintenance CronJob
            # stay — deleting the CronJob from the pause gate's own
            # /stop call would cascade-GC the running maintenance Job
            assert len(deletes) == 3
            assert not any("persistentvolumeclaims" in p for p in deletes)
            assert not any("cronjobs" in p for p in deletes)
            # ...but it is SUSPENDED so a scheduled run can't auto-start
            # the deliberately paused pipeline
            suspends = [r for r in server.requests
                        if r.method == "PATCH" and "cronjobs" in r.path]
            assert suspends and suspends[-1].json == {
                "spec": {"suspend": True}}
            # permanent teardown drops the CronJob and PVC too
            await orch.delete_pipeline(7)
            deletes = [p for p in server.paths() if p.startswith("DELETE")]
            assert sum(1 for p in deletes
                       if "persistentvolumeclaims" in p) == 1
            assert sum(1 for p in deletes if "cronjobs" in p) == 1
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_lake_destination_gets_maintenance_cronjob(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            await orch.start_pipeline(ReplicatorSpec(
                3, "t", {"destination": {"type": "lake",
                                         "warehouse_path": "/wh"},
                         "maintenance": {"schedule": "0 2 * * *"}}))
            cron = [r for r in server.requests
                    if r.path.endswith("/cronjobs")][0].json
            assert cron["metadata"]["name"] == "etl-replicator-3-maintenance"
            assert cron["spec"]["schedule"] == "0 2 * * *"
            assert cron["spec"]["concurrencyPolicy"] == "Forbid"
            job_spec = cron["spec"]["jobTemplate"]["spec"]["template"][
                "spec"]
            args = job_spec["containers"][0]["args"]
            assert "--warehouse" in args and "/wh" in args
            # the pause gate (maintenance.py run_maintenance) requires
            # BOTH --api-url and --pipeline-id; without the id the job
            # compacts while the replicator is live
            assert "--pipeline-id" in args
            assert args[args.index("--pipeline-id") + 1] == "3"
            assert "--coordinate" not in args  # not opted in here
            # no control-plane URL configured -> no pause-gate API args
            # (the replicator pod serves only /metrics + /health, so
            # pointing --api-url at it would fail every run)
            assert "--api-url" not in args
            # replicator + maintenance share ONE warehouse PVC mounted at
            # the warehouse path — separate pod-local filesystems would
            # make compaction a silent no-op
            assert job_spec["volumes"] == [{
                "name": "warehouse", "persistentVolumeClaim": {
                    "claimName": "etl-replicator-3-warehouse"}}]
            assert job_spec["containers"][0]["volumeMounts"] == [
                {"name": "warehouse", "mountPath": "/wh"}]
            pvc = [r for r in server.requests
                   if r.path.endswith("/persistentvolumeclaims")]
            assert len(pvc) == 1
            assert pvc[0].json["metadata"]["name"] == \
                "etl-replicator-3-warehouse"
            sts = [r for r in server.requests
                   if r.path.endswith("/statefulsets")][0].json
            sts_spec = sts["spec"]["template"]["spec"]
            assert {"name": "warehouse", "persistentVolumeClaim": {
                "claimName": "etl-replicator-3-warehouse"}} \
                in sts_spec["volumes"]
            assert {"name": "warehouse", "mountPath": "/wh"} \
                in sts_spec["containers"][0]["volumeMounts"]
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_coordinated_maintenance_cronjob_opt_in(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            await orch.start_pipeline(ReplicatorSpec(
                4, "t", {"destination": {"type": "lake",
                                         "warehouse_path": "/wh"},
                         "maintenance": {"coordination": True}}))
            cron = [r for r in server.requests
                    if r.path.endswith("/cronjobs")][0].json
            job_spec = cron["spec"]["jobTemplate"]["spec"]["template"][
                "spec"]
            args = job_spec["containers"][0]["args"]
            assert "--coordinate" in args
            # coordination rides the shared warehouse catalog: no API args
            assert "--api-url" not in args
            # RWO PVC: the job must be co-scheduled with the replicator
            aff = job_spec["affinity"]["podAffinity"][
                "requiredDuringSchedulingIgnoredDuringExecution"][0]
            assert aff["labelSelector"]["matchLabels"] == {
                "app": "etl-replicator-4"}
            assert aff["topologyKey"] == "kubernetes.io/hostname"
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_uncoordinated_cronjob_uses_control_plane_gate(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl",
                                   control_api_url="http://etl-api:8000",
                                   control_api_key_secret="etl-api-key")
            await orch.start_pipeline(ReplicatorSpec(
                5, "acme", {"destination": {"type": "lake",
                                            "warehouse_path": "/wh",
                                            "warehouse_size": "50Gi"}}))
            cron = [r for r in server.requests
                    if r.path.endswith("/cronjobs")][0].json
            args = cron["spec"]["jobTemplate"]["spec"]["template"]["spec"][
                "containers"][0]["args"]
            # pause gate aimed at the CONTROL-PLANE API with the
            # pipeline's tenant identity
            assert args[args.index("--api-url") + 1] == \
                "http://etl-api:8000"
            assert args[args.index("--tenant-id") + 1] == "acme"
            assert "--coordinate" not in args
            env = cron["spec"]["jobTemplate"]["spec"]["template"]["spec"][
                "containers"][0]["env"]
            # secured control plane: bearer token reaches the job as
            # ETL_API_KEY (maintenance.py:194) via a deployer Secret
            assert env == [{"name": "ETL_API_KEY", "valueFrom": {
                "secretKeyRef": {"name": "etl-api-key",
                                 "key": "api-key"}}}]
            pvc = [r for r in server.requests
                   if r.path.endswith("/persistentvolumeclaims")][0].json
            assert pvc["spec"]["resources"]["requests"]["storage"] == "50Gi"
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_pod_status_derivation(self):
        from etl_tpu.api.orchestrator import derive_pod_status

        assert derive_pod_status(None) == "stopped"
        assert derive_pod_status(
            {"metadata": {"deletionTimestamp": "t"}}) == "stopping"
        assert derive_pod_status(
            {"metadata": {}, "status": {"phase": "Pending"}}) == "starting"
        assert derive_pod_status({"metadata": {}, "status": {
            "phase": "Running",
            "containerStatuses": [{"ready": True, "state": {}}],
        }}) == "started"
        assert derive_pod_status({"metadata": {}, "status": {
            "phase": "Running",
            "containerStatuses": [{"ready": False, "state": {
                "waiting": {"reason": "CrashLoopBackOff"}}}],
        }}) == "failed"
        assert derive_pod_status({"metadata": {}, "status": {
            "phase": "Running",
            "containerStatuses": [{"ready": False, "state": {
                "terminated": {"exitCode": 1}}}],
        }}) == "failed"
        assert derive_pod_status(
            {"metadata": {}, "status": {"phase": "Succeeded"}}) == "stopped"
        assert derive_pod_status(
            {"metadata": {}, "status": {"phase": "Failed"}}) == "failed"

    async def test_status_reports_crashloop_as_failed(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            def responder(req):
                if "/pods" in req.path:
                    return 200, {"items": [{"metadata": {}, "status": {
                        "phase": "Running",
                        "containerStatuses": [{"ready": False, "state": {
                            "waiting": {"reason": "CrashLoopBackOff"}}}],
                    }}]}
                if req.path.endswith("/statefulsets/etl-replicator-9"):
                    return 200, {"status": {"readyReplicas": 0}}
                return None

            server.responders.append(responder)
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            st = await orch.status(9)
            assert st.state == "failed"
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_conflict_replaces(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            server.fail_next = [409]  # first resource (Secret) exists
            orch = K8sOrchestrator(api_url=server.url())
            await orch.start_pipeline(ReplicatorSpec(1, "t", {}))
            # an existing Secret is REPLACED via PUT (atomic, no
            # delete-to-create window) so rotated-away credential keys
            # cannot survive a merge and a concurrently starting pod
            # never sees the Secret missing
            paths = server.paths()
            puts = [p for p in paths
                    if p.startswith("PUT ") and "secrets" in p]
            assert len(puts) == 1
            assert not any(p.startswith("DELETE ") for p in paths)
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_conflict_patches_statefulset(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            server.responders.append(
                lambda req: (409, {}) if req.method == "POST"
                and req.path.endswith("/statefulsets") else None)
            orch = K8sOrchestrator(api_url=server.url())
            await orch.start_pipeline(ReplicatorSpec(1, "t", {}))
            # workloads roll via strategic-merge PATCH, not recreate
            assert any(p.startswith("PATCH ") and "statefulsets" in p
                       for p in server.paths())
            await orch.shutdown()
        finally:
            await server.stop()


class TestReviewRegressions:
    async def test_non_numeric_id_is_404(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            for path in ("/v1/sources/abc", "/v1/pipelines/abc",
                         "/v1/destinations/%20"):
                assert (await client.get(path, headers=H)).status == 404
            assert (await client.post("/v1/pipelines/xyz/start",
                                      headers=H)).status == 404
        finally:
            await client.close()

    async def test_malformed_body_is_400(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            resp = await client.post("/v1/sources", headers=H,
                                     data=b"not json")
            assert resp.status == 400
        finally:
            await client.close()

    async def test_delete_referenced_source_conflicts(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            pid = await setup_pipeline(client)
            resp = await client.delete("/v1/sources/1", headers=H)
            assert resp.status == 409
            assert "in use" in (await resp.json())["error"]
            # deleting the pipeline first frees the source
            await client.delete(f"/v1/pipelines/{pid}", headers=H)
            assert (await client.delete("/v1/sources/1",
                                        headers=H)).status == 204
        finally:
            await client.close()


class TestSlotLagSurface:
    async def test_replication_status_includes_slot_lag(self, tmp_path):
        """replication-status surfaces source-side slot lag when the
        source is reachable (reference lag.rs via routes/pipelines.rs) and
        degrades to null when it isn't."""
        from etl_tpu.runtime.state import TableState
        from etl_tpu.store.sql import SqliteStore
        from etl_tpu.testing.fake_pg_server import FakePgServer
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.models import ColumnSchema, Oid, TableName, TableSchema

        db = FakeDatabase()
        db.create_table(TableSchema(
            16384, TableName("public", "t"),
            (ColumnSchema("id", Oid.INT4, nullable=False,
                          primary_key_ordinal=1),)))
        db.create_publication("pub", [16384])
        from etl_tpu.postgres.fake import FakeSource
        await FakeSource(db).create_slot("supabase_etl_apply_7")
        server = FakePgServer(db)
        await server.start()

        store_path = str(tmp_path / "pipe.db")
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            src = await (await client.post(
                "/v1/sources", headers=H,
                json={"name": "s", "config": {
                    "host": "127.0.0.1", "port": server.port,
                    "name": "postgres", "username": "etl"}})).json()
            dst = await (await client.post(
                "/v1/destinations", headers=H,
                json={"name": "d", "config": {"type": "memory"}})).json()
            resp = await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": src["id"], "destination_id": dst["id"],
                      "publication_name": "pub", "store_path": store_path})
            pid = (await resp.json())["id"]
            store = SqliteStore(store_path, pid)
            await store.connect()
            await store.update_table_state(16384, TableState.ready())
            await store.close()

            doc = await (await client.get(
                f"/v1/pipelines/{pid}/replication-status",
                headers=H)).json()
            assert doc["slot_lag"], doc
            slot = doc["slot_lag"][0]
            assert slot["slot_name"].startswith("supabase_etl_")
            assert "confirmed_flush_lag_bytes" in slot
        finally:
            await client.close()
            await server.stop()

    async def test_slot_lag_null_when_source_unreachable(self, tmp_path):
        from etl_tpu.runtime.state import TableState
        from etl_tpu.store.sql import SqliteStore

        store_path = str(tmp_path / "pipe.db")
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            src = await (await client.post(
                "/v1/sources", headers=H,
                json={"name": "s", "config": {
                    "host": "127.0.0.1", "port": 1,
                    "name": "postgres", "username": "etl"}})).json()
            dst = await (await client.post(
                "/v1/destinations", headers=H,
                json={"name": "d", "config": {"type": "memory"}})).json()
            resp = await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": src["id"], "destination_id": dst["id"],
                      "publication_name": "pub", "store_path": store_path})
            pid = (await resp.json())["id"]
            store = SqliteStore(store_path, pid)
            await store.connect()
            await store.update_table_state(1, TableState.ready())
            await store.close()
            doc = await (await client.get(
                f"/v1/pipelines/{pid}/replication-status",
                headers=H)).json()
            assert doc["slot_lag"] is None
        finally:
            await client.close()


class TestAuth:
    async def test_bearer_key_required_when_configured(self, tmp_path):
        client, state = await make_client(tmp_path, api_key="k-12345")
        try:
            # no key → 401 before tenant routing
            r = await client.get("/v1/tenants", headers=H)
            assert r.status == 401
            r = await client.get("/v1/tenants", headers={
                **H, "Authorization": "Bearer wrong"})
            assert r.status == 401
            r = await client.get("/v1/tenants", headers={
                **H, "Authorization": "Bearer k-12345"})
            assert r.status == 200
            # health/metrics/openapi stay open for probes
            assert (await client.get("/health")).status == 200
            assert (await client.get("/openapi.json")).status == 200
        finally:
            await client.close()


class TestImages:
    async def test_images_crud_and_default_used_at_start(self, tmp_path):
        orch = StubOrchestrator()
        client, _ = await make_client(tmp_path, orch)
        try:
            pid = await setup_pipeline(client)
            img = await (await client.post(
                "/v1/images", headers=H,
                json={"name": "replicator:v2", "default": True})).json()
            await client.post("/v1/images", headers=H,
                              json={"name": "replicator:v3"})
            imgs = await (await client.get("/v1/images",
                                           headers=H)).json()
            assert {i["name"]: i["default"] for i in imgs} == {
                "replicator:v2": True, "replicator:v3": False}
            # duplicate name → 409
            assert (await client.post(
                "/v1/images", headers=H,
                json={"name": "replicator:v2"})).status == 409

            await client.post(f"/v1/pipelines/{pid}/start", headers=H)
            # StubOrchestrator doesn't capture image; assert via spec calls
            assert orch.specs[-1].image == "replicator:v2"

            v3 = next(i for i in imgs if i["name"] == "replicator:v3")
            await client.post(f"/v1/images/{v3['id']}/set-default",
                              headers=H)
            await client.post(f"/v1/pipelines/{pid}/restart", headers=H)
            assert orch.specs[-1].image == "replicator:v3"
            assert (await client.delete(f"/v1/images/{v3['id']}",
                                        headers=H)).status == 204
        finally:
            await client.close()

    async def test_pipeline_version_pins_and_rolls_out(self, tmp_path):
        """POST /pipelines/{id}/version (reference
        routes/pipelines.rs:662-735): pins the image the pipeline runs
        independent of the tenant default, re-applies a RUNNING
        pipeline so the rollout happens now, and reverts to
        default-tracking when image_id is omitted."""
        orch = StubOrchestrator()
        client, _ = await make_client(tmp_path, orch)
        try:
            pid = await setup_pipeline(client)
            await client.post("/v1/images", headers=H,
                              json={"name": "replicator:v2",
                                    "default": True})
            v9 = await (await client.post(
                "/v1/images", headers=H,
                json={"name": "replicator:v9"})).json()

            # stopped pipeline: version pin persists, no rollout yet
            r = await (await client.post(
                f"/v1/pipelines/{pid}/version", headers=H,
                json={"image_id": v9["id"]})).json()
            assert r == {"id": pid, "image": "replicator:v9",
                         "pinned": True, "rolled_out": False}
            got = await (await client.get(
                f"/v1/pipelines/{pid}", headers=H)).json()
            assert got["image"] == "replicator:v9"
            # start uses the PIN, not the default
            await client.post(f"/v1/pipelines/{pid}/start", headers=H)
            assert orch.specs[-1].image == "replicator:v9"

            # running pipeline: a version change rolls out immediately
            r = await (await client.post(
                f"/v1/pipelines/{pid}/version", headers=H,
                json={})).json()
            assert r["pinned"] is False and r["rolled_out"] is True
            assert r["image"] == "replicator:v2"  # back on the default
            assert orch.specs[-1].image == "replicator:v2"

            # unknown image → 404; non-int → 400
            assert (await client.post(
                f"/v1/pipelines/{pid}/version", headers=H,
                json={"image_id": 999})).status == 404
            assert (await client.post(
                f"/v1/pipelines/{pid}/version", headers=H,
                json={"image_id": "vX"})).status == 400

            # deleting an image a pipeline PINS → 409 (a silent delete
            # would leave the pipeline deploying an unregistered name);
            # unpin → delete succeeds
            await client.post(f"/v1/pipelines/{pid}/version", headers=H,
                              json={"image_id": v9["id"]})
            assert (await client.delete(
                f"/v1/images/{v9['id']}", headers=H)).status == 409
            await client.post(f"/v1/pipelines/{pid}/version", headers=H,
                              json={})
            assert (await client.delete(
                f"/v1/images/{v9['id']}", headers=H)).status == 204
        finally:
            await client.close()


class TestRollbackDepth:
    async def test_rollback_reports_prior_state_and_clears_progress(
            self, tmp_path):
        from etl_tpu.models import Lsn, RetryKind
        from etl_tpu.postgres.slots import table_sync_slot_name
        from etl_tpu.runtime.state import TableState, TableStateType
        from etl_tpu.store.sql import SqliteStore

        store_path = str(tmp_path / "p.db")
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            src = await (await client.post(
                "/v1/sources", headers=H,
                json={"name": "s", "config": {
                    "host": "db", "port": 5432, "name": "app",
                    "username": "etl"}})).json()
            dst = await (await client.post(
                "/v1/destinations", headers=H,
                json={"name": "d", "config": {"type": "memory"}})).json()
            pid = (await (await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": src["id"], "destination_id": dst["id"],
                      "publication_name": "pub",
                      "store_path": store_path})).json())["id"]
            store = SqliteStore(store_path, pid)
            await store.connect()
            await store.update_table_state(7, TableState.errored(
                "kaput", retry_policy=RetryKind.MANUAL, retry_attempts=3))
            await store.update_durable_progress(
                table_sync_slot_name(pid, 7), Lsn(900))
            await store.close()

            doc = await (await client.post(
                f"/v1/pipelines/{pid}/rollback-tables", headers=H,
                json={"table_ids": [7, 999]})).json()
            assert doc["rolled_back"] == [7]
            assert doc["unknown_table_ids"] == [999]
            assert doc["tables"][0]["previous_state"] == "errored"
            assert doc["tables"][0]["previous_reason"] == "kaput"

            store = SqliteStore(store_path, pid)
            await store.connect()
            assert (await store.get_table_state(7)).type \
                is TableStateType.INIT
            assert await store.get_durable_progress(
                table_sync_slot_name(pid, 7)) is None
            await store.close()
        finally:
            await client.close()


class TestOpenApi:
    async def test_document_covers_every_route(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            doc = await (await client.get("/openapi.json")).json()
            assert doc["openapi"].startswith("3.")
            app_routes = {
                "/v1/tenants", "/v1/sources", "/v1/sources/{id}",
                "/v1/destinations", "/v1/destinations/{id}", "/v1/images",
                "/v1/images/{id}", "/v1/images/{id}/set-default",
                "/v1/pipelines", "/v1/pipelines/{id}",
                "/v1/pipelines/{id}/start", "/v1/pipelines/{id}/stop",
                "/v1/pipelines/{id}/restart", "/v1/pipelines/{id}/status",
                "/v1/pipelines/{id}/replication-status",
                "/v1/pipelines/{id}/rollback-tables"}
            assert app_routes <= set(doc["paths"])
            # every operation carries a human summary + response schema
            for path, ops in doc["paths"].items():
                for method, op in ops.items():
                    assert op.get("summary"), (path, method)
                    assert "responses" in op, (path, method)
            assert "bearer" in doc["components"]["securitySchemes"]
        finally:
            await client.close()


class TestSecretRoundTrip:
    async def test_put_back_masked_config_keeps_real_secret(self, tmp_path):
        """GET → edit → PUT must not overwrite the stored credential with
        the mask sentinel."""
        orch = StubOrchestrator()
        client, _ = await make_client(tmp_path, orch)
        try:
            pid = await setup_pipeline(client)
            got = await (await client.get("/v1/sources/1",
                                          headers=H)).json()
            assert got["config"]["password"] == "********"
            got["config"]["host"] = "db2"  # unrelated edit
            r = await client.put("/v1/sources/1", headers=H,
                                 json={"config": got["config"]})
            assert r.status == 200
            await client.post(f"/v1/pipelines/{pid}/start", headers=H)
            cfg = orch.calls[-1][2]
            assert cfg["pg_connection"]["password"] == "s3cret-password-42"
            assert cfg["pg_connection"]["host"] == "db2"
        finally:
            await client.close()

    async def test_nested_secret_values_masked(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            await client.post(
                "/v1/sources", headers=H,
                json={"name": "s", "config": {
                    "token": {"value": "eyJhbGci"},
                    "keys": ["k1", "k2"], "host": "h", "port": 5432,
                    "name": "app", "username": "etl"}})
            got = await (await client.get("/v1/sources/1",
                                          headers=H)).json()
            assert got["config"]["token"] == "********"
            assert got["config"]["keys"] == "********"
            assert got["config"]["host"] == "h"
        finally:
            await client.close()


class TestImageTenancy:
    async def test_images_are_tenant_scoped(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
            await client.post("/v1/tenants", json={"id": "rival", "name": "R"})
            await client.post("/v1/images", headers=H,
                              json={"name": "mine:v1", "default": True})
            other = {"tenant_id": "rival"}
            assert await (await client.get("/v1/images",
                                           headers=other)).json() == []
            # rival can't hijack acme's default or delete acme's image
            assert (await client.post("/v1/images/1/set-default",
                                      headers=other)).status == 404
            await client.delete("/v1/images/1", headers=other)
            imgs = await (await client.get("/v1/images", headers=H)).json()
            assert imgs and imgs[0]["name"] == "mine:v1"
        finally:
            await client.close()


class TestValidationRoutes:
    """Reject-before-store + the :validate live-probe routes (reference
    routes/destinations.rs:468-516, validation/ framework)."""

    async def test_create_rejects_invalid_source_config(self, tmp_path):
        client, _ = await make_client(tmp_path)
        await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
        resp = await client.post("/v1/sources", headers=H, json={
            "name": "bad", "config": {"port": 99999}})
        assert resp.status == 400
        doc = await resp.json()
        names = {f["name"] for f in doc["validation_failures"]}
        # invalid-config snapshot: every static failure reported at once
        assert {"Missing host", "Missing name", "Missing username",
                "Invalid port"} <= names
        assert all(f["failure_type"] == "critical"
                   for f in doc["validation_failures"])
        await client.close()

    async def test_create_rejects_unknown_destination_type(self, tmp_path):
        client, _ = await make_client(tmp_path)
        await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
        resp = await client.post("/v1/destinations", headers=H, json={
            "name": "bad", "config": {"type": "warehouse9000"}})
        assert resp.status == 400
        doc = await resp.json()
        assert doc["validation_failures"][0]["name"] == \
            "Unknown destination type"
        # nothing was stored
        listing = await (await client.get("/v1/destinations",
                                          headers=H)).json()
        assert listing == []
        await client.close()

    async def test_update_rejects_invalid_config(self, tmp_path):
        client, _ = await make_client(tmp_path)
        await setup_pipeline(client)
        resp = await client.put("/v1/destinations/1", headers=H, json={
            "config": {"type": "bigquery"}})  # missing project/dataset
        assert resp.status == 400
        stored = await (await client.get("/v1/destinations/1",
                                         headers=H)).json()
        assert stored["config"]["type"] == "lake"  # unchanged
        await client.close()

    async def test_validate_source_live_probes(self, tmp_path):
        from etl_tpu.testing.fake_pg_server import FakePgServer
        from tests.test_pipeline_e2e import make_db

        server = FakePgServer(make_db())
        await server.start()
        client, _ = await make_client(tmp_path)
        await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
        try:
            good = {"host": "127.0.0.1", "port": server.port,
                    "name": "postgres", "username": "etl"}
            doc = await (await client.post(
                "/v1/sources:validate", headers=H,
                json={"config": good})).json()
            assert doc["validation_failures"] == []
            # existing publication passes; missing one is critical
            doc = await (await client.post(
                "/v1/sources:validate", headers=H,
                json={"config": good,
                      "pipeline_config": {"publication_name": "pub"}})).json()
            assert doc["validation_failures"] == []
            doc = await (await client.post(
                "/v1/sources:validate", headers=H,
                json={"config": good,
                      "pipeline_config": {"publication_name": "nope"}})).json()
            assert [f["name"] for f in doc["validation_failures"]] == \
                ["Publication missing"]
            # unreachable endpoint is a critical failure, not a 500
            bad = dict(good, port=1)
            doc = await (await client.post(
                "/v1/sources:validate", headers=H,
                json={"config": bad})).json()
            assert doc["validation_failures"][0]["name"] == \
                "Source connection failed"
        finally:
            await client.close()
            await server.stop()

    async def test_validate_destination_live_probes(self, tmp_path):
        from etl_tpu.testing.fake_http import RecordingHttpServer

        server = RecordingHttpServer()
        await server.start()
        client, _ = await make_client(tmp_path)
        await client.post("/v1/tenants", json={"id": "acme", "name": "A"})
        try:
            doc = await (await client.post(
                "/v1/destinations:validate", headers=H,
                json={"config": {"type": "clickhouse",
                                 "url": server.url(),
                                 "database": "etl"}})).json()
            assert doc["validation_failures"] == []
            # auth rejection surfaces as a critical failure
            server.responders.append(
                lambda rec: (401, {"error": "bad token"})
                if "/datasets/" in rec.path else None)
            doc = await (await client.post(
                "/v1/destinations:validate", headers=H,
                json={"config": {"type": "bigquery", "project_id": "p",
                                 "dataset_id": "d",
                                 "base_url": server.url(),
                                 "auth_token": "bad"}})).json()
            assert doc["validation_failures"][0]["name"] == \
                "BigQuery authentication failed"
            # source_id and pipeline_config must travel together
            resp = await client.post(
                "/v1/destinations:validate", headers=H,
                json={"config": {"type": "lake", "warehouse_path": "/tmp"},
                      "source_id": 1})
            assert resp.status == 400
        finally:
            await client.close()
            await server.stop()


def k8s_existence_responder():
    """Emulates resource existence: POST of an already-created name →
    409; DELETE forgets it (so the orchestrator's replace path works the
    way the real API does)."""
    existing: set[str] = set()

    def responder(rec):
        if rec.method == "POST":
            name = (rec.json or {}).get("metadata", {}).get("name", "")
            key = f"{rec.path}/{name}"
            if key in existing:
                return 409, {}
            existing.add(key)
            return None
        if rec.method == "DELETE":
            existing.discard(rec.path)
        return None

    return responder


class TestOrchestratorRollout:
    async def test_statefulset_update_rolls_template(self):
        """An image change on an EXISTING pipeline must PATCH the
        StatefulSet with a fresh restarted-at template annotation — the
        rolling-restart trigger (reference k8s/http.rs:1676,1708)."""
        server = RecordingHttpServer()
        await server.start()
        try:
            server.responders.append(k8s_existence_responder())
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            spec = ReplicatorSpec(3, "t", {"publication_name": "pub"},
                                  image="img:v1")
            await orch.start_pipeline(spec)
            first = [r for r in server.requests
                     if r.path.endswith("/statefulsets")][0].json
            anno1 = first["spec"]["template"]["metadata"]["annotations"][
                "etl/restarted-at"]
            # every resource now exists → conflict on each create
            await orch.start_pipeline(ReplicatorSpec(
                3, "t", {"publication_name": "pub"}, image="img:v2"))
            patches = [r for r in server.requests if r.method == "PATCH"]
            sts = [r for r in patches
                   if "statefulsets/etl-replicator-3" in r.path][0].json
            tpl = sts["spec"]["template"]
            assert tpl["spec"]["containers"][0]["image"] == "img:v2"
            assert tpl["metadata"]["annotations"]["etl/restarted-at"] \
                != anno1
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_restart_rolls_without_teardown(self):
        """K8sOrchestrator.restart_pipeline must NOT delete+recreate (the
        base-class default): it re-applies with a fresh restarted-at
        annotation so the controller rolls the pods in place."""
        server = RecordingHttpServer()
        await server.start()
        try:
            server.responders.append(k8s_existence_responder())
            orch = K8sOrchestrator(api_url=server.url(), namespace="etl")
            spec = ReplicatorSpec(9, "t", {"publication_name": "pub"})
            await orch.start_pipeline(spec)
            first = [r for r in server.requests
                     if r.path.endswith("/statefulsets")][0].json
            anno1 = first["spec"]["template"]["metadata"]["annotations"][
                "etl/restarted-at"]
            await orch.restart_pipeline(spec)
            # the WORKLOAD is never torn down (secrets/configmaps are
            # replaced, which is invisible to running pods until restart)
            assert not any(r.method == "DELETE" and "statefulsets" in r.path
                           for r in server.requests)
            patches = [r for r in server.requests if r.method == "PATCH"]
            sts = [r for r in patches
                   if "statefulsets/etl-replicator-9" in r.path][0]
            assert sts.headers["Content-Type"] == \
                "application/strategic-merge-patch+json"
            anno2 = sts.json["spec"]["template"]["metadata"][
                "annotations"]["etl/restarted-at"]
            assert anno2 != anno1  # pods roll even with unchanged config
            await orch.shutdown()
        finally:
            await server.stop()

    async def test_local_orchestrator_restarts_on_config_change(
            self, tmp_path, monkeypatch):
        """Same spec → keep the process; changed config/image → restart
        with the new config on disk (single-host template roll)."""
        import asyncio as aio
        import sys

        from etl_tpu.api.orchestrator import LocalOrchestrator

        spawned = []
        real_exec = aio.create_subprocess_exec

        async def fake_exec(*args, **kwargs):
            # record then run an inert long-lived process
            spawned.append(args)
            return await real_exec(sys.executable, "-c",
                                   "import time; time.sleep(60)",
                                   **{k: v for k, v in kwargs.items()
                                      if k in ("stdout", "stderr")})

        monkeypatch.setattr(aio, "create_subprocess_exec", fake_exec)
        orch = LocalOrchestrator(str(tmp_path))
        spec_a = ReplicatorSpec(5, "t", {"publication_name": "a"})
        await orch.start_pipeline(spec_a)
        pid1 = orch._procs[5].pid
        await orch.start_pipeline(spec_a)  # unchanged → same process
        assert orch._procs[5].pid == pid1 and len(spawned) == 1
        spec_b = ReplicatorSpec(5, "t", {"publication_name": "b"})
        await orch.start_pipeline(spec_b)  # changed → restart
        assert orch._procs[5].pid != pid1 and len(spawned) == 2
        import yaml
        conf = yaml.safe_load(
            (tmp_path / "pipeline-5" / "base.yaml").read_text())
        assert conf["publication_name"] == "b"
        assert (await orch.status(5)).state == "running"
        await orch.shutdown()
        assert (await orch.status(5)).state == "stopped"


class TestDocsPage:
    async def test_docs_served_self_contained(self, tmp_path):
        client, _ = await make_client(tmp_path)
        try:
            resp = await client.get("/docs")
            assert resp.status == 200
            assert "text/html" in resp.headers["Content-Type"]
            body = await resp.text()
            # renders the spec client-side with ZERO external assets
            assert "/openapi.json" in body
            assert "http://" not in body and "https://" not in body
        finally:
            await client.close()
