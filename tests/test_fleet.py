"""etl-fleet unit + integration coverage: spec document semantics,
per-pipeline actuation journals, quota placement, the pure diff, the
level-triggered reconciler (tick / converge / hold / resume across both
crash windows), the simulated runtime's idempotence + delivery
invariants, and the three policy plugins on the shared signal bus.

The 100-pipeline end-to-end proofs live in `python -m etl_tpu.chaos
--fleet` (kill-mid-roll convergence) and `bench.py --fleet` (converge
tick gate); this file pins the pieces those compose."""

import pytest

from etl_tpu.autoscale.signals import ShardSignals, SignalFrame
from etl_tpu.fleet import (MAX_SHARDS_PER_PIPELINE, STATUS_ABORTED,
                           STATUS_APPLIED, STATUS_PENDING, VERB_CREATE,
                           VERB_DELETE, VERB_RESIZE, ActuationJournal,
                           AdaptiveAckDepthPolicy, AdmissionWeightPolicy,
                           FleetReconciler, FleetSignalBus, FleetSpec,
                           PidLagPolicy, PipelineSpec, SimulatedFleetRuntime,
                           TenantQuota, diff_fleet, place_fleet,
                           seeded_fleet_spec)
from etl_tpu.models.errors import ErrorKind, EtlError
from etl_tpu.store.memory import MemoryStore


def pipe(pid, tenant="acme", k=1, **kw) -> PipelineSpec:
    return PipelineSpec(pipeline_id=pid, tenant_id=tenant,
                        shard_count=k, **kw)


def frame(tick, lag_bytes, k=1) -> SignalFrame:
    return SignalFrame(tick=tick, at_s=float(tick),
                       shards=tuple(ShardSignals(shard=i,
                                                 lag_bytes=lag_bytes // k)
                                    for i in range(k)))


class TestFleetSpec:
    def test_validate_rejects_duplicates_and_bad_counts(self):
        with pytest.raises(EtlError) as e:
            FleetSpec(pipelines=(pipe(1), pipe(1))).validate()
        assert e.value.kind is ErrorKind.CONFIG_INVALID
        with pytest.raises(EtlError):
            pipe(1, k=0).validate()
        with pytest.raises(EtlError):
            pipe(1, k=MAX_SHARDS_PER_PIPELINE + 1).validate()
        with pytest.raises(EtlError):
            FleetSpec(quotas={"t": TenantQuota(max_shards=-1)}).validate()
        with pytest.raises(EtlError):
            FleetSpec(quotas={"t": TenantQuota(slo_weight=0)}).validate()

    def test_with_edit_bumps_version_and_rewrites(self):
        spec = FleetSpec(spec_version=4, pipelines=(pipe(1), pipe(2, k=3)))
        edited = spec.with_edit(remove=[1], add=[pipe(5, k=2)],
                                resize={2: 1})
        assert edited.spec_version == 5
        assert [p.pipeline_id for p in edited.pipelines] == [2, 5]
        assert edited.by_id()[2].shard_count == 1
        # the original document is untouched (frozen value semantics)
        assert spec.by_id()[2].shard_count == 3

    def test_json_round_trip(self):
        spec = FleetSpec(
            spec_version=9,
            pipelines=(pipe(3, tenant="g", k=2, destination="clickhouse",
                            profile="tiny_txs", config={"x": 1}),),
            quotas={"g": TenantQuota(max_shards=5, slo_weight=0.5)})
        assert FleetSpec.from_json(spec.to_json()) == spec
        assert FleetSpec.from_json(None) == FleetSpec()


class TestActuationJournal:
    def test_open_settle_pending_applied(self):
        j = ActuationJournal()
        rec = j.open(verb=VERB_CREATE, from_k=0, to_k=2, spec_version=1)
        assert rec.decision_id == 1 and j.next_id == 2
        assert j.pending() == rec
        j.settle(rec.decision_id, STATUS_APPLIED)
        assert j.pending() is None
        assert [r.decision_id for r in j.applied()] == [1]

    def test_bounded_history_keeps_id_counter(self):
        j = ActuationJournal(max_entries=4)
        for i in range(10):
            rec = j.open(verb=VERB_RESIZE, from_k=1, to_k=2,
                         spec_version=1)
            j.settle(rec.decision_id, STATUS_APPLIED)
        assert len(j.entries) == 4
        assert j.next_id == 11
        back = ActuationJournal.from_json(j.to_json())
        assert back.next_id == 11 and len(back.entries) == 4

    def test_satisfied_by_is_the_observed_target_test(self):
        j = ActuationJournal()
        rec = j.open(verb=VERB_DELETE, from_k=3, to_k=0, spec_version=2)
        assert rec.satisfied_by(0) and not rec.satisfied_by(3)
        assert rec.status == STATUS_PENDING


class TestPlacement:
    def test_unlimited_tenants_get_their_ask(self):
        spec = FleetSpec(pipelines=(pipe(1, k=3), pipe(2, k=2)))
        assert place_fleet(spec) == {1: 3, 2: 2}

    def test_quota_clamps_in_id_order_floor_one_shard(self):
        spec = FleetSpec(
            pipelines=(pipe(1, k=4), pipe(2, k=4), pipe(3, k=4)),
            quotas={"acme": TenantQuota(max_shards=6)})
        # every pipeline keeps 1, surplus (3) dealt id-first
        assert place_fleet(spec) == {1: 4, 2: 1, 3: 1}

    def test_zero_max_shards_means_unlimited(self):
        spec = FleetSpec(pipelines=(pipe(1, k=4),),
                         quotas={"acme": TenantQuota(max_shards=0)})
        assert place_fleet(spec) == {1: 4}

    def test_seeded_spec_quotas_visibly_bite(self):
        spec = seeded_fleet_spec(7, 100)
        targets = place_fleet(spec)
        asked = {p.pipeline_id: p.shard_count for p in spec.pipelines}
        clamped = [pid for pid in targets if targets[pid] < asked[pid]]
        assert clamped, "seeded quotas must clamp someone"
        assert all(k >= 1 for k in targets.values())


class TestDiff:
    def test_verb_order_deletes_creates_resizes(self):
        targets = {2: 3, 4: 1, 5: 2}
        observed = {1: 2, 2: 1, 5: 2}
        actions = diff_fleet(targets, observed)
        assert [(a.verb, a.pipeline_id, a.from_k, a.to_k)
                for a in actions] == [
            (VERB_DELETE, 1, 2, 0),
            (VERB_CREATE, 4, 0, 1),
            (VERB_RESIZE, 2, 1, 3),
        ]

    def test_steady_state_diffs_to_nothing(self):
        assert diff_fleet({1: 2}, {1: 2}) == ()
        assert diff_fleet({}, {}) == ()


class TestSimulatedRuntime:
    async def test_verbs_are_idempotent(self):
        rt = SimulatedFleetRuntime(seed=3)
        await rt.create_pipeline(pipe(1, k=2, profile="tiny_txs"))
        ledger = list(rt.pipelines[1].committed)
        await rt.create_pipeline(pipe(1, k=2, profile="tiny_txs"))
        assert rt.pipelines[1].committed == ledger  # no re-seed
        await rt.resize_pipeline(pipe(1, k=2, profile="tiny_txs"))
        assert rt.pipelines[1].rolls == 0  # same-K resize no-ops
        await rt.delete_pipeline(9)  # absent: state no-op
        assert await rt.list_pipelines() == {1: 2}
        assert rt.violations() == []

    async def test_roll_redelivers_bounded_tail(self):
        rt = SimulatedFleetRuntime(seed=3)
        await rt.create_pipeline(pipe(1, k=1, profile="insert_heavy"))
        await rt.resize_pipeline(pipe(1, k=3, profile="insert_heavy"))
        p = rt.pipelines[1]
        assert p.rolls == 1
        assert max(p.delivered.values()) == 2  # tail dup, within budget
        assert rt.violations() == []
        # a phantom delivery IS a violation the model catches
        p.delivered["phantom:1:0"] = 1
        assert rt.violations()


async def converged_reconciler(seed=7, n=20):
    store = MemoryStore()
    runtime = SimulatedFleetRuntime(seed=seed)
    spec = seeded_fleet_spec(seed, n)
    await store.update_fleet_spec(spec.to_json())
    rec = FleetReconciler(store=store, runtime=runtime,
                          scheduler=_StubScheduler())
    ticks = await rec.converge()
    return store, runtime, spec, rec, ticks


class _StubScheduler:
    def __init__(self):
        self.weights = {}

    def set_slo_weight(self, tenant, weight):
        self.weights[tenant] = weight


class TestReconciler:
    async def test_converges_from_empty_in_one_working_tick(self):
        store, runtime, spec, rec, ticks = await converged_reconciler()
        assert ticks == 1
        assert await runtime.list_pipelines() == place_fleet(spec)
        # every actuation is backed 1:1 by an APPLIED journal record
        journals = [ActuationJournal.from_json(d) for d in
                    (await store.get_fleet_journals()).values()]
        assert sum(len(j.applied()) for j in journals) \
            == len(runtime.actuation_log)
        assert all(j.pending() is None for j in journals)
        assert runtime.violations() == []

    async def test_edit_absorbed_and_slo_weights_fed(self):
        store, runtime, spec, rec, _ = await converged_reconciler()
        edited = spec.with_edit(remove=[1], resize={5: 6},
                                add=[pipe(900, tenant="tenant-burst",
                                          k=2, profile="tiny_txs")])
        await store.update_fleet_spec(edited.to_json())
        assert await rec.converge() == 1
        observed = await runtime.list_pipelines()
        assert observed == place_fleet(edited)
        assert 1 not in observed and observed[900] == 2
        assert 1 in runtime.retired
        # quota SLO weights reached the scheduler via the spec document
        sched = rec._scheduler
        for tenant, quota in edited.quotas.items():
            assert sched.weights[tenant] == quota.slo_weight
        assert runtime.violations() == []

    async def test_pending_journal_holds_the_pipeline(self):
        store, runtime, spec, rec, _ = await converged_reconciler()
        # a crashed coordinator's pending record holds pipeline 5
        # mid-roll (5's tenant is unclamped, so the resize survives
        # placement and actually diffs)
        j = ActuationJournal.from_json(await store.get_fleet_journal(5))
        j.open(verb=VERB_RESIZE, from_k=1, to_k=9,
               spec_version=spec.spec_version)
        await store.update_fleet_journal(5, j.to_json())
        await store.update_fleet_spec(
            spec.with_edit(resize={5: 9}).to_json())
        before = len(runtime.actuation_log)
        result = await rec.tick()
        assert result.held == [5] and result.applied == []
        assert not result.converged
        assert len(runtime.actuation_log) == before  # held = no verbs

    async def test_resume_settles_crash_after_actuation(self):
        """Fleet already shows the target: journal-only settle, ZERO
        runtime calls — the no-double-actuation half."""
        store, runtime, spec, rec, _ = await converged_reconciler()
        target = spec.pipelines[0].pipeline_id
        observed_k = (await runtime.list_pipelines())[target]
        j = ActuationJournal.from_json(await store.get_fleet_journal(target))
        pend = j.open(verb=VERB_RESIZE, from_k=1, to_k=observed_k,
                      spec_version=spec.spec_version)
        await store.update_fleet_journal(target, j.to_json())
        before = len(runtime.actuation_log)
        settled = await rec.resume()
        assert [(r.decision_id, r.status) for r in settled] \
            == [(pend.decision_id, STATUS_APPLIED)]
        assert len(runtime.actuation_log) == before
        assert await rec.resume() == []  # idempotent

    async def test_resume_redrives_crash_before_actuation(self):
        store, runtime, spec, rec, _ = await converged_reconciler()
        target = spec.pipelines[0].pipeline_id
        want = (await runtime.list_pipelines())[target] + 3
        j = ActuationJournal.from_json(await store.get_fleet_journal(target))
        j.open(verb=VERB_RESIZE, from_k=1, to_k=want,
               spec_version=spec.spec_version)
        await store.update_fleet_journal(target, j.to_json())
        await store.update_fleet_spec(
            spec.with_edit(resize={target: want}).to_json())
        before = len(runtime.actuation_log)
        settled = await rec.resume()
        assert [r.status for r in settled] == [STATUS_APPLIED]
        assert len(runtime.actuation_log) == before + 1  # exactly one
        assert (await runtime.list_pipelines())[target] == want
        assert await rec.resume() == []

    async def test_resume_aborts_when_spec_moved_on(self):
        store, runtime, spec, rec, _ = await converged_reconciler()
        target = spec.pipelines[0].pipeline_id
        j = ActuationJournal.from_json(await store.get_fleet_journal(target))
        j.open(verb=VERB_RESIZE, from_k=1, to_k=40,
               spec_version=spec.spec_version)
        await store.update_fleet_journal(target, j.to_json())
        await store.update_fleet_spec(
            spec.with_edit(remove=[target]).to_json())
        before = len(runtime.actuation_log)
        settled = await rec.resume()
        assert [r.status for r in settled] == [STATUS_ABORTED]
        assert len(runtime.actuation_log) == before
        # the next converge deletes the stray against the new truth
        await rec.converge()
        assert target not in await runtime.list_pipelines()
        assert runtime.violations() == []


class TestSignalBus:
    def test_pid_recommends_scale_up_for_lagging_pipeline_only(self):
        bus = FleetSignalBus()
        pid_policy = PidLagPolicy()
        bus.register(pid_policy)
        for t in range(3):
            bus.publish(1, frame(t, 256 * 1024 * 1024, k=2))  # lagging
            bus.publish(2, frame(t, 1024, k=2))  # healthy
            bus.step()
        assert pid_policy.recommendations[1] > 2
        assert 2 not in pid_policy.recommendations

    def test_pid_integral_is_wind_up_clamped(self):
        bus = FleetSignalBus()
        pid_policy = PidLagPolicy()
        bus.register(pid_policy)
        cap = pid_policy.config.max_shards
        for t in range(50):  # a LONG sustained surge
            bus.publish(1, frame(t, 1 << 40, k=2))
            bus.step()
        assert pid_policy.recommendations[1] <= cap

    def test_ack_depth_tracks_measured_latency(self):
        class _Window:
            limit = None

            def set_limit(self, v):
                self.limit = v

        window = _Window()
        reads = [(24, 24 * 0.4)]  # mean 0.4s over 0.05s flushes -> 9
        bus = FleetSignalBus()
        policy = AdaptiveAckDepthPolicy(
            window_of=lambda pid: window,
            histogram_read=lambda: reads[-1])
        bus.register(policy)
        bus.publish(1, frame(0, 0))
        assert len(bus.step()) == 1
        # the epsilon fencepost: 0.4/0.05 is 8.000…02 in binary — depth
        # must be ceil(8)+1 = 9, not 10
        assert window.limit == 9
        # unchanged histogram: held (state IS the applied depth)
        bus.publish(1, frame(1, 0))
        assert bus.step() == []
        # latency falls -> depth follows
        reads.append((100, 100 * 0.05))
        bus.publish(1, frame(2, 0))
        bus.step()
        assert window.limit == 2

    def test_ack_depth_cold_histogram_is_held(self):
        bus = FleetSignalBus()
        policy = AdaptiveAckDepthPolicy(
            window_of=lambda pid: None,
            histogram_read=lambda: (3, 0.9))  # < min_samples
        bus.register(policy)
        bus.publish(1, frame(0, 0))
        assert bus.step() == []

    def test_admission_weight_base_and_lag_boost(self):
        sched = _StubScheduler()
        bus = FleetSignalBus()
        spec = FleetSpec(
            spec_version=1,
            pipelines=(pipe(1, tenant="hot", k=1),
                       pipe(2, tenant="cold", k=1)),
            quotas={"hot": TenantQuota(slo_weight=1.5),
                    "cold": TenantQuota(slo_weight=0.5)})
        bus.bind_spec(spec)
        policy = AdmissionWeightPolicy(bus, scheduler=sched)
        bus.register(policy)
        bus.publish(1, frame(0, 256 * 1024 * 1024))  # over the boost bar
        bus.publish(2, frame(0, 1024))
        bus.step()
        assert sched.weights["hot"] == pytest.approx(3.0)  # 1.5 * 2
        assert sched.weights["cold"] == pytest.approx(0.5)
        # unchanged signals: weights are held, not re-applied
        bus.publish(1, frame(1, 256 * 1024 * 1024))
        bus.publish(2, frame(1, 1024))
        assert bus.step() == []

    def test_drop_forgets_history_and_state(self):
        bus = FleetSignalBus()
        pid_policy = PidLagPolicy()
        bus.register(pid_policy)
        bus.publish(1, frame(0, 1 << 30))
        bus.step()
        bus.drop(1)
        assert bus.step() == []
        assert ("pid_lag", 1) not in bus._state
