"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths compile and execute without TPU hardware."""

import asyncio
import functools
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin overrides JAX_PLATFORMS at import time; the config
# knob wins over it (verified: env alone still selects the TPU backend).
import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_collection_modifyitems(config, items):
    """Run `async def` tests on a fresh event loop (no pytest-asyncio in the
    image)."""
    for item in items:
        if inspect.iscoroutinefunction(getattr(item, "function", None)):
            item.obj = _sync_wrapper(item.function)


def _sync_wrapper(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=120))

    return wrapper
