"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths compile and execute without TPU hardware."""

import asyncio
import functools
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin overrides JAX_PLATFORMS at import time; the config
# knob wins over it (verified: env alone still selects the TPU backend).
import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True)
def _disarm_failpoints():
    """Failpoint hygiene (chaos satellite): no test can leak an armed
    site (or a mid-stall block, or a supervision-forced host-oracle
    degrade) into the next test — cleared after every test, pass or
    fail."""
    yield
    from etl_tpu.chaos import failpoints
    from etl_tpu.ops import engine

    failpoints.disarm_all()
    engine.clear_forced_oracle()


def pytest_pyfunc_call(pyfuncitem):
    """Run `async def` tests on a fresh event loop (no pytest-asyncio in the
    image)."""
    if inspect.iscoroutinefunction(pyfuncitem.function):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(asyncio.wait_for(pyfuncitem.obj(**kwargs), timeout=120))
        return True
    return None
