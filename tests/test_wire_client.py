"""Wire-protocol client tests against the socket-level fake backend.

The full pipeline runs over real TCP with protocol-v3 framing: startup,
SCRAM auth, catalog queries, snapshot-pinned COPY, CopyBoth replication
with standby status updates — everything the reference exercises against a
dockerized Postgres (SURVEY §4.2), at the deepest seam this environment
allows."""

import asyncio

import pytest

from etl_tpu.config import (BatchConfig, BatchEngine, PgConnectionConfig,
                            PipelineConfig)
from etl_tpu.destinations import MemoryDestination
from etl_tpu.models import ErrorKind, EtlError, InsertEvent, Lsn
from etl_tpu.postgres.client import PgReplicationClient
from etl_tpu.postgres.version import (POSTGRES_15, meets_version,
                                      parse_server_version)
from etl_tpu.runtime import Pipeline, TableStateType
from etl_tpu.store import NotifyingStore
from etl_tpu.testing.fake_pg_server import FakePgServer
from tests.test_pipeline_e2e import ACCOUNTS, ORDERS, make_db


async def start_server(db, **kw):
    server = FakePgServer(db, **kw)
    await server.start()
    return server


def client_for(server, password=None):
    return PgReplicationClient(PgConnectionConfig(
        host="127.0.0.1", port=server.port, name="postgres",
        username="etl", password=password))


class TestWireBasics:
    async def test_connect_and_catalog(self):
        db = make_db()
        server = await start_server(db)
        try:
            c = client_for(server)
            await c.connect()
            assert c.server_version == 160003
            assert await c.publication_exists("pub")
            assert not await c.publication_exists("nope")
            assert await c.get_publication_table_ids("pub") == \
                [ACCOUNTS, ORDERS]
            schema = await c.get_table_schema(ACCOUNTS, "pub")
            assert [col.name for col in schema.replicated_columns] == \
                ["id", "name", "balance"]
            assert [col.name for col in schema.identity_columns()] == ["id"]
            lsn = await c.get_current_wal_lsn()
            assert lsn > Lsn.ZERO
            await c.close()
        finally:
            await server.stop()

    async def test_scram_auth(self):
        db = make_db()
        server = await start_server(db, password="s3cret")
        try:
            good = client_for(server, password="s3cret")
            await good.connect()
            assert await good.publication_exists("pub")
            await good.close()
            bad = client_for(server, password="wrong")
            with pytest.raises(EtlError) as ei:
                await bad.connect()
            assert ei.value.kind is ErrorKind.SOURCE_AUTH_FAILED
        finally:
            await server.stop()

    async def test_slot_lifecycle(self):
        db = make_db()
        server = await start_server(db)
        try:
            c = client_for(server)
            await c.connect()
            assert await c.get_slot("s1") is None
            created = await c.create_slot("s1")
            assert created.snapshot_id
            info = await c.get_slot("s1")
            assert info is not None and not info.invalidated
            with pytest.raises(EtlError) as ei:
                await c.create_slot("s1")
            assert ei.value.kind is ErrorKind.SLOT_ALREADY_EXISTS
            await c.delete_slot("s1")
            await c.delete_slot("s1")  # absent: no error
            assert await c.get_slot("s1") is None
            await c.close()
        finally:
            await server.stop()

    async def test_snapshot_pinned_copy(self):
        db = make_db()
        server = await start_server(db)
        try:
            c = client_for(server)
            await c.connect()
            created = await c.create_slot("s2")
            # mutate AFTER the snapshot
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["99", "late", "0"])
            stream = await c.copy_table_stream(ACCOUNTS, "pub",
                                               created.snapshot_id)
            data = b""
            async for chunk in stream:
                data += chunk
            lines = [l for l in data.split(b"\n") if l]
            assert len(lines) == 3  # snapshot view: no row 99
            await c.close()
        finally:
            await server.stop()

    def test_server_version_parse(self):
        assert parse_server_version("15.4") == 150004
        assert parse_server_version("16.3 (Debian 16.3-1)") == 160003
        assert parse_server_version("17beta1") == 170000
        assert parse_server_version("") == 0
        assert meets_version(150004, POSTGRES_15)
        assert not meets_version(140011, POSTGRES_15)
        assert not meets_version(0, POSTGRES_15)  # unknown never passes


class TestWireReplication:
    async def test_stream_and_status_updates(self):
        db = make_db()
        server = await start_server(db, keepalive_interval_s=0.03)
        try:
            c = client_for(server)
            await c.connect()
            created = await c.create_slot("repl")
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["42", "wired", "1"])
            stream = await c.start_replication("repl", "pub",
                                               created.consistent_point)
            from etl_tpu.postgres.codec.pgoutput import (PrimaryKeepalive,
                                                         XLogData)
            seen_insert = False
            commit_end = None
            async for frame in stream:
                if isinstance(frame, XLogData):
                    if frame.payload[:1] == b"I":
                        seen_insert = True
                    if frame.payload[:1] == b"C":
                        commit_end = frame.start_lsn
                        break
            assert seen_insert and commit_end is not None
            await stream.send_status_update(commit_end, commit_end,
                                            commit_end)
            await asyncio.sleep(0.05)
            assert db.slots["repl"].confirmed_flush >= commit_end
            await stream.close()
            await c.close()
        finally:
            await server.stop()


class TestPipelineOverWire:
    async def test_full_pipeline_over_tcp(self):
        """The complete pipeline — copy, handoff, CDC, resume — over the
        real wire protocol."""
        db = make_db()
        server = await start_server(db, keepalive_interval_s=0.03)
        store = NotifyingStore()
        dest = MemoryDestination()

        def mk():
            return Pipeline(
                config=PipelineConfig(
                    pipeline_id=2, publication_name="pub",
                    pg_connection=PgConnectionConfig(
                        host="127.0.0.1", port=server.port,
                        name="postgres", username="etl"),
                    batch=BatchConfig(max_size_bytes=1 << 20, max_fill_ms=40,
                                      batch_engine=BatchEngine.TPU)),
                store=store, destination=dest,
                source_factory=lambda: client_for(server))

        try:
            p = mk()
            await p.start()
            await asyncio.wait_for(
                store.notify_on(ACCOUNTS, TableStateType.READY), 20)
            assert len(dest.table_rows[ACCOUNTS]) == 3
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["5", "overwire", "123"])
            while not any(isinstance(e, InsertEvent)
                          and e.row.values[0] == 5 for e in dest.events):
                await asyncio.sleep(0.02)
            await p.shutdown_and_wait()

            # restart over the wire: no duplicate deliveries
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["6", "again", "1"])
            p2 = mk()
            await p2.start()
            while not any(isinstance(e, InsertEvent)
                          and e.row.values[0] == 6 for e in dest.events):
                await asyncio.sleep(0.02)
            n5 = sum(1 for e in dest.events if isinstance(e, InsertEvent)
                     and e.row.values[0] == 5)
            assert n5 == 1
            await p2.shutdown_and_wait()
        finally:
            await server.stop()


class TestWireChaos:
    async def test_tcp_partition_mid_stream_resumes_without_dupes(self):
        """NetworkChaos analogue at the deepest seam: sever the live TCP
        replication session mid-stream (transport abort — the client sees
        a hard reset, not CopyDone) and verify the apply worker's timed
        retry reconnects and resumes from confirmed_flush with no
        duplicate deliveries. Reference: Chaos Mesh NetworkChaos on
        replicator pods (xtask/src/commands/chaos/mod.rs:70-120)."""
        from etl_tpu.config import RetryConfig

        db = make_db()
        server = await start_server(db, keepalive_interval_s=0.03)
        store = NotifyingStore()
        dest = MemoryDestination()
        p = Pipeline(
            config=PipelineConfig(
                pipeline_id=9, publication_name="pub",
                pg_connection=PgConnectionConfig(
                    host="127.0.0.1", port=server.port,
                    name="postgres", username="etl"),
                batch=BatchConfig(max_size_bytes=1 << 20, max_fill_ms=20,
                                  batch_engine=BatchEngine.TPU),
                apply_retry=RetryConfig(max_attempts=8,
                                        initial_delay_ms=20)),
            store=store, destination=dest,
            source_factory=lambda: client_for(server))
        try:
            await p.start()
            await asyncio.wait_for(
                store.notify_on(ACCOUNTS, TableStateType.READY), 20)

            async def delivered(pk: int) -> None:
                while not any(isinstance(e, InsertEvent)
                              and e.row.values[0] == pk
                              for e in dest.events):
                    await asyncio.sleep(0.02)

            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["5", "before-cut", "1"])
            await asyncio.wait_for(delivered(5), 10)
            assert len(db.active_streams) >= 1  # wire session registered

            # partition: abort the TCP transport under the live session
            await db.sever_streams()
            # writes that land while the link is down must survive the
            # outage and arrive exactly once after the retry reconnects
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["6", "during-cut", "2"])
            await asyncio.wait_for(delivered(6), 10)
            async with db.transaction() as tx:
                tx.insert(ACCOUNTS, ["7", "after-heal", "3"])
            await asyncio.wait_for(delivered(7), 10)

            for pk in (5, 6, 7):
                n = sum(1 for e in dest.events if isinstance(e, InsertEvent)
                        and e.row.values[0] == pk)
                assert n == 1, f"row {pk} delivered {n} times"
        finally:
            await p.shutdown_and_wait()
            await server.stop()

    def _proxied_pipeline(self, proxy, max_attempts=12):
        from etl_tpu.config import RetryConfig

        cfg = PgConnectionConfig(host="127.0.0.1", port=proxy.port,
                                 name="postgres", username="etl")
        store = NotifyingStore()
        dest = MemoryDestination()
        p = Pipeline(
            config=PipelineConfig(
                pipeline_id=9, publication_name="pub",
                pg_connection=cfg,
                batch=BatchConfig(max_size_bytes=1 << 20, max_fill_ms=20,
                                  batch_engine=BatchEngine.TPU),
                apply_retry=RetryConfig(max_attempts=max_attempts,
                                        initial_delay_ms=20)),
            store=store, destination=dest,
            source_factory=lambda: PgReplicationClient(cfg))
        return p, store, dest

    async def test_latency_chaos_no_loss_no_dupes(self):
        """NetworkChaos Latency (tc netem delay analogue): every chunk
        through the proxy sleeps; delivery must stay exactly-once, just
        slower (xtask chaos/scenario.rs Latency)."""
        from etl_tpu.testing.chaos_proxy import ChaosProxy

        db = make_db()
        server = await start_server(db, keepalive_interval_s=0.03)
        proxy = ChaosProxy("127.0.0.1", server.port, delay_ms=15,
                           jitter_ms=5)
        await proxy.start()
        p, store, dest = self._proxied_pipeline(proxy)
        try:
            await p.start()
            await asyncio.wait_for(
                store.notify_on(ACCOUNTS, TableStateType.READY), 30)
            for pk in (50, 51, 52):
                async with db.transaction() as tx:
                    tx.insert(ACCOUNTS, [str(pk), "slow", "1"])
            while sum(1 for e in dest.events
                      if isinstance(e, InsertEvent)
                      and e.row.values[0] in (50, 51, 52)) < 3:
                await asyncio.sleep(0.02)
            counts = [sum(1 for e in dest.events
                          if isinstance(e, InsertEvent)
                          and e.row.values[0] == pk)
                      for pk in (50, 51, 52)]
            assert counts == [1, 1, 1], counts
        finally:
            await p.shutdown_and_wait()
            await proxy.stop()
            await server.stop()

    async def test_corruption_chaos_typed_error_then_recovery(self):
        """tc netem corrupt analogue: the proxy flips a byte in the
        walsender's stream; the wire client must surface a typed
        protocol/IO error (not hang on a corrupt length), reconnect,
        and resume exactly-once."""
        from etl_tpu.testing.chaos_proxy import ChaosProxy

        db = make_db()
        server = await start_server(db, keepalive_interval_s=0.03)
        proxy = ChaosProxy("127.0.0.1", server.port)
        await proxy.start()
        p, store, dest = self._proxied_pipeline(proxy, max_attempts=30)
        try:
            await p.start()
            await asyncio.wait_for(
                store.notify_on(ACCOUNTS, TableStateType.READY), 30)
            # arm after copy: streaming chaos. Every 5th chunk — dense
            # enough to fire on CDC traffic, sparse enough that retry
            # reconnect handshakes usually survive (the devtools
            # scenario uses the same density)
            proxy.corrupt_every = 5
            delivered = set()
            pk = 60
            # keep writing until corruption demonstrably fired AND the
            # rows around it all arrived (recovery, not luck)
            while proxy.corrupted < 1 or len(delivered) < 6:
                async with db.transaction() as tx:
                    tx.insert(ACCOUNTS, [str(pk), "x" * 200, "1"])
                target = pk
                pk += 1
                for _ in range(900):
                    got = {e.row.values[0] for e in dest.events
                           if isinstance(e, InsertEvent)}
                    if target in got:
                        delivered = got
                        break
                    await asyncio.sleep(0.02)
                else:
                    raise AssertionError(
                        f"row {target} never recovered after corruption")
            counts = {v: 0 for v in delivered}
            for e in dest.events:
                if isinstance(e, InsertEvent) and e.row.values[0] in counts:
                    counts[e.row.values[0]] += 1
            assert all(c == 1 for c in counts.values()), counts
            assert proxy.corrupted >= 1
        finally:
            await p.shutdown_and_wait()
            await proxy.stop()
            await server.stop()

    async def test_partition_during_copy_exact_row_set(self):
        """Chaos DURING the initial copy: partition the wire while the
        table copy is in flight; the crash-marker/fencing path must
        land EXACTLY the source row set (no loss, no dupes) before
        going READY."""
        from etl_tpu.models import (ColumnSchema, Oid, TableName,
                                    TableSchema)
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.chaos_proxy import ChaosProxy

        db = FakeDatabase()
        big = 18000
        n = 800
        db.create_table(TableSchema(
            big, TableName("public", "big"),
            (ColumnSchema("id", Oid.INT4, nullable=False,
                          primary_key_ordinal=1),
             ColumnSchema("v", Oid.TEXT))),
            rows=[[str(i + 1), f"v{i}" + "y" * 60] for i in range(n)])
        db.create_publication("pub", [big])
        server = await start_server(db, keepalive_interval_s=0.03)
        proxy = ChaosProxy("127.0.0.1", server.port)
        await proxy.start()
        p, store, dest = self._proxied_pipeline(proxy, max_attempts=30)
        try:
            ready = store.notify_on(big, TableStateType.READY)
            await p.start()
            severs = 0
            while not ready.done() and severs < 3:
                await asyncio.sleep(0.05)
                if ready.done():
                    break  # sever now would hit CDC, not the copy
                proxy.sever()
                severs += 1
            await asyncio.wait_for(ready, 60)
            assert severs >= 1, "copy finished before any chaos fired"
            got = [r.values[0] for r in dest.table_rows[big]]
            assert sorted(got) == list(range(1, n + 1)), (
                len(got), len(set(got)))
        finally:
            await p.shutdown_and_wait()
            await proxy.stop()
            await server.stop()


class TestWirePartitionsAndFilters:
    async def test_partition_leaves_over_wire(self):
        from tests.test_pipeline_e2e import (PART_L1, PART_L2, PART_ROOT,
                                             make_partitioned_db)

        db = make_partitioned_db()
        server = await start_server(db)
        try:
            c = client_for(server)
            await c.connect()
            leaves = await c.get_partition_leaves(PART_ROOT)
            assert [l[0] for l in leaves] == [PART_L1, PART_L2]
            assert leaves[0][1] == 150 and leaves[1][1] == 70
            assert await c.get_partition_leaves(PART_L1) == []
            await c.close()
        finally:
            await server.stop()

    async def test_copy_sql_includes_row_filter(self):
        """The wire COPY must carry the publication rowfilter predicate
        (transaction.rs:868) — the fake surfaces it via
        pg_publication_tables.rowfilter and filters server-side."""
        db = make_db()
        db.create_publication(
            "pub", [ACCOUNTS],
            row_filters={ACCOUNTS: ("balance >= 0",
                                    lambda r: r[2] is not None
                                    and int(r[2]) >= 0)})
        server = await start_server(db)
        try:
            c = client_for(server)
            await c.connect()
            created = await c.create_slot("supabase_etl_table_sync_1_16384")
            stream = await c.copy_table_stream(ACCOUNTS, "pub",
                                               created.snapshot_id)
            data = b""
            async for chunk in stream:
                data += chunk
            lines = [l for l in data.split(b"\n") if l]
            ids = {l.split(b"\t")[0] for l in lines}
            assert ids == {b"1", b"3"}  # bob (-5) filtered at COPY
            await c.close()
        finally:
            await server.stop()


class TestDrainBufferedErrorFrame:
    """An 'E' frame mid-drain must not discard the frames parsed (and
    already deleted from the reader buffer) earlier in the same pass —
    they would only come back via restart-from-durable re-delivery
    (ADVICE r2)."""

    def test_frames_before_error_survive(self):
        from etl_tpu.postgres.client import _WireReplicationStream
        from etl_tpu.postgres.codec.pgoutput import (
            PrimaryKeepalive, encode_primary_keepalive)
        from etl_tpu.postgres.wire import PgServerError

        def copy_data(payload: bytes) -> bytes:
            return b"d" + (4 + len(payload)).to_bytes(4, "big") + payload

        def error_frame(message: str) -> bytes:
            fields = b"SERROR\x00C57P01\x00M" + message.encode() + b"\x00\x00"
            return b"E" + (4 + len(fields)).to_bytes(4, "big") + fields

        buf = bytearray(
            copy_data(encode_primary_keepalive(0x100, 1_000_000))
            + copy_data(encode_primary_keepalive(0x200, 2_000_000))
            + error_frame("terminating connection")
            + copy_data(encode_primary_keepalive(0x300, 3_000_000)))

        stream = _WireReplicationStream.__new__(_WireReplicationStream)

        class _Reader:
            _buffer = buf

        class _Conn:
            _reader = _Reader()

        stream._conn = _Conn()
        stream._closed = False
        stream._pending_error = None

        out = stream.drain_buffered(10)
        assert [f.end_lsn for f in out] == [0x100, 0x200]
        assert all(isinstance(f, PrimaryKeepalive) for f in out)
        # the error surfaces on the NEXT drain, not mid-pass
        with pytest.raises(PgServerError, match="terminating"):
            stream.drain_buffered(10)
        # after raising once the stream drains normally again
        assert [f.end_lsn for f in stream.drain_buffered(10)] == [0x300]


class TestVersionGates:
    """PG14/15/17 matrix (reference etl-postgres/src/version.rs +
    transaction.rs:268,661): publication column lists and row filters are
    PG15+ catalog columns — on 14 the client must not even issue those
    queries (the fake, like real PG14, errors with 42703 on pt.attnames)."""

    async def test_pg14_schema_skips_publication_column_query(self):
        db = make_db()
        server = await start_server(db, server_version="14.11")
        try:
            c = client_for(server)
            await c.connect()
            assert c.server_version == 140011
            schema = await c.get_table_schema(ACCOUNTS, "pub")
            # all columns replicate pre-15
            assert [col.name for col in schema.replicated_columns] == \
                ["id", "name", "balance"]
            assert not any("pt.attnames" in q for q in server.queries)
            await c.close()
        finally:
            await server.stop()

    @pytest.mark.parametrize("version", ["15.4", "17.0"])
    async def test_pg15_plus_schema_applies_column_list(self, version):
        db = make_db()
        db.create_publication("pub", [ACCOUNTS],
                              column_filters={ACCOUNTS: ["id", "balance"]})
        server = await start_server(db, server_version=version)
        try:
            c = client_for(server)
            await c.connect()
            schema = await c.get_table_schema(ACCOUNTS, "pub")
            assert [col.name for col in schema.replicated_columns] == \
                ["id", "balance"]
            assert any("pt.attnames" in q for q in server.queries)
            await c.close()
        finally:
            await server.stop()

    async def test_pg14_copy_ignores_row_filter_and_survives(self):
        """A PG14 server has no rowfilter column: the gated client copies
        every row without issuing the PG15-only query (ungated code would
        die on 42703)."""
        db = make_db()
        db.create_publication(
            "pub", [ACCOUNTS],
            row_filters={ACCOUNTS: ("balance >= 0",
                                    lambda r: r[2] is not None
                                    and int(r[2]) >= 0)})
        server = await start_server(db, server_version="14.11")
        try:
            c = client_for(server)
            await c.connect()
            created = await c.create_slot("supabase_etl_table_sync_9_16384")
            stream = await c.copy_table_stream(ACCOUNTS, "pub",
                                               created.snapshot_id)
            data = b""
            async for chunk in stream:
                data += chunk
            lines = [l for l in data.split(b"\n") if l]
            assert len(lines) == 3  # no predicate applied pre-15
            assert not any("pt.rowfilter" in q for q in server.queries)
            await c.close()
        finally:
            await server.stop()

    async def test_pg15_copy_applies_row_filter(self):
        db = make_db()
        db.create_publication(
            "pub", [ACCOUNTS],
            row_filters={ACCOUNTS: ("balance >= 0",
                                    lambda r: r[2] is not None
                                    and int(r[2]) >= 0)})
        server = await start_server(db, server_version="15.4")
        try:
            c = client_for(server)
            await c.connect()
            created = await c.create_slot("supabase_etl_table_sync_8_16384")
            stream = await c.copy_table_stream(ACCOUNTS, "pub",
                                               created.snapshot_id)
            data = b""
            async for chunk in stream:
                data += chunk
            lines = [l for l in data.split(b"\n") if l]
            ids = {l.split(b"\t")[0] for l in lines}
            assert ids == {b"1", b"3"}
            await c.close()
        finally:
            await server.stop()


class TestWireTls:
    """TLS handshake coverage (VERDICT r2 weak #3: zero ssl-path tests):
    the client's sslmode=require path against the fake server with a
    self-signed cert, plus refusal and verification-failure shapes."""

    def _tls_client(self, server, cert_pem, password=None):
        from etl_tpu.config.pipeline import TlsConfig

        return PgReplicationClient(PgConnectionConfig(
            host="127.0.0.1", port=server.port, name="postgres",
            username="etl", password=password,
            tls=TlsConfig(enabled=True,
                          trusted_root_certs=cert_pem.decode())))

    async def test_scram_and_catalog_over_tls(self):
        from etl_tpu.testing.tls import make_self_signed_cert

        cert, key = make_self_signed_cert()
        db = make_db()
        server = await start_server(db, password="tls-secret",
                                    tls_cert=(cert, key))
        try:
            c = self._tls_client(server, cert, password="tls-secret")
            await c.connect()
            assert c.server_version == 160003
            assert await c.publication_exists("pub")
            schema = await c.get_table_schema(ACCOUNTS, "pub")
            assert [col.name for col in schema.replicated_columns] == \
                ["id", "name", "balance"]
            await c.close()
        finally:
            await server.stop()

    async def test_server_refuses_tls_errors_typed(self):
        from etl_tpu.testing.tls import make_self_signed_cert

        cert, _ = make_self_signed_cert()
        db = make_db()
        server = await start_server(db)  # no tls_cert → 'N' on SSLRequest
        try:
            c = self._tls_client(server, cert)
            with pytest.raises(EtlError) as ei:
                await c.connect()
            assert ei.value.kind is ErrorKind.SOURCE_TLS_FAILED
        finally:
            await server.stop()

    async def test_untrusted_ca_fails_verification(self):
        from etl_tpu.testing.tls import make_self_signed_cert

        server_cert, server_key = make_self_signed_cert()
        other_cert, _ = make_self_signed_cert()  # different CA
        db = make_db()
        server = await start_server(db, tls_cert=(server_cert, server_key))
        try:
            c = self._tls_client(server, other_cert)
            with pytest.raises(EtlError) as ei:
                await c.connect()
            assert ei.value.kind is ErrorKind.SOURCE_TLS_FAILED
        finally:
            await server.stop()


class TestGoldenTranscripts:
    """Pinned byte exchanges: framing/auth regressions must fail loudly,
    not just keep passing against the same codebase's fake (VERDICT r2
    weak #3 self-confirmation risk)."""

    async def test_scram_exchange_matches_pinned_transcript(self, monkeypatch):
        """With fixed nonces/salt the full SCRAM-SHA-256 exchange is
        deterministic; the pinned messages below were cross-checked with a
        test-local independent RFC 5802 computation (asserted too)."""
        import base64
        import hashlib
        import hmac as hmac_mod

        from etl_tpu.postgres.wire import PgWireConnection

        db = make_db()
        server = await start_server(db, password="pencil",
                                    scram_salt=bytes(range(16)),
                                    scram_nonce_tail="FIXEDSERVERNONCE")
        monkeypatch.setattr(PgWireConnection, "_scram_nonce_bytes",
                            staticmethod(lambda: bytes(range(18))))
        try:
            c = client_for(server, password="pencil")
            await c.connect()
            await c.close()
        finally:
            await server.stop()
        assert server.scram_transcript == [
            ("C", "n,,n=,r=AAECAwQFBgcICQoLDA0ODxAR"),
            ("S", "r=AAECAwQFBgcICQoLDA0ODxARFIXEDSERVERNONCE,"
                  "s=AAECAwQFBgcICQoLDA0ODw==,i=4096"),
            ("C", "c=biws,r=AAECAwQFBgcICQoLDA0ODxARFIXEDSERVERNONCE,"
                  "p=k1+3DsLb3BLeE7IUByi2TYW5Un24LiB+SdvlSjsO2QY="),
            ("S", "v=4DPyfFjArFn8MEqHF4h0GV+j4KCJmanPBOiXaZcs4kc="),
        ]
        # independent RFC 5802 math (straight from the spec, not the
        # client implementation): proof = ClientKey XOR HMAC(StoredKey, A)
        salted = hashlib.pbkdf2_hmac("sha256", b"pencil", bytes(range(16)),
                                     4096)
        client_key = hmac_mod.new(salted, b"Client Key",
                                  hashlib.sha256).digest()
        stored = hashlib.sha256(client_key).digest()
        auth_message = (
            "n=,r=AAECAwQFBgcICQoLDA0ODxAR,"
            "r=AAECAwQFBgcICQoLDA0ODxARFIXEDSERVERNONCE,"
            "s=AAECAwQFBgcICQoLDA0ODw==,i=4096,"
            "c=biws,r=AAECAwQFBgcICQoLDA0ODxARFIXEDSERVERNONCE")
        sig = hmac_mod.new(stored, auth_message.encode(),
                           hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, sig))
        assert base64.b64encode(proof).decode() == \
            "k1+3DsLb3BLeE7IUByi2TYW5Un24LiB+SdvlSjsO2QY="
        server_key = hmac_mod.new(salted, b"Server Key",
                                  hashlib.sha256).digest()
        verifier = hmac_mod.new(server_key, auth_message.encode(),
                                hashlib.sha256).digest()
        assert base64.b64encode(verifier).decode() == \
            "4DPyfFjArFn8MEqHF4h0GV+j4KCJmanPBOiXaZcs4kc="

    def test_pgoutput_frame_bytes_pinned(self):
        """CopyBoth payload framing: pgoutput v2 message bytes and the
        XLogData ('w') envelope, pinned against the documented layouts
        (Begin: lsn/ts/xid; Insert: relid,'N',tuple; Commit: flags,
        2×lsn, ts; XLogData: start/end/clock + payload)."""
        from etl_tpu.postgres.codec import pgoutput as pg

        assert pg.encode_begin(0x12345678, 1_700_000_000_000_000, 42).hex() \
            == "4200000000123456780002ad22dce660000000002a"
        assert pg.encode_insert(16384, [b"7", None, b"x"]).hex() \
            == "49000040004e00037400000001376e740000000178"
        assert pg.encode_commit(0x12345678, 0x12345680,
                                1_700_000_000_000_000).hex() \
            == "4300000000001234567800000000123456800002ad22dce66000"
        assert pg.encode_xlog_data(0x100, 0x200, 999, b"ABC").hex() \
            == "7700000000000001000000000000000200fffca2fec4c823e7414243"
        # and the decoder round-trips the pinned bytes
        msg = pg.decode_logical_message(bytes.fromhex(
            "49000040004e00037400000001376e740000000178"))
        assert isinstance(msg, pg.InsertMessage)
        assert msg.relation_id == 16384
        assert msg.new_tuple.values == [b"7", None, b"x"]
