"""Exactly-once delivery: transactional sink commits keyed by WAL
coordinates (ISSUE 19).

Covers, bottom-up:
  - `CommitRange` derivation from WAL-ordered flush payloads and the
    wire-token shape sinks record;
  - the reference transactional sink (`TransactionalMemoryDestination`):
    streamed dedup against the monotone high-water coordinate, replay
    dedup by exact row key (never moving the high-water mark), atomic
    data+range commits, and the scripted recovery-fault knobs;
  - wrapper forwarding: every destination wrapper delegates the
    capability probe and both seam methods to the INNER sink;
  - satellite 1: recovery high-water queries retried through
    `RetryPolicy`, bounded by `destination_op_timeout_s`, degrading to
    a blind re-stream with the fallback metric on exhaustion;
  - satellite 2: DLQ replay through a transactional destination carries
    the original WAL-coordinate keys — replaying twice is a no-op and
    replays never advance the streaming high-water mark;
  - satellite 3: the hard-kill matrix green in tier-1 plus per-seed
    determinism of the stable end-state via the CLI.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from etl_tpu.config import PipelineConfig, RetryConfig
from etl_tpu.destinations import (DelayedAckDestination,
                                  FaultInjectingDestination,
                                  MemoryDestination,
                                  PoisonRejectingDestination,
                                  TransactionalMemoryDestination)
from etl_tpu.destinations.base import CommitRange, event_coordinate
from etl_tpu.dlq import DeadLetterQueue
from etl_tpu.models import ColumnSchema, Oid, TableName, TableSchema
from etl_tpu.models.errors import ErrorKind, EtlError
from etl_tpu.models.event import BeginEvent, CommitEvent, InsertEvent
from etl_tpu.models.lsn import Lsn
from etl_tpu.models.schema import ReplicatedTableSchema
from etl_tpu.models.table_row import TableRow
from etl_tpu.store import MemoryStore
from etl_tpu.supervision.destination import SupervisedDestination


def make_schema(tid: int = 16384) -> ReplicatedTableSchema:
    return ReplicatedTableSchema.with_all_columns(TableSchema(
        tid, TableName("public", f"t{tid}"),
        (ColumnSchema("id", Oid.INT8, nullable=False,
                      primary_key_ordinal=1),
         ColumnSchema("note", Oid.TEXT))))


def insert_event(schema, pk: int, note: str, commit: int = 100,
                 ordinal: int | None = None) -> InsertEvent:
    return InsertEvent(Lsn(commit - 1), Lsn(commit),
                       ordinal if ordinal is not None else pk, schema,
                       TableRow([pk, note]))


# -- CommitRange --------------------------------------------------------------


class TestCommitRange:
    def test_from_events_takes_lexicographic_max(self):
        schema = make_schema()
        events = [insert_event(schema, 1, "a", commit=100, ordinal=3),
                  insert_event(schema, 2, "b", commit=200, ordinal=1),
                  insert_event(schema, 3, "c", commit=200, ordinal=2)]
        rng = CommitRange.from_events(events, commit_end_lsn=250)
        assert rng.high == (200, 2)
        assert rng.commit_end_lsn == 250
        assert rng.replay is False

    def test_token_is_offset_token_hex_shape(self):
        rng = CommitRange(high=(0x1A2B, 7))
        assert rng.token() == "0000000000001a2b/0000000000000007"

    def test_controls_have_no_coordinates(self):
        # Begin/Commit envelopes carry no row identity: a control-only
        # flush has nothing to dedup and derives no range
        controls = [BeginEvent(Lsn(99), Lsn(100), 0, 5),
                    CommitEvent(Lsn(100), Lsn(100), Lsn(101), 0)]
        assert all(event_coordinate(e) is None for e in controls)
        assert CommitRange.from_events(controls) is None

    def test_row_coordinate_identity(self):
        e = insert_event(make_schema(), 9, "x", commit=300, ordinal=4)
        assert event_coordinate(e) == (300, 4)

    def test_replay_flag_carried(self):
        rng = CommitRange.from_events(
            [insert_event(make_schema(), 1, "a")], replay=True)
        assert rng.replay is True and rng.commit_end_lsn is None


# -- the reference transactional sink -----------------------------------------


class TestTransactionalMemorySink:
    def _sink(self):
        return TransactionalMemoryDestination()

    async def test_stream_commit_records_data_and_range_atomically(self):
        sink = self._sink()
        schema = make_schema()
        events = [insert_event(schema, i, f"r{i}", commit=100 + i)
                  for i in range(3)]
        ack = await sink.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=110))
        await ack.wait_durable()
        assert [e.row.values[0] for e in sink.events] == [0, 1, 2]
        assert sink.high_water == (102, 2)
        assert sink.committed_end_lsn == 110
        assert sink.high_water_log == [(102, 2)]

    async def test_blind_restream_dedups_below_high_water(self):
        """The crash shape: re-streamed rows at coordinates <= the
        recorded high-water drop regardless of the retry's batch
        boundaries; only the genuinely-new suffix applies."""
        sink = self._sink()
        schema = make_schema()
        first = [insert_event(schema, i, f"r{i}", commit=100 + i)
                 for i in range(4)]
        await sink.write_event_batches_committed(
            first, CommitRange.from_events(first, commit_end_lsn=104))
        # re-stream overlaps the last two rows and adds two new ones
        retry = first[2:] + [
            insert_event(schema, i, f"r{i}", commit=100 + i)
            for i in range(4, 6)]
        await sink.write_event_batches_committed(
            retry, CommitRange.from_events(retry, commit_end_lsn=106))
        assert sink.dedup_skipped_rows == 2
        assert [e.row.values[0] for e in sink.events] == [0, 1, 2, 3, 4, 5]
        assert sink.high_water == (105, 5)

    async def test_fully_deduped_flush_is_a_noop_write(self):
        sink = self._sink()
        schema = make_schema()
        events = [insert_event(schema, 1, "a", commit=100)]
        await sink.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=100))
        before = len(sink.events)
        ack = await sink.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=100))
        await ack.wait_durable()
        assert len(sink.events) == before
        assert sink.dedup_skipped_rows == 1
        # the range still committed (log appends; monotone, not strict)
        assert sink.high_water_log == [(100, 1), (100, 1)]

    async def test_replay_dedups_by_exact_key_not_high_water(self):
        """Replayed rows sit BELOW the streaming high-water mark by
        construction (they were parked while the stream moved on) — a
        replay must still apply them once, keyed exactly, and must not
        move the high-water mark."""
        sink = self._sink()
        schema = make_schema()
        live = [insert_event(schema, 9, "live", commit=900)]
        await sink.write_event_batches_committed(
            live, CommitRange.from_events(live, commit_end_lsn=900))
        parked = [insert_event(schema, 1, "parked", commit=100),
                  insert_event(schema, 2, "parked", commit=101)]
        rng = CommitRange.from_events(parked, replay=True)
        await sink.write_event_batches_committed(parked, rng)
        assert [e.row.values[0] for e in sink.events] == [9, 1, 2]
        assert sink.replay_skipped_rows == 0
        assert sink.high_water == (900, 9)  # unmoved
        # replay twice: the second pass is a keyed no-op
        await sink.write_event_batches_committed(parked, rng)
        assert [e.row.values[0] for e in sink.events] == [9, 1, 2]
        assert sink.replay_skipped_rows == 2

    async def test_plain_write_counts_as_uncoordinated(self):
        sink = self._sink()
        await sink.write_events([insert_event(make_schema(), 1, "a")])
        assert sink.uncoordinated_writes == 1

    async def test_recover_high_water_round_trip_and_faults(self):
        sink = self._sink()
        assert await sink.recover_high_water() is None  # fresh sink
        schema = make_schema()
        events = [insert_event(schema, 1, "a", commit=100)]
        await sink.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=100))
        rng = await sink.recover_high_water()
        assert rng.high == (100, 1) and rng.commit_end_lsn == 100
        sink.recover_faults.append(
            EtlError(ErrorKind.TIMEOUT, "scripted"))
        with pytest.raises(EtlError):
            await sink.recover_high_water()
        # faults are FIFO: the next query answers again
        assert (await sink.recover_high_water()).high == (100, 1)
        assert sink.recover_calls == 4


# -- wrapper forwarding -------------------------------------------------------


class TestWrapperForwarding:
    WRAPPERS = [
        ("supervised", lambda inner: SupervisedDestination(
            inner, timeout_s=5.0)),
        ("delayed_ack", lambda inner: DelayedAckDestination(inner, 0.0)),
        ("fault_injecting", FaultInjectingDestination),
        ("poison_rejecting", PoisonRejectingDestination),
    ]

    @pytest.mark.parametrize("name,make", WRAPPERS,
                             ids=[w[0] for w in WRAPPERS])
    async def test_probe_reflects_inner(self, name, make):
        wrapped = make(TransactionalMemoryDestination())
        assert wrapped.supports_transactional_commit() is True
        plain = make(MemoryDestination())
        assert plain.supports_transactional_commit() is False
        await wrapped.shutdown()
        await plain.shutdown()

    @pytest.mark.parametrize("name,make", WRAPPERS,
                             ids=[w[0] for w in WRAPPERS])
    async def test_committed_write_and_recovery_forward(self, name, make):
        inner = TransactionalMemoryDestination()
        wrapped = make(inner)
        schema = make_schema()
        events = [insert_event(schema, 1, "a", commit=100)]
        ack = await wrapped.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=100))
        await ack.wait_durable()
        assert inner.high_water == (100, 1)
        assert inner.uncoordinated_writes == 0
        rng = await wrapped.recover_high_water()
        assert rng is not None and rng.high == (100, 1)
        assert inner.recover_calls == 1
        await wrapped.shutdown()


# -- satellite 1: recovery-query failure policy -------------------------------


class _RecoveryEnv:
    """An ApplyWorker wired just enough to drive
    `_recover_sink_high_water` (the method touches only config,
    destination, and the metrics registry)."""

    def __init__(self, destination, *, max_attempts: int = 3,
                 op_timeout_s: float = 5.0):
        from etl_tpu.runtime.apply_worker import ApplyWorker
        from etl_tpu.runtime.shutdown import ShutdownSignal

        config = PipelineConfig(
            pipeline_id=1, publication_name="pub",
            destination_op_timeout_s=op_timeout_s,
            apply_retry=RetryConfig(max_attempts=max_attempts,
                                    initial_delay_ms=1, max_delay_ms=5))
        self.worker = ApplyWorker(
            config=config, store=MemoryStore(), destination=destination,
            source_factory=None, pool=None, table_cache=None,
            shutdown=ShutdownSignal())


def _counters():
    from etl_tpu.telemetry.metrics import (
        ETL_EXACTLY_ONCE_RECOVERIES_TOTAL,
        ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL, registry)

    return (registry.get_counter(ETL_EXACTLY_ONCE_RECOVERIES_TOTAL),
            registry.get_counter(ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL,
                                 labels={"reason": "error"}),
            registry.get_counter(ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL,
                                 labels={"reason": "timeout"}))


class TestRecoveryFailurePolicy:
    async def test_non_transactional_sink_never_queried(self):
        env = _RecoveryEnv(MemoryDestination())
        assert await env.worker._recover_sink_high_water() is None

    async def test_transient_fault_retried_to_success(self):
        sink = TransactionalMemoryDestination()
        schema = make_schema()
        events = [insert_event(schema, 1, "a", commit=100)]
        await sink.write_event_batches_committed(
            events, CommitRange.from_events(events, commit_end_lsn=100))
        sink.recover_faults.append(
            EtlError(ErrorKind.DESTINATION_CONNECTION_FAILED, "blip"))
        ok_before, *_ = _counters()
        env = _RecoveryEnv(sink)
        rng = await env.worker._recover_sink_high_water()
        assert rng is not None and rng.high == (100, 1)
        assert sink.recover_calls == 2  # fault, then the retried success
        assert _counters()[0] == ok_before + 1

    async def test_exhausted_retries_degrade_to_blind_restream(self):
        sink = TransactionalMemoryDestination()
        for _ in range(5):
            sink.recover_faults.append(
                EtlError(ErrorKind.DESTINATION_FAILED, "down"))
        _, err_before, _ = _counters()
        env = _RecoveryEnv(sink, max_attempts=2)
        assert await env.worker._recover_sink_high_water() is None
        assert sink.recover_calls == 2  # bounded by the policy
        assert _counters()[1] == err_before + 1

    async def test_op_timeout_bounds_each_attempt(self):
        sink = TransactionalMemoryDestination()
        sink.recover_delay_s = 5.0  # far past the op bound
        _, _, to_before = _counters()
        env = _RecoveryEnv(sink, max_attempts=2, op_timeout_s=0.05)
        assert await env.worker._recover_sink_high_water() is None
        assert _counters()[2] == to_before + 1

    async def test_untyped_sink_exception_surfaces_typed(self):
        class BrokenSink(TransactionalMemoryDestination):
            async def recover_high_water(self):
                self.recover_calls += 1
                raise RuntimeError("raw client explosion")

        sink = BrokenSink()
        _, err_before, _ = _counters()
        env = _RecoveryEnv(sink, max_attempts=2)
        # the raw exception is wrapped DESTINATION_FAILED, retried, and
        # degrades — it never propagates out of recovery
        assert await env.worker._recover_sink_high_water() is None
        assert sink.recover_calls == 2
        assert _counters()[1] == err_before + 1


# -- satellite 2: DLQ replay keyed by original coordinates --------------------


class TestDlqReplayTransactional:
    async def _parked_store(self, schema, rows):
        from etl_tpu.dlq.codec import encode_row_event
        from etl_tpu.store.base import DeadLetterEntry

        store = MemoryStore()
        await store.store_table_schema(schema, 1)
        entries = []
        for pk, note, commit in rows:
            ev = insert_event(schema, pk, note, commit=commit)
            change, payload = encode_row_event(ev)
            entries.append(DeadLetterEntry(
                entry_id=0, table_id=schema.id,
                commit_lsn=int(ev.commit_lsn), tx_ordinal=ev.tx_ordinal,
                change_type=change, payload=payload,
                error_kind="DESTINATION_REJECTED", detail="test"))
        await store.append_dead_letters(entries)
        return store

    async def test_replay_twice_is_idempotent_on_transactional_sink(self):
        schema = make_schema()
        store = await self._parked_store(
            schema, [(1, "p1", 100), (2, "p2", 101)])
        sink = TransactionalMemoryDestination()
        # the live stream moved on while these rows were parked
        live = [insert_event(schema, 9, "live", commit=900)]
        await sink.write_event_batches_committed(
            live, CommitRange.from_events(live, commit_end_lsn=900))

        dlq = DeadLetterQueue(store)
        out = await dlq.replay(sink)
        assert len(out["replayed"]) == 2
        assert [e.row.values[0] for e in sink.events] == [9, 1, 2]
        # replays dedup by EXACT key, below the high-water mark, and
        # never advance it
        assert sink.high_water == (900, 9)
        assert sink.dedup_skipped_rows == 0

        # status-flip idempotence: a second replay finds nothing
        again = await dlq.replay(sink)
        assert again["replayed"] == []
        # crash-between-write-and-flip shape: force a re-push of
        # already-replayed entries — the sink's replay keys absorb it
        forced = await dlq.replay(sink, include_replayed=True)
        assert len(forced["replayed"]) == 2
        assert [e.row.values[0] for e in sink.events] == [9, 1, 2]
        assert sink.replay_skipped_rows == 2
        assert sink.uncoordinated_writes == 0

    async def test_replay_on_plain_sink_keeps_at_least_once(self):
        """A non-transactional destination replays through the plain
        seam unchanged — the DLQ stays destination-agnostic."""
        schema = make_schema()
        store = await self._parked_store(schema, [(1, "p1", 100)])
        sink = MemoryDestination()
        out = await DeadLetterQueue(store).replay(sink)
        assert len(out["replayed"]) == 1
        assert [e.row.values[0] for e in sink.events] == [1]


# -- satellite 3: the hard-kill matrix in tier-1 ------------------------------


def _stable_window_view(doc: dict) -> dict:
    """The seed-deterministic end-state subset of one window's
    describe(): kill timing races (resume LSN, in-flight acks, dedup
    counts) vary run to run; the DELIVERED state must not."""
    return {k: doc[k] for k in ("window", "seed", "max_duplication",
                                "delivered_events", "expected_rows",
                                "high_water")}


class TestExactlyOnceChaos:
    async def test_kill_matrix_exactly_once(self):
        from etl_tpu.chaos.exactly_once import (KILL_WINDOWS,
                                                run_exactly_once_crash)

        run = await run_exactly_once_crash(seed=7)
        assert run.ok, run.report.violations
        assert [w["window"] for w in run.windows] == list(KILL_WINDOWS)
        for w in run.windows:
            # dup budget 0: no row event delivered more than once
            assert w["max_duplication"] <= 1, w
            assert w["delivered_events"] > 0, w
            assert w["recover_calls"] >= len(w["restarts"]), w
            assert len(w["restarts"]) >= 1, w
        # the mid-recovery window really took two kills
        assert len(run.windows[2]["restarts"]) == 2

    def test_cli_determinism(self):
        """`python -m etl_tpu.chaos --exactly-once` delivers the same
        end state per seed (timing-raced kill diagnostics stripped)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        outs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-m", "etl_tpu.chaos", "--exactly-once",
                 "--seed", "11"],
                capture_output=True, text=True, timeout=240, cwd=repo,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stdout + proc.stderr
            doc = json.loads(proc.stdout.strip().splitlines()[-1])
            assert doc["ok"] is True
            outs.append({
                "seed": doc["seed"],
                "invariants": doc["invariants"]["violations"],
                "windows": [_stable_window_view(w)
                            for w in doc["windows"]],
            })
        assert outs[0] == outs[1]


# -- satellite 5: the bench harness slice -------------------------------------


class TestExactlyOnceBenchHarness:
    async def test_run_exactly_once_smoke_slice(self):
        """One small pass of the full A/B + restart-leg harness: the
        gate arithmetic (zero dups, loss, re-stream <= unacked suffix,
        seam coverage) holds at smoke size."""
        from etl_tpu.benchmarks import harness

        out = await harness.run_exactly_once(n_events=400, tx_size=20,
                                             repeats=1)
        assert out["failures"] == [], out
        assert out["ok"] is True
        assert out["transactional"]["uncoordinated_writes"] == 0
        leg = out["restart"]
        assert leg["duplicate_rows"] == 0
        assert leg["rows_delivered"] == 400
        assert leg["restreamed_deduped_rows"] <= leg["unacked_suffix_rows"]
        assert leg["recover_calls"] >= 1
