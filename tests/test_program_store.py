"""Program-store tests (ISSUE 12): canonical decode-program layouts,
AOT disk persistence, startup prewarm, invalidation, and two-process
cache-dir sharing.

The byte-identity matrix follows the Pallas==XLA differential stance:
the canonical layout (index erasure + kind sort + count padding) must
produce the SAME decoded ColumnarBatch as the exact layout on every
engine and routing path, because column outputs index by schema
position, never by program slot."""

import os
import pickle
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from etl_tpu.models import (ColumnSchema, Oid, ReplicatedTableSchema,
                            TableName, TableSchema)
from etl_tpu.models.pgtypes import CellKind
from etl_tpu.ops import engine as engine_mod
from etl_tpu.ops import program_store
from etl_tpu.ops.engine import DeviceDecoder
from etl_tpu.ops.staging import stage_tuples, synthetic_staged_batch
from etl_tpu.postgres.codec.pgoutput import (TUPLE_NULL, TUPLE_TEXT,
                                             TupleData)
from etl_tpu.telemetry.metrics import (ETL_COMPILE_CACHE_HITS_TOTAL,
                                       ETL_COMPILE_CACHE_MISSES_TOTAL,
                                       ETL_PROGRAMS_COMPILED_TOTAL,
                                       registry)


def make_schema(oids, tid=1):
    return ReplicatedTableSchema.with_all_columns(TableSchema(
        tid, TableName("public", f"t{tid}"),
        tuple(ColumnSchema(f"c{i}", oid) for i, oid in enumerate(oids))))


def tuples_from_texts(rows):
    out = []
    for r in rows:
        kinds = [TUPLE_NULL if v is None else TUPLE_TEXT for v in r]
        vals = [None if v is None else v.encode() for v in r]
        out.append(TupleData(kinds, vals))
    return out


def assert_batches_identical(a, b):
    assert a.num_rows == b.num_rows
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(ca.validity, cb.validity)
        if ca.is_dense:
            da = np.where(ca.validity, ca.data, 0)
            db = np.where(cb.validity, cb.data, 0)
            if np.issubdtype(da.dtype, np.floating):
                w = np.uint32 if da.dtype == np.float32 else np.uint64
                np.testing.assert_array_equal(da.view(w), db.view(w))
            else:
                np.testing.assert_array_equal(da, db)
        else:
            for i in range(a.num_rows):
                if ca.validity[i]:
                    assert ca.value(i) == cb.value(i)


def decode_exact_and_canonical(schema, staged, **decoder_kw):
    """Decode the SAME staged batch with canonicalization on and off
    (fresh decoders each side, plan cache cleared between)."""
    canon = DeviceDecoder(schema, **decoder_kw).decode(staged)
    old = program_store.CANONICALIZE
    program_store.CANONICALIZE = False
    program_store._PLAN_CACHE.clear()
    try:
        exact = DeviceDecoder(schema, **decoder_kw).decode(staged)
    finally:
        program_store.CANONICALIZE = old
        program_store._PLAN_CACHE.clear()
    return canon, exact


@pytest.fixture(autouse=True)
def _deconfigure_store():
    yield
    program_store.configure(None)


def _specs(*triples):
    """Synthetic engine specs: (col_idx, kind, w, bw) with sequential
    col indices."""
    return tuple((i, k, w, bw) for i, (k, w, bw) in enumerate(triples))


class TestCanonicalPlan:
    def test_pad_count_ladder(self):
        assert [program_store.pad_count(n) for n in (1, 2, 3, 5, 7, 9, 13)] \
            == [1, 2, 3, 6, 8, 12, 16]
        # ≤1.5× steps: padding never adds more than half a group again
        for n in range(1, 257):
            assert n <= program_store.pad_count(n) <= max(2, (3 * n) // 2)

    def test_identity_when_sorted_and_at_bucket(self):
        plan = program_store.canonical_plan(
            _specs((CellKind.I32, 12, 12), (CellKind.I32, 12, 12)))
        assert plan.identity and not plan.phantom_slots
        # index erasure still applies: program specs are positional
        assert plan.specs == ((0, CellKind.I32, 12, 12),
                              (1, CellKind.I32, 12, 12))

    def test_sorts_and_pads(self):
        # 5× I32 (pads to 6) interleaved with one I64
        specs = _specs(*([(CellKind.I32, 12, 12)] * 2
                         + [(CellKind.I64, 20, 20)]
                         + [(CellKind.I32, 12, 12)] * 3))
        plan = program_store.canonical_plan(specs)
        assert plan.n_slots == 7  # 6 I32 slots + 1 I64
        assert len(plan.phantom_slots) == 1
        assert sorted(plan.slot_of) == sorted(
            set(range(plan.n_slots)) - set(plan.phantom_slots))
        # phantom donors carry the group's own triple
        for slot in plan.phantom_slots:
            donor = plan.pack_dense[slot]
            assert specs[donor][1:] == plan.specs[slot][1:]
        # the padded layout is what an actual 6-I32 + 1-I64 table gets
        full = program_store.canonical_plan(
            _specs(*([(CellKind.I32, 12, 12)] * 6
                     + [(CellKind.I64, 20, 20)])))
        assert full.specs == plan.specs

    def test_order_erasure_shares_layout(self):
        a = program_store.canonical_plan(
            _specs((CellKind.I64, 20, 20), (CellKind.F64, 32, 24)))
        b = program_store.canonical_plan(
            _specs((CellKind.F64, 32, 24), (CellKind.I64, 20, 20)))
        assert a.specs == b.specs

    def test_max_slots_falls_back_to_sort_only(self):
        # 52 groups of 5 would pad to 312 slots > 256: no phantoms
        triples = []
        for g in range(52):
            triples += [(CellKind.I32, 4 + 4 * (g % 50), 10)] * 5
        plan = program_store.canonical_plan(_specs(*triples))
        assert plan.n_slots == 260 or plan.n_slots == len(triples)
        assert not plan.phantom_slots

    def test_canonicalize_off_is_identity(self, monkeypatch):
        monkeypatch.setattr(program_store, "CANONICALIZE", False)
        program_store._PLAN_CACHE.clear()
        specs = _specs((CellKind.I64, 20, 20), (CellKind.I32, 12, 12))
        plan = program_store.canonical_plan(specs)
        assert plan.identity and plan.slot_of == (0, 1)
        program_store._PLAN_CACHE.clear()

    def test_host_key_shared_across_permuted_schemas(self):
        d1 = DeviceDecoder(make_schema([Oid.INT8, Oid.FLOAT8, Oid.INT4]),
                           mesh=None)
        d2 = DeviceDecoder(make_schema([Oid.INT4, Oid.INT8, Oid.FLOAT8]),
                           mesh=None)
        assert engine_mod._host_fn_key(256, d1._host_specs()) \
            == engine_mod._host_fn_key(256, d2._host_specs())


MATRIX_OIDS = [Oid.BOOL, Oid.INT2, Oid.INT4, Oid.INT8, Oid.FLOAT4,
               Oid.FLOAT8, Oid.DATE, Oid.TIME, Oid.TIMESTAMP,
               Oid.TIMESTAMPTZ, Oid.TEXT, Oid.NUMERIC]

MATRIX_ROWS = [
    # narrow widths
    ["t", "1", "2", "3", "1.5", "2.5", "2024-01-02", "03:04:05",
     "2024-01-02 03:04:05", "2024-01-02 03:04:05+00", "x", "1.0"],
    # wide widths (different device width buckets per column)
    ["f", "-32768", "-2147483648", "-9223372036854775808",
     "-1.17549e-38", "-2.2250738585072014e-308", "1999-12-31",
     "23:59:59.999999", "9999-12-31 23:59:59.999999",
     "0001-01-01 00:00:00+15:59", "long text value " * 4,
     "-123456.789012"],
    [None] * 12,
    ["t", "7", "8", "9", "0.0", "-0.0", "2000-02-29", "00:00:00",
     "1970-01-01 00:00:00", "2024-06-01 12:00:00-08", "", "0"],
]


class TestCanonicalByteIdentity:
    """Canonical == exact, proven the way Pallas == XLA is."""

    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    def test_kind_width_matrix(self, engine):
        schema = make_schema(MATRIX_OIDS)
        staged = stage_tuples(
            tuples_from_texts(MATRIX_ROWS * 64), len(MATRIX_OIDS))
        canon, exact = decode_exact_and_canonical(
            schema, staged, device_min_rows=0, mesh=None,
            use_pallas=engine == "pallas")
        assert_batches_identical(canon, exact)

    def test_host_path_matrix(self):
        schema = make_schema(MATRIX_OIDS)
        staged = stage_tuples(
            tuples_from_texts(MATRIX_ROWS * 32), len(MATRIX_OIDS))
        canon, exact = decode_exact_and_canonical(
            schema, staged, device_min_rows=1 << 30, host_min_rows=1,
            mesh=None)
        assert_batches_identical(canon, exact)

    def test_phantom_padding_byte_identity(self):
        # 5 same-(kind, width) columns pad to 6 slots (device specs are
        # data-dependent, so the 5 columns must carry equal-width text
        # to land in one canonical group)
        oids = [Oid.INT4] * 5 + [Oid.INT8]
        schema = make_schema(oids)
        rows = [[str(100 + i % 800), str(100 + (i * 7) % 800),
                 None if i % 5 == 0 else str(200 + i % 700),
                 str(100 + (i * 3) % 800), str(999 - i % 800),
                 None if i % 7 == 0 else str(i * 1000)]
                for i in range(200)]
        staged = stage_tuples(tuples_from_texts(rows), len(oids))
        dec = DeviceDecoder(schema, device_min_rows=0, mesh=None)
        specs = dec._specs(staged, dec._widths(staged))
        plan = program_store.canonical_plan(specs)
        assert plan.phantom_slots, "scenario must actually pad"
        canon, exact = decode_exact_and_canonical(
            schema, staged, device_min_rows=0, mesh=None)
        assert_batches_identical(canon, exact)

    def test_nibble_path_with_phantoms(self):
        # all-nibble kinds (ints/dates) keep the nibble fast path with
        # phantom slots zeroed after the pack
        oids = [Oid.INT4] * 5 + [Oid.DATE]
        schema = make_schema(oids)
        rows = [[str(100 + i), str(101 + i), str(102 + i), str(103 + i),
                 str(104 + i), "2024-03-0%d" % (1 + i % 9)]
                for i in range(100)]
        staged = stage_tuples(tuples_from_texts(rows), len(oids))
        dec = DeviceDecoder(schema, device_min_rows=0, mesh=None)
        packed = dec._pack_stage(
            staged, dec._specs(staged, dec._widths(staged)))
        assert packed.nibble, "scenario must exercise the nibble pack"
        assert packed.plan is not None and packed.plan.phantom_slots
        canon, exact = decode_exact_and_canonical(
            schema, staged, device_min_rows=0, mesh=None)
        assert_batches_identical(canon, exact)

    def test_oracle_fallback_rows_identical(self):
        # oversized-width values (valid via leading zeros) force CPU
        # fixup through the canonical unpack path
        oids = [Oid.INT4, Oid.INT4, Oid.TEXT]
        schema = make_schema(oids)
        rows = [["0" * 57 + str(100 + i), str(i), "v"]
                for i in range(150)]
        staged = stage_tuples(tuples_from_texts(rows), 3)
        canon, exact = decode_exact_and_canonical(
            schema, staged, device_min_rows=0, mesh=None)
        assert_batches_identical(canon, exact)

    def test_mesh_8shard_byte_identity(self):
        """Canonical == exact under an 8-way forced host-platform mesh
        (subprocess: the device count is fixed at backend init)."""
        code = r"""
import numpy as np
from tests.test_program_store import (decode_exact_and_canonical,
                                      assert_batches_identical,
                                      make_schema, tuples_from_texts)
from etl_tpu.ops.staging import stage_tuples
from etl_tpu.models import Oid
from etl_tpu.parallel.mesh import decode_mesh

oids = [Oid.INT4] * 5 + [Oid.INT8, Oid.FLOAT8]
schema = make_schema(oids)
rows = [[str(i), str(i*3), None, "77", str(-i), str(i*1000), "1.5"]
        for i in range(1024)]
staged = stage_tuples(tuples_from_texts(rows), len(oids))
mesh = decode_mesh()
assert mesh is not None and mesh.size == 8
canon, exact = decode_exact_and_canonical(
    schema, staged, device_min_rows=0, mesh=mesh, mesh_min_rows=0)
assert_batches_identical(canon, exact)
print("MESH_CANONICAL_OK")
"""
        repo = Path(__file__).resolve().parent.parent
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=300,
                              cwd=repo, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "MESH_CANONICAL_OK" in proc.stdout


_TEXT_BY_KIND = {
    CellKind.BOOL: lambda i: "t" if i % 2 else "f",
    CellKind.DATE: lambda i: "2024-03-%02d" % (1 + i % 28),
    CellKind.TIME: lambda i: "03:04:%02d" % (i % 60),
    CellKind.TIMESTAMP: lambda i: "2024-01-02 03:04:%02d" % (i % 60),
    CellKind.TIMESTAMPTZ: lambda i: "2024-01-02 03:04:%02d+00" % (i % 60),
    CellKind.NUMERIC: lambda i: "%d.25" % i,
    CellKind.F32: lambda i: "%d.5" % i,
    CellKind.F64: lambda i: "%d.5" % i,
}


def _decode_once(schema, tmp_cache, rows=None):
    """One host-path decode against a configured cache dir; returns the
    batch and the decoder. The canonical host key is evicted from the
    in-process cache FIRST — earlier tests in the suite may share the
    same canonical layout (that sharing is the feature), and these
    tests specifically exercise the compile/persist/load path, so every
    call must behave like a fresh process."""
    program_store.configure(str(tmp_cache))
    oids = [c.type_oid for c in schema.replicated_columns]
    kinds = [c.kind for c in schema.replicated_columns]
    rows = rows or [[_TEXT_BY_KIND.get(k, lambda i: str(i))(i)
                     for k in kinds] for i in range(128)]
    staged = stage_tuples(tuples_from_texts(rows), len(oids))
    dec = DeviceDecoder(schema, device_min_rows=1 << 30, host_min_rows=1,
                        mesh=None)
    _evict_keys([engine_mod._host_fn_key(staged.row_capacity,
                                         dec._host_specs(), None)])
    return dec.decode(staged), dec


def _evict_keys(keys):
    with engine_mod._SHARED_FN_LOCK:
        for k in keys:
            engine_mod._SHARED_FN_CACHE.pop(k, None)


class TestPersistence:
    def test_save_load_roundtrip_zero_compiles(self, tmp_path):
        schema = make_schema([Oid.INT8, Oid.INT4], tid=41)
        b1, dec = _decode_once(schema, tmp_path)
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) > 0
        # simulate a fresh process: evict the compiled program
        _evict_keys(dec._fn_cache)
        c0 = registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL)
        h0 = registry.get_counter(ETL_COMPILE_CACHE_HITS_TOTAL,
                                  {"layer": "disk"})
        b2, _ = _decode_once(schema, tmp_path)
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) == c0, \
            "warm restart must compile ZERO fresh programs"
        assert registry.get_counter(ETL_COMPILE_CACHE_HITS_TOTAL,
                                    {"layer": "disk"}) == h0 + 1
        assert_batches_identical(b1, b2)

    def test_corrupt_file_degrades_to_rebuild(self, tmp_path):
        schema = make_schema([Oid.INT8, Oid.DATE], tid=42)
        b1, dec = _decode_once(schema, tmp_path)
        progs = list(Path(tmp_path).rglob("*.prog"))
        assert progs
        for p in progs:
            p.write_bytes(b"garbage")
        _evict_keys(dec._fn_cache)
        i0 = registry.get_counter(ETL_COMPILE_CACHE_MISSES_TOTAL,
                                  {"reason": "invalid"})
        c0 = registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL)
        b2, _ = _decode_once(schema, tmp_path)
        assert registry.get_counter(ETL_COMPILE_CACHE_MISSES_TOTAL,
                                    {"reason": "invalid"}) > i0
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) > c0
        assert_batches_identical(b1, b2)
        # the rebuild re-persisted a VALID entry
        _evict_keys(dec._fn_cache)
        c1 = registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL)
        _decode_once(schema, tmp_path)
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) == c1

    def test_key_mismatch_treated_as_invalid(self, tmp_path):
        schema = make_schema([Oid.INT8, Oid.INT2], tid=43)
        _, dec = _decode_once(schema, tmp_path)
        key = next(iter(dec._fn_cache))
        path = Path(program_store._path_for(key, str(tmp_path)))
        data = pickle.loads(path.read_bytes())
        data["key"] = "somebody else's key"
        path.write_bytes(pickle.dumps(data))
        assert program_store.try_load(key) is None
        assert not path.exists(), "mismatched entry must be deleted"

    def test_version_tag_invalidation(self, monkeypatch, tmp_path):
        import jaxlib

        t0 = program_store.version_tag()
        # jaxlib upgrade → different tag (old population never read)
        monkeypatch.setattr(jaxlib, "__version__", "99.99.99")
        monkeypatch.setattr(program_store, "_VERSION_TAG", [])
        t1 = program_store.version_tag()
        assert t1 != t0
        # decode-source change → different tag
        monkeypatch.setattr(program_store, "_VERSION_TAG", [])
        monkeypatch.setattr(program_store, "_source_hash",
                            lambda: "feedfacefeedface")
        t2 = program_store.version_tag()
        assert t2 not in (t0, t1)

    def test_fingerprint_stability_and_separation(self):
        key1 = engine_mod._host_fn_key(
            256, DeviceDecoder(make_schema([Oid.INT8]),
                               mesh=None)._host_specs())
        assert program_store.fingerprint(key1) \
            == program_store.fingerprint(key1)
        # mesh fingerprint in the slot separates keys (the PR 8
        # contract, now extended to disk)
        base = (256, key1[1], False, None, False, None, False)
        meshed = (256, key1[1], False, (("sp",), (8,), tuple(range(8))),
                  False, None, False)
        assert program_store.fingerprint(base) \
            != program_store.fingerprint(meshed)

    def test_stable_repr_renders_enums_by_name(self):
        s = program_store._stable_repr(
            (1, (CellKind.I64, 20), None, True, "x"))
        assert "CellKind.I64" in s and "None" in s

    def test_unconfigured_store_never_touches_disk(self, tmp_path):
        program_store.configure(None)
        # no env var in tests → no disk layer: try_load/save are no-ops
        if os.environ.get("ETL_TPU_PROGRAM_CACHE_DIR"):
            pytest.skip("cache dir forced by environment")
        key = ("k",)
        assert program_store.try_load(key) is None
        assert program_store.save(key, object()) is False

    def test_two_process_cache_dir_sharing(self, tmp_path):
        """Two concurrent processes share one dir (atomic writes); a
        third incarnation loads with zero compiles."""
        code = r"""
import sys
from tests.test_program_store import make_schema, _decode_once
from etl_tpu.telemetry.metrics import ETL_PROGRAMS_COMPILED_TOTAL, registry
from etl_tpu.models import Oid

schema = make_schema([Oid.INT8, Oid.TIMESTAMP], tid=44)
_decode_once(schema, sys.argv[1])
print("COMPILED=%d" % registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL))
"""
        repo = Path(__file__).resolve().parent.parent
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen(
            [sys.executable, "-c", code, str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=repo, env=env) for _ in range(2)]
        outs = [p.communicate(timeout=300) for p in procs]
        assert all(p.returncode == 0 for p in procs), outs
        progs = list(Path(tmp_path).rglob("*.prog"))
        assert progs and not list(Path(tmp_path).rglob("*.tmp.*"))
        third = subprocess.run(
            [sys.executable, "-c", code, str(tmp_path)],
            capture_output=True, text=True, timeout=300, cwd=repo, env=env)
        assert third.returncode == 0, third.stderr[-2000:]
        assert "COMPILED=0" in third.stdout


class TestPrewarm:
    def _seed(self, tmp_path, schema):
        program_store.configure(str(tmp_path))
        dec = DeviceDecoder(schema, mesh=None)
        key = engine_mod._host_fn_key(256, dec._host_specs(), None)
        # evict BEFORE seeding: earlier suite tests may share this
        # canonical layout, and a memory-hot key would make the seed a
        # no-op instead of writing the disk entry under test
        _evict_keys([key])
        stats = program_store.warm_host_programs(
            [schema], row_buckets=(256,), wait=True)
        assert stats["layouts"] == 1
        # fresh-process simulation
        _evict_keys([key])
        return key

    def test_host_fn_ready_loads_from_disk(self, tmp_path):
        schema = make_schema([Oid.INT8, Oid.NUMERIC], tid=51)
        key = self._seed(tmp_path, schema)
        dec = DeviceDecoder(schema, mesh=None, nonblocking_compile=True)
        staged = synthetic_staged_batch(2, 256)
        c0 = registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL)
        assert engine_mod._host_fn_ready(dec, staged, dec._host_specs()) \
            is True, "disk-warm key must be READY, not background-compiled"
        assert engine_mod.background_compiles_inflight() == 0
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) == c0
        assert engine_mod._shared_fn_get(key) is not None

    def test_prewarm_pipeline_from_schema_store(self, tmp_path):
        import asyncio

        from etl_tpu.config import BatchConfig
        from etl_tpu.store import NotifyingStore

        schema = make_schema([Oid.INT8, Oid.INT4, Oid.FLOAT8], tid=52)
        key = self._seed(tmp_path, schema)

        async def go():
            store = NotifyingStore()
            await store.store_table_schema(schema, 0)
            return await program_store.prewarm_pipeline(
                store, BatchConfig(program_cache_dir=str(tmp_path),
                                   prewarm_row_buckets=(256,)))

        c0 = registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL)
        stats = asyncio.run(go())
        assert stats == {"layouts": 1, "ready": 1, "building": 0,
                         "observed": 1, "observed_ready": 1,
                         "observed_missing": 0}
        assert registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL) == c0
        assert engine_mod._shared_fn_get(key) is not None

    def test_prewarm_pipeline_empty_store_noop(self, tmp_path):
        import asyncio

        from etl_tpu.config import BatchConfig
        from etl_tpu.store import NotifyingStore

        async def go():
            return await program_store.prewarm_pipeline(
                NotifyingStore(),
                BatchConfig(program_cache_dir=str(tmp_path)))

        assert asyncio.run(go()) == {"layouts": 0, "ready": 0,
                                     "building": 0, "observed": 0,
                                     "observed_ready": 0,
                                     "observed_missing": 0}

    def test_prewarm_auto_disabled_without_cache_dir(self):
        import asyncio

        from etl_tpu.config import BatchConfig
        from etl_tpu.store import NotifyingStore

        async def go():
            return await program_store.prewarm_pipeline(
                NotifyingStore(), BatchConfig())

        assert asyncio.run(go()) == {}

    def test_prewarm_dedupes_canonical_layouts(self, tmp_path):
        """N permuted-column tables warm ONE layout — the compile-storm
        fix for many-table pipelines."""
        program_store.configure(str(tmp_path))
        schemas = [make_schema(o, tid=60 + i) for i, o in enumerate([
            [Oid.INT8, Oid.INT4, Oid.FLOAT8],
            [Oid.FLOAT8, Oid.INT8, Oid.INT4],
            [Oid.INT4, Oid.FLOAT8, Oid.INT8]])]
        stats = program_store.warm_host_programs(
            schemas, row_buckets=(256,), wait=True)
        assert stats["layouts"] == 1
