"""Durable SQL store tests: reference `etl` schema semantics on BOTH
dialects — sqlite (file-backed) and Postgres (over the from-scratch wire
client against the socket-level fake server) — including
cross-process-style restart persistence (reference postgres_store.rs
integration suite)."""

import asyncio

import pytest

from etl_tpu.config import PgConnectionConfig
from etl_tpu.models import (ColumnSchema, Lsn, Oid, ReplicatedTableSchema,
                            RetryKind, TableName, TableSchema)
from etl_tpu.models.errors import EtlError
from etl_tpu.runtime.state import TableState, TableStateType
from etl_tpu.store.base import DestinationTableMetadata
from etl_tpu.store.sql import PostgresStore, SqliteStore


def schema(tid=5):
    return ReplicatedTableSchema.with_all_columns(TableSchema(
        tid, TableName("public", "t"),
        (ColumnSchema("a", Oid.INT4, nullable=False, primary_key_ordinal=1),
         ColumnSchema("b", Oid.TEXT))))


class StoreEnv:
    """Builds stores of one dialect sharing backing storage, so a second
    `make()` models a process restart."""

    def __init__(self, dialect: str, tmp_path):
        self.dialect = dialect
        self.tmp_path = tmp_path
        self._server = None
        self._stores = []

    async def make(self, pipeline_id: int = 1):
        if self.dialect == "sqlite":
            s = SqliteStore(self.tmp_path / "store.db", pipeline_id)
        else:
            if self._server is None:
                from etl_tpu.postgres.fake import FakeDatabase
                from etl_tpu.testing.fake_pg_server import FakePgServer

                self._server = FakePgServer(FakeDatabase())
                await self._server.start()
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1",
                                   port=self._server.port,
                                   name="postgres", username="etl"),
                pipeline_id)
        await s.connect()
        self._stores.append(s)
        return s

    async def cleanup(self):
        for s in self._stores:
            try:
                await s.close()
            except Exception:
                pass
        if self._server is not None:
            await self._server.stop()


DIALECTS = ["sqlite", "postgres"]


@pytest.mark.parametrize("dialect", DIALECTS)
class TestSqlStoreDialects:
    async def test_states_persist_across_restart(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            s1 = await env.make()
            await s1.update_table_state(5, TableState.init())
            await s1.update_table_state(5, TableState.data_sync())
            await s1.update_table_state(
                5, TableState.errored("x", retry_policy=RetryKind.MANUAL,
                                      retry_attempts=2))
            await s1.close()

            s2 = await env.make()
            st = await s2.get_table_state(5)
            assert st.type is TableStateType.ERRORED
            assert st.retry_policy is RetryKind.MANUAL
            assert st.retry_attempts == 2
            # prev-pointer history chain preserved oldest→newest
            hist = await s2.state_history(5)
            assert [h.type for h in hist] == [
                TableStateType.INIT, TableStateType.DATA_SYNC,
                TableStateType.ERRORED]
        finally:
            await env.cleanup()

    async def test_pipeline_isolation(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            a = await env.make(1)
            b = await env.make(2)
            await a.update_table_state(5, TableState.ready())
            assert await b.get_table_state(5) is None
        finally:
            await env.cleanup()

    async def test_memory_only_rejected(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            with pytest.raises(EtlError):
                await s.update_table_state(1, TableState.sync_wait(Lsn(1)))
        finally:
            await env.cleanup()

    async def test_progress_monotonic_and_durable(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            assert await s.update_durable_progress("slot_a", Lsn(100))
            assert not await s.update_durable_progress("slot_a", Lsn(50))
            await s.close()
            s2 = await env.make()
            assert await s2.get_durable_progress("slot_a") == Lsn(100)
            # regression attempt after reload also rejected
            assert not await s2.update_durable_progress("slot_a", Lsn(99))
            await s2.delete_durable_progress("slot_a")
            assert await s2.get_durable_progress("slot_a") is None
        finally:
            await env.cleanup()

    async def test_schema_versions_durable(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            r1 = schema()
            await s.store_table_schema(r1, 0)
            cols2 = r1.table_schema.columns + (ColumnSchema("c", Oid.BOOL),)
            r2 = ReplicatedTableSchema.with_all_columns(
                TableSchema(5, r1.name, cols2))
            await s.store_table_schema(r2, 500)
            await s.close()

            s2 = await env.make()
            assert (await s2.get_table_schema(5, at_snapshot=100)) == r1
            assert (await s2.get_table_schema(5)) == r2
            assert await s2.get_schema_versions(5) == [0, 500]
            assert await s2.prune_schema_versions(5, 600) == 1
            assert await s2.get_schema_versions(5) == [500]
            await s2.close()
            # prune is durable too
            s3 = await env.make()
            assert await s3.get_schema_versions(5) == [500]
        finally:
            await env.cleanup()

    async def test_destination_metadata(self, dialect, tmp_path):
        env = StoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            await s.update_destination_metadata(
                DestinationTableMetadata(5, "public_t", generation=2))
            await s.close()
            s2 = await env.make()
            m = await s2.get_destination_metadata(5)
            assert m.destination_table_name == "public_t" \
                and m.generation == 2
        finally:
            await env.cleanup()

    async def test_state_json_with_quotes_roundtrips(self, dialect, tmp_path):
        """Client-side literal binding must survive quotes in error text
        (the Postgres dialect quotes by doubling)."""
        env = StoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            msg = "it's a 'quoted' failure; DROP TABLE x; --"
            await s.update_table_state(7, TableState.errored(
                msg, retry_policy=RetryKind.MANUAL, retry_attempts=1))
            await s.close()
            s2 = await env.make()
            st = await s2.get_table_state(7)
            assert st.reason == msg
        finally:
            await env.cleanup()


class TestPipelineWithSqliteStore:
    async def test_e2e_with_durable_store(self, tmp_path):
        """Pipeline restart with a durable store: states and progress come
        from disk, copy doesn't re-run."""
        from etl_tpu.destinations import MemoryDestination
        from etl_tpu.models import InsertEvent
        from etl_tpu.postgres.fake import FakeSource
        from etl_tpu.runtime import Pipeline
        from etl_tpu.config import BatchConfig, BatchEngine, PipelineConfig
        from tests.test_pipeline_e2e import ACCOUNTS, make_db, _wait_for

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = MemoryDestination()
        path = tmp_path / "pipeline.db"

        async def run_once(insert_id=None):
            store = SqliteStore(path, 1)
            await store.connect()
            p = Pipeline(
                config=PipelineConfig(
                    pipeline_id=1, publication_name="pub",
                    batch=BatchConfig(max_size_bytes=1 << 20, max_fill_ms=30,
                                      batch_engine=BatchEngine.TPU)),
                store=store, destination=dest,
                source_factory=lambda: FakeSource(db))
            await p.start()
            await _wait_for(lambda: store._states.get(ACCOUNTS) is not None
                            and store._states[ACCOUNTS].type
                            is TableStateType.READY, timeout=15)
            if insert_id is not None:
                async with db.transaction() as tx:
                    tx.insert(ACCOUNTS, [str(insert_id), "d", "0"])
                await _wait_for(lambda: any(
                    isinstance(e, InsertEvent)
                    and e.row.values[0] == insert_id for e in dest.events))
            await p.shutdown_and_wait()
            await store.close()

        await run_once(insert_id=80)
        assert len(dest.table_rows[ACCOUNTS]) == 3
        await run_once(insert_id=81)
        # copy did not re-run; no duplicate CDC for 80
        assert len(dest.table_rows[ACCOUNTS]) == 3
        n80 = sum(1 for e in dest.events
                  if getattr(e, "row", None) and e.row.values[0] == 80)
        assert n80 == 1


class TestExtendedProtocol:
    def test_dollar_conversion(self):
        from etl_tpu.store.sql import to_dollar_params

        assert to_dollar_params("a = ? AND b = ?", 2) == "a = $1 AND b = $2"
        assert to_dollar_params("SELECT '?' , ?", 1) == "SELECT '?' , $1"
        with pytest.raises(EtlError):
            to_dollar_params("a = ?", 2)

    async def test_hostile_params_are_data_not_sql(self, tmp_path):
        """Server-side binding: a value full of quote/comment/statement
        syntax round-trips verbatim on the postgres dialect."""
        env = StoreEnv("postgres", tmp_path)
        try:
            s = await env.make()
            evil = "x'; DROP TABLE etl_replication_state; --\n$1 ' OR '1'='1"
            await s.update_table_state(5, TableState.errored(
                evil, retry_policy=RetryKind.MANUAL, retry_attempts=1))
            await s.close()
            s2 = await env.make()
            st = await s2.get_table_state(5)
            assert st.reason == evil
            # the table the injection tried to drop still answers
            assert (await s2.state_history(5))[-1].reason == evil
        finally:
            await env.cleanup()


class TestLegacySchemaUpgrade:
    async def test_flat_table_state_survives_upgrade(self):
        """Pre-r3 deployments stored durable state in flat etl_* tables in
        the default schema; connect() must migrate it into the etl schema
        (SET SCHEMA + RENAME) rather than restart replication from empty.
        The fake models the DDL as no-ops in its flat sqlite namespace, so
        seeding legacy tables and reading them back through the qualified
        statement set pins the upgrade contract end to end."""
        import sqlite3

        from etl_tpu.models.lsn import Lsn
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.fake_pg_server import FakePgServer

        db = FakeDatabase()
        legacy = sqlite3.connect(":memory:", check_same_thread=False)
        legacy.isolation_level = None
        legacy.executescript("""
CREATE TABLE etl_replication_state (
    id INTEGER PRIMARY KEY, pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL, state TEXT NOT NULL, prev BIGINT,
    is_current INTEGER NOT NULL DEFAULT 1);
CREATE UNIQUE INDEX etl_replication_state_current
    ON etl_replication_state (pipeline_id, table_id) WHERE is_current = 1;
CREATE TABLE etl_replication_progress (
    pipeline_id BIGINT NOT NULL, progress_key TEXT NOT NULL,
    lsn BIGINT NOT NULL, PRIMARY KEY (pipeline_id, progress_key));
INSERT INTO etl_replication_state
    (pipeline_id, table_id, state, prev, is_current)
    VALUES (1, 777, '{"state": "ready"}', NULL, 1);
INSERT INTO etl_replication_progress VALUES (1, 'apply', 4096);
""")
        db._store_sql_db = legacy
        server = FakePgServer(db)
        await server.start()
        try:
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s.connect()
            st = await s.get_table_state(777)
            assert st is not None and st.type.value == "ready"
            assert await s.get_durable_progress("apply") == Lsn(4096)
            await s.close()
        finally:
            await server.stop()


class TestQualifiedNameInBoundValue:
    async def test_literal_containing_qualified_table_name_roundtrips(self):
        """A bound value that happens to contain 'etl.replication_state'
        text (e.g. an error reason quoting a relation) must round-trip
        byte-identical — real Postgres binds server-side and would never
        rewrite it; the fake's flat-name mapping must be quote-aware."""
        from etl_tpu.models.table_state import TableState
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.fake_pg_server import FakePgServer

        db = FakeDatabase()
        server = FakePgServer(db)
        await server.start()
        try:
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s.connect()
            reason = 'relation "etl.replication_state" does not exist'
            await s.update_table_state(5, TableState.errored(reason))
            # restart: the read-back must come from the database, not the
            # in-memory cache
            s2 = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s2.connect()
            st = await s2.get_table_state(5)
            assert st is not None and st.reason == reason
            await s.close()
            await s2.close()
        finally:
            await server.stop()


class TestPostgresPool:
    """The PostgresStore runs a CONNECTION POOL (reference sqlx pool):
    concurrent callers ride separate wire connections, transactions pin
    one connection for their whole BEGIN..COMMIT."""

    async def test_concurrent_writers_use_separate_connections(self):
        from etl_tpu.models.table_state import TableState
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.fake_pg_server import FakePgServer

        server = FakePgServer(FakeDatabase())
        await server.start()
        try:
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s.connect()
            import asyncio

            async def write(i: int) -> None:
                await s.update_table_state(
                    2000 + i, TableState.errored(f"e{i}"))

            async def read(i: int) -> None:
                await s.get_table_state(2000 + (i % 8))

            await asyncio.gather(*(write(i) for i in range(8)),
                                 *(read(i) for i in range(8)))
            # the pool actually opened more than the old single serialized
            # connection (lazy slots connect under contention)
            assert server.connections > 1, server.connections
            # every transaction committed atomically: a fresh store sees
            # all eight states
            s2 = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s2.connect()
            for i in range(8):
                st = await s2.get_table_state(2000 + i)
                assert st is not None and st.reason == f"e{i}"
            await s.close()
            await s2.close()
        finally:
            await server.stop()

    async def test_broken_connection_slot_reconnects(self):
        from etl_tpu.models.table_state import TableState
        from etl_tpu.postgres.fake import FakeDatabase
        from etl_tpu.testing.fake_pg_server import FakePgServer

        server = FakePgServer(FakeDatabase())
        await server.start()
        try:
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1,
                pool_size=1)
            await s.connect()
            await s.update_table_state(1, TableState.errored("before"))
            # sever the server side: the pooled connection is now dead
            for w in list(server._writers):
                w.close()
            import asyncio

            await asyncio.sleep(0.05)
            # first WRITE fails on the dead wire (reads are cache-served),
            # marking the slot broken...
            with pytest.raises(BaseException):
                await s.update_table_state(2, TableState.errored("dead"))
            # ...and the next acquire reconnects the slot transparently
            await s.update_table_state(3, TableState.errored("after"))
            s2 = PostgresStore(
                PgConnectionConfig(host="127.0.0.1", port=server.port,
                                   name="postgres", username="etl"), 1)
            await s2.connect()
            st = await s2.get_table_state(3)
            assert st is not None and st.reason == "after"
            await s.close()
            await s2.close()
        finally:
            await server.stop()
