"""Durable SQL store tests: reference `etl` schema semantics on sqlite,
including cross-process-style restart persistence (reference
postgres_store.rs integration suite)."""

import asyncio

import pytest

from etl_tpu.models import (ColumnSchema, Lsn, Oid, ReplicatedTableSchema,
                            RetryKind, TableName, TableSchema)
from etl_tpu.models.errors import EtlError
from etl_tpu.runtime.state import TableState, TableStateType
from etl_tpu.store.base import DestinationTableMetadata
from etl_tpu.store.sql import SqliteStore


def schema(tid=5):
    return ReplicatedTableSchema.with_all_columns(TableSchema(
        tid, TableName("public", "t"),
        (ColumnSchema("a", Oid.INT4, nullable=False, primary_key_ordinal=1),
         ColumnSchema("b", Oid.TEXT))))


class TestSqliteStore:
    async def test_states_persist_across_restart(self, tmp_path):
        path = tmp_path / "store.db"
        s1 = SqliteStore(path, pipeline_id=1)
        await s1.connect()
        await s1.update_table_state(5, TableState.init())
        await s1.update_table_state(5, TableState.data_sync())
        await s1.update_table_state(
            5, TableState.errored("x", retry_policy=RetryKind.MANUAL,
                                  retry_attempts=2))
        await s1.close()

        s2 = SqliteStore(path, pipeline_id=1)
        await s2.connect()
        st = await s2.get_table_state(5)
        assert st.type is TableStateType.ERRORED
        assert st.retry_policy is RetryKind.MANUAL
        assert st.retry_attempts == 2
        # prev-pointer history chain preserved oldest→newest
        hist = await s2.state_history(5)
        assert [h.type for h in hist] == [
            TableStateType.INIT, TableStateType.DATA_SYNC,
            TableStateType.ERRORED]
        await s2.close()

    async def test_pipeline_isolation(self, tmp_path):
        path = tmp_path / "store.db"
        a = SqliteStore(path, 1)
        b = SqliteStore(path, 2)
        await a.connect()
        await b.connect()
        await a.update_table_state(5, TableState.ready())
        assert await b.get_table_state(5) is None
        await a.close()
        await b.close()

    async def test_memory_only_rejected(self, tmp_path):
        s = SqliteStore(tmp_path / "s.db", 1)
        await s.connect()
        with pytest.raises(EtlError):
            await s.update_table_state(1, TableState.sync_wait(Lsn(1)))
        await s.close()

    async def test_progress_monotonic_and_durable(self, tmp_path):
        path = tmp_path / "store.db"
        s = SqliteStore(path, 1)
        await s.connect()
        assert await s.update_durable_progress("slot_a", Lsn(100))
        assert not await s.update_durable_progress("slot_a", Lsn(50))
        await s.close()
        s2 = SqliteStore(path, 1)
        await s2.connect()
        assert await s2.get_durable_progress("slot_a") == Lsn(100)
        # regression attempt after reload also rejected
        assert not await s2.update_durable_progress("slot_a", Lsn(99))
        await s2.delete_durable_progress("slot_a")
        assert await s2.get_durable_progress("slot_a") is None
        await s2.close()

    async def test_schema_versions_durable(self, tmp_path):
        path = tmp_path / "store.db"
        s = SqliteStore(path, 1)
        await s.connect()
        r1 = schema()
        await s.store_table_schema(r1, 0)
        cols2 = r1.table_schema.columns + (ColumnSchema("c", Oid.BOOL),)
        r2 = ReplicatedTableSchema.with_all_columns(
            TableSchema(5, r1.name, cols2))
        await s.store_table_schema(r2, 500)
        await s.close()

        s2 = SqliteStore(path, 1)
        await s2.connect()
        assert (await s2.get_table_schema(5, at_snapshot=100)) == r1
        assert (await s2.get_table_schema(5)) == r2
        assert await s2.get_schema_versions(5) == [0, 500]
        assert await s2.prune_schema_versions(5, 600) == 1
        assert await s2.get_schema_versions(5) == [500]
        await s2.close()
        # prune is durable too
        s3 = SqliteStore(path, 1)
        await s3.connect()
        assert await s3.get_schema_versions(5) == [500]
        await s3.close()

    async def test_destination_metadata(self, tmp_path):
        path = tmp_path / "store.db"
        s = SqliteStore(path, 1)
        await s.connect()
        await s.update_destination_metadata(
            DestinationTableMetadata(5, "public_t", generation=2))
        await s.close()
        s2 = SqliteStore(path, 1)
        await s2.connect()
        m = await s2.get_destination_metadata(5)
        assert m.destination_table_name == "public_t" and m.generation == 2
        await s2.close()


class TestPipelineWithSqliteStore:
    async def test_e2e_with_durable_store(self, tmp_path):
        """Pipeline restart with a durable store: states and progress come
        from disk, copy doesn't re-run."""
        from etl_tpu.destinations import MemoryDestination
        from etl_tpu.models import InsertEvent
        from etl_tpu.postgres.fake import FakeSource
        from etl_tpu.runtime import Pipeline
        from etl_tpu.config import BatchConfig, BatchEngine, PipelineConfig
        from tests.test_pipeline_e2e import ACCOUNTS, make_db, _wait_for

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = MemoryDestination()
        path = tmp_path / "pipeline.db"

        async def run_once(insert_id=None):
            store = SqliteStore(path, 1)
            await store.connect()
            p = Pipeline(
                config=PipelineConfig(
                    pipeline_id=1, publication_name="pub",
                    batch=BatchConfig(max_size_bytes=1 << 20, max_fill_ms=30,
                                      batch_engine=BatchEngine.TPU)),
                store=store, destination=dest,
                source_factory=lambda: FakeSource(db))
            await p.start()
            await _wait_for(lambda: store._states.get(ACCOUNTS) is not None
                            and store._states[ACCOUNTS].type
                            is TableStateType.READY, timeout=15)
            if insert_id is not None:
                async with db.transaction() as tx:
                    tx.insert(ACCOUNTS, [str(insert_id), "d", "0"])
                await _wait_for(lambda: any(
                    isinstance(e, InsertEvent)
                    and e.row.values[0] == insert_id for e in dest.events))
            await p.shutdown_and_wait()
            await store.close()

        await run_once(insert_id=80)
        assert len(dest.table_rows[ACCOUNTS]) == 3
        await run_once(insert_id=81)
        # copy did not re-run; no duplicate CDC for 80
        assert len(dest.table_rows[ACCOUNTS]) == 3
        n80 = sum(1 for e in dest.events
                  if getattr(e, "row", None) and e.row.values[0] == 80)
        assert n80 == 1
