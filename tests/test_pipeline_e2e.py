"""End-to-end pipeline tests against the in-memory fake walsender.

Mirrors the reference integration strategy (crates/etl/tests/pipeline.rs,
SURVEY §4.2): real pgoutput bytes flow through the full stack — fake
walsender → replication stream → apply loop → decode engine →
MemoryDestination — with notification-driven synchronization (no sleeps).
"""

import asyncio

import pytest

from etl_tpu.config import BatchConfig, BatchEngine, PipelineConfig
from etl_tpu.destinations import (FaultAction, FaultInjectingDestination,
                                  FaultKind, MemoryDestination)
from etl_tpu.models import (ColumnSchema, InsertEvent, DeleteEvent, Lsn, Oid,
                            TableName, TableSchema, UpdateEvent)
from etl_tpu.postgres.fake import FakeDatabase, FakeSource
from etl_tpu.runtime import Pipeline, TableStateType
from etl_tpu.store import MemoryStore, NotifyingStore

ACCOUNTS = 16384
ORDERS = 16385


def make_db() -> FakeDatabase:
    db = FakeDatabase()
    db.create_table(TableSchema(
        ACCOUNTS, TableName("public", "accounts"),
        (ColumnSchema("id", Oid.INT4, nullable=False, primary_key_ordinal=1),
         ColumnSchema("name", Oid.TEXT),
         ColumnSchema("balance", Oid.INT8))),
        rows=[["1", "alice", "100"], ["2", "bob", "-5"], ["3", None, "0"]])
    db.create_table(TableSchema(
        ORDERS, TableName("public", "orders"),
        (ColumnSchema("oid", Oid.INT4, nullable=False, primary_key_ordinal=1),
         ColumnSchema("amount", Oid.NUMERIC))),
        rows=[["10", "9.99"]])
    db.create_publication("pub", [ACCOUNTS, ORDERS])
    return db


def make_pipeline(db, store=None, destination=None, engine=BatchEngine.TPU,
                  batch=None, **cfg):
    config = PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=batch if batch is not None else
        BatchConfig(max_size_bytes=256 * 1024, max_fill_ms=50,
                    batch_engine=engine),
        **cfg)
    store = store if store is not None else NotifyingStore()
    destination = destination if destination is not None else MemoryDestination()
    pipeline = Pipeline(config=config, store=store, destination=destination,
                        source_factory=lambda: FakeSource(db),
                        )
    return pipeline, store, destination


async def wait_ready(store, table_id, timeout=10.0):
    await asyncio.wait_for(store.notify_on(table_id, TableStateType.READY),
                           timeout)


class TestInitialCopyAndCdc:
    async def test_copy_then_ready(self):
        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        rows = {tuple(r.values) for r in dest.table_rows[ACCOUNTS]}
        assert rows == {(1, "alice", 100), (2, "bob", -5), (3, None, 0)}
        from etl_tpu.models import PgNumeric
        assert [tuple(r.values) for r in dest.table_rows[ORDERS]] == \
            [(10, PgNumeric("9.99"))]
        await pipeline.shutdown_and_wait()

    async def test_idle_commit_flushes_before_fill_window(self):
        """Idle-commit fast path: with no write in flight, a commit
        boundary flushes IMMEDIATELY — an idle pipeline must not sit on
        a committed transaction for the whole fill window (here 5s; the
        wait below would time out if the deadline were the trigger)."""
        db = make_db()
        pipeline, store, dest = make_pipeline(
            db, batch=BatchConfig(max_size_bytes=256 * 1024,
                                  max_fill_ms=5000,
                                  batch_engine=BatchEngine.TPU))
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["42", "instant", "1"])
        await asyncio.wait_for(
            _wait_for(lambda: 42 in _account_ids(dest)), 2.0)
        await pipeline.shutdown_and_wait()

    async def test_cdc_after_ready(self):
        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["4", "carol", "7"])
            tx.update(ACCOUNTS, ["1", None, None], ["1", "alice", "150"])
            tx.delete(ACCOUNTS, ["2", None, None])
        # wait for the events to land (batch deadline = 50ms)
        await _wait_for(lambda: len(_row_events(dest)) >= 3)
        evs = _row_events(dest)
        ins = [e for e in evs if isinstance(e, InsertEvent)]
        upd = [e for e in evs if isinstance(e, UpdateEvent)]
        dele = [e for e in evs if isinstance(e, DeleteEvent)]
        assert [tuple(e.row.values) for e in ins] == [(4, "carol", 7)]
        assert [tuple(e.row.values) for e in upd] == [(1, "alice", 150)]
        assert len(dele) == 1 and dele[0].old_row.values[0] == 2
        # ordering matches WAL order
        assert [type(e).__name__ for e in evs] == \
            ["InsertEvent", "UpdateEvent", "DeleteEvent"]
        await pipeline.shutdown_and_wait()

    async def test_rows_during_copy_window_arrive_once(self):
        """Rows committed between pipeline start and catchup arrive exactly
        once (via snapshot copy or CDC catchup, never both)."""
        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        # race: insert while copy likely in flight
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["100", "race", "1"])
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["101", "after", "2"])
        await _wait_for(lambda: _account_ids(dest) >= {100, 101})
        copied = [tuple(r.values) for r in dest.table_rows[ACCOUNTS]]
        cdc = [tuple(e.row.values) for e in _row_events(dest)
               if isinstance(e, InsertEvent) and e.schema.id == ACCOUNTS]
        seen_100 = [v for v in copied + cdc if v[0] == 100]
        assert len(seen_100) == 1, f"row 100 seen {len(seen_100)} times"
        await pipeline.shutdown_and_wait()


def _row_events(dest):
    return [e for e in dest.events
            if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent))]


def _account_ids(dest):
    ids = {r.values[0] for r in dest.table_rows[ACCOUNTS]}
    for e in _row_events(dest):
        if isinstance(e, InsertEvent) and e.schema.id == ACCOUNTS:
            ids.add(e.row.values[0])
    return ids


async def _wait_for(cond, timeout=10.0, interval=0.02):
    async def poll():
        while not cond():
            await asyncio.sleep(interval)

    await asyncio.wait_for(poll(), timeout)


class TestResume:
    async def test_restart_resumes_without_duplicates(self):
        db = make_db()
        store = NotifyingStore()
        dest = MemoryDestination()
        pipeline, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["50", "first", "1"])
        await _wait_for(lambda: 50 in _account_ids(dest))
        await pipeline.shutdown_and_wait()
        n_events_before = len(dest.events)

        # offline WAL while pipeline is down
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["51", "offline", "2"])

        pipeline2, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline2.start()
        await _wait_for(lambda: 51 in _account_ids(dest))
        ins_50 = [e for e in _row_events(dest)
                  if isinstance(e, InsertEvent) and e.row.values[0] == 50]
        assert len(ins_50) == 1, "event 50 re-delivered after restart"
        # copy must not re-run: tables stayed READY
        states = await store.get_table_states()
        assert states[ACCOUNTS].type is TableStateType.READY
        assert dest.dropped_tables == []
        await pipeline2.shutdown_and_wait()


class TestColumnFilters:
    async def test_publication_column_list(self):
        db = make_db()
        db.create_publication("pub", [ACCOUNTS],
                              column_filters={ACCOUNTS: ["id", "balance"]})
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        rows = {tuple(r.values) for r in dest.table_rows[ACCOUNTS]}
        assert rows == {(1, 100), (2, -5), (3, 0)}  # name filtered out
        await pipeline.shutdown_and_wait()


class TestTruncate:
    async def test_truncate_event(self):
        from etl_tpu.models import TruncateEvent

        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.truncate([ACCOUNTS])
        await _wait_for(lambda: any(isinstance(e, TruncateEvent)
                                    for e in dest.events))
        ev = next(e for e in dest.events if isinstance(e, TruncateEvent))
        assert [s.id for s in ev.schemas] == [ACCOUNTS]
        await pipeline.shutdown_and_wait()


class TestEngines:
    @pytest.mark.parametrize("engine", [BatchEngine.CPU, BatchEngine.TPU])
    async def test_both_engines_same_events(self, engine):
        db = make_db()
        pipeline, store, dest = make_pipeline(db, engine=engine)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["7", "x\ty", None])
            tx.insert(ACCOUNTS, ["8", None, "-9223372036854775808"])
        await _wait_for(lambda: len(_row_events(dest)) >= 2)
        vals = [tuple(e.row.values) for e in _row_events(dest)]
        assert vals == [(7, "x\ty", None), (8, None, -9223372036854775808)]
        await pipeline.shutdown_and_wait()

    @pytest.mark.parametrize("engine", [BatchEngine.CPU, BatchEngine.TPU])
    async def test_old_tuple_identity_both_engines(self, engine):
        """PK-changing updates ('K' tuples), identity-full updates/deletes
        ('O' tuples) and key deletes must produce IDENTICAL events on both
        engines (reference codec/event.rs:28-50 old/new merge; VERDICT r1
        item 2: the TPU path previously dropped old-tuple identity)."""
        from etl_tpu.models.table_row import PartialTableRow

        db = make_db()
        pipeline, store, dest = make_pipeline(db, engine=engine)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        db.set_replica_identity(ORDERS, "f")
        async with db.transaction() as tx:
            # PK change 1 → 50: PG sends a 'K' key tuple
            tx.update(ACCOUNTS, ["1", None, None], ["50", "alice", "150"])
            # non-key update: no old tuple at all
            tx.update(ACCOUNTS, ["2", None, None], ["2", "bob", "77"])
            # delete with default identity: 'K' key-only tuple
            tx.delete(ACCOUNTS, ["3", None, None])
            # identity-full table: updates and deletes carry full 'O' rows
            tx.update(ORDERS, ["10", None], ["10", "19.99"])
            tx.delete(ORDERS, ["10", None])
        await _wait_for(lambda: len(_row_events(dest)) >= 5)
        evs = _row_events(dest)
        upd_pk = next(e for e in evs if isinstance(e, UpdateEvent)
                      and e.schema.id == ACCOUNTS and e.row.values[0] == 50)
        assert isinstance(upd_pk.old_row, PartialTableRow)
        assert upd_pk.old_row.values[0] == 1
        assert list(upd_pk.old_row.present) == [True, False, False]

        upd_plain = next(e for e in evs if isinstance(e, UpdateEvent)
                         and e.schema.id == ACCOUNTS and e.row.values[0] == 2)
        assert upd_plain.old_row is None

        del_k = next(e for e in evs if isinstance(e, DeleteEvent)
                     and e.schema.id == ACCOUNTS)
        assert isinstance(del_k.old_row, PartialTableRow)
        assert del_k.old_row.values[0] == 3
        assert list(del_k.old_row.present) == [True, False, False]

        from etl_tpu.models import PgNumeric
        upd_full = next(e for e in evs if isinstance(e, UpdateEvent)
                        and e.schema.id == ORDERS)
        assert type(upd_full.old_row).__name__ == "TableRow"
        assert tuple(upd_full.old_row.values) == (10, PgNumeric("9.99"))

        del_full = next(e for e in evs if isinstance(e, DeleteEvent)
                        and e.schema.id == ORDERS)
        assert type(del_full.old_row).__name__ == "TableRow"
        assert tuple(del_full.old_row.values) == (10, PgNumeric("19.99"))
        await pipeline.shutdown_and_wait()


class TestFaults:
    async def test_copy_reject_then_retry_recovers(self):
        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = FaultInjectingDestination(MemoryDestination())
        dest.script("write_table_rows", FaultAction(FaultKind.REJECT))
        pipeline, store, _ = make_pipeline(
            db, destination=dest,
            table_retry=__import__("etl_tpu.config", fromlist=["RetryConfig"])
            .RetryConfig(max_attempts=5, initial_delay_ms=20))
        await pipeline.start()
        # first copy attempt fails → Errored → timed retry → success
        await asyncio.wait_for(
            store.notify_on(ACCOUNTS, TableStateType.ERRORED), 10.0)
        await wait_ready(store, ACCOUNTS)
        rows = {tuple(r.values) for r in dest.inner.table_rows[ACCOUNTS]}
        assert rows == {(1, "alice", 100), (2, "bob", -5), (3, None, 0)}
        # crash-consistency: the second attempt dropped the half-written table
        assert ACCOUNTS in dest.inner.dropped_tables
        await pipeline.shutdown_and_wait()

    async def test_held_write_defers_durability(self):
        """An Accepted-but-not-durable write must not advance durable
        progress until released (reference async_result.rs semantics)."""
        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        release = asyncio.Event()
        dest = FaultInjectingDestination(MemoryDestination())
        pipeline, store, _ = make_pipeline(db, destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        from etl_tpu.postgres.slots import apply_slot_name

        key = apply_slot_name(1)
        progress_before = await store.get_durable_progress(key)
        dest.script("write_events", FaultAction(FaultKind.HOLD,
                                                release_event=release))
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["60", "held", "0"])
        await _wait_for(lambda: dest.write_events_calls >= 1)
        await asyncio.sleep(0.1)  # give the loop a chance to (wrongly) ack
        progress_held = await store.get_durable_progress(key)
        assert progress_held == progress_before, \
            "durable progress advanced on a non-durable ack"
        release.set()
        await _wait_for(lambda: (asyncio.get_event_loop(),)[0] is not None
                        and True)
        await _wait_for_progress(store, key, progress_before)
        await pipeline.shutdown_and_wait()


async def _wait_for_progress(store, key, above, timeout=10.0):
    async def poll():
        while True:
            p = await store.get_durable_progress(key)
            if p is not None and (above is None or p > above):
                return
            await asyncio.sleep(0.02)

    await asyncio.wait_for(poll(), timeout)


class TestPublicationChanges:
    async def test_unpublished_table_purged(self):
        db = make_db()
        store = NotifyingStore()
        pipeline, _, dest = make_pipeline(db, store=store)
        await pipeline.start()
        await wait_ready(store, ORDERS)
        await pipeline.shutdown_and_wait()
        # drop ORDERS from the publication and restart
        db.create_publication("pub", [ACCOUNTS])
        pipeline2, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline2.start()
        await _wait_for(lambda: True)
        states = await store.get_table_states()
        assert ORDERS not in states
        await pipeline2.shutdown_and_wait()


class TestReviewRegressions:
    async def test_fatal_apply_error_propagates_and_releases_workers(self):
        """A fatal apply-worker error must not leave wait() hanging on
        parked sync workers (reviewed failure: catchup futures only the
        dead apply worker could resolve)."""
        from etl_tpu.config import InvalidatedSlotBehavior, RetryConfig
        db = make_db()
        pipeline, store, dest = make_pipeline(
            db, apply_retry=RetryConfig(max_attempts=1, initial_delay_ms=10))
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        # invalidate the apply slot mid-stream: behavior=ERROR is fatal
        from etl_tpu.postgres.slots import apply_slot_name
        db.invalidate_slot(apply_slot_name(1))
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["70", "x", "1"])
        from etl_tpu.models import ErrorKind, EtlError
        with pytest.raises(EtlError) as ei:
            await asyncio.wait_for(pipeline.wait(), 20)
        assert ErrorKind.SLOT_INVALIDATED in ei.value.kinds()

    async def test_invalidated_slot_resync_drops_destination_tables(self):
        """recreate_and_resync must drop populated destination tables before
        recopying (reviewed failure: reset_table deleted the drop marker)."""
        from etl_tpu.config import InvalidatedSlotBehavior
        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        store = NotifyingStore()
        dest = MemoryDestination()
        pipeline, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await pipeline.shutdown_and_wait()
        assert len(dest.table_rows[ACCOUNTS]) == 3

        from etl_tpu.postgres.slots import apply_slot_name
        db.invalidate_slot(apply_slot_name(1))
        pipeline2, _, _ = make_pipeline(
            db, store=store, destination=dest,
            invalidated_slot_behavior=InvalidatedSlotBehavior.RECREATE_AND_RESYNC)
        reset_seen = store.notify_on(ACCOUNTS, TableStateType.INIT)
        await pipeline2.start()
        await asyncio.wait_for(reset_seen, 20)  # table reset for resync
        await wait_ready(store, ACCOUNTS, timeout=20)
        # no duplicates: table was dropped then recopied
        assert ACCOUNTS in dest.dropped_tables
        assert len(dest.table_rows[ACCOUNTS]) == 3
        await pipeline2.shutdown_and_wait()

    async def test_sync_done_window_events_not_lost(self):
        """Transactions committing after a table's sync-done LSN but before
        its Ready transition must be applied by the apply worker (reviewed
        failure: permanent event loss in the SYNC_DONE window)."""
        from etl_tpu.models.lsn import Lsn
        from etl_tpu.runtime.apply_loop import ApplyContext, ApplyLoop
        from etl_tpu.runtime.state import TableState
        from etl_tpu.config import PipelineConfig

        class StubCoord:
            def table_state(self, tid):
                return TableState.sync_done(Lsn(0x5000))

        loop = ApplyLoop.__new__(ApplyLoop)
        loop.ctx = ApplyContext(progress_key="k", coordination=StubCoord())
        loop._ready_states = {}
        from etl_tpu.runtime.apply_loop import _LoopState
        loop.state = _LoopState()
        # tx committing BEFORE done lsn: sync worker delivered it → skip
        loop.state.current_commit_lsn = Lsn(0x4000)
        assert not await loop._table_owned(ACCOUNTS)
        # tx committing AT/AFTER done lsn: apply worker must own it
        loop.state.current_commit_lsn = Lsn(0x5000)
        assert await loop._table_owned(ACCOUNTS)
        loop.state.current_commit_lsn = Lsn(0x6000)
        assert await loop._table_owned(ACCOUNTS)


class TestSchemaChanges:
    async def test_ddl_message_versions_schema_and_reaches_destination(self):
        """DDL logical messages (the source event-trigger payload) version
        the schema store and flow to the destination
        (reference pipelines_with_schema_changes.rs)."""
        from etl_tpu.models import SchemaChangeEvent
        from etl_tpu.models.schema import ColumnSchema as CS, TableSchema as TS
        from etl_tpu.postgres.codec.event import (DDL_MESSAGE_PREFIX,
                                                  encode_schema_change)

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)

        old = db.tables[ACCOUNTS].schema
        new_schema = TS(ACCOUNTS, old.name, old.columns
                        + (CS("added_col", Oid.TEXT),))
        async with db.transaction() as tx:
            tx.logical_message(DDL_MESSAGE_PREFIX,
                               encode_schema_change(ACCOUNTS, new_schema))
        await _wait_for(lambda: any(isinstance(e, SchemaChangeEvent)
                                    for e in dest.events))
        ev = next(e for e in dest.events if isinstance(e, SchemaChangeEvent))
        assert [c.name for c in ev.new_schema.table_schema.columns][-1] == \
            "added_col"
        # versioned store: old schema still readable below the DDL LSN
        versions = await store.get_schema_versions(ACCOUNTS)
        assert len(versions) == 2
        at_old = await store.get_table_schema(ACCOUNTS,
                                              at_snapshot=versions[0])
        assert len(at_old.table_schema.columns) == 3
        latest = await store.get_table_schema(ACCOUNTS)
        assert len(latest.table_schema.columns) == 4
        await pipeline.shutdown_and_wait()


class TestConnectionChaos:
    async def test_stream_drop_mid_cdc_recovers(self):
        """Severing the replication stream mid-CDC (the NetworkChaos
        analogue, SURVEY §4.8) must retry and deliver everything exactly
        once past the durable watermark."""
        from etl_tpu.config import RetryConfig

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        pipeline, store, dest = make_pipeline(
            db, apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=20))
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["600", "pre-cut", "1"])
        await _wait_for(lambda: 600 in _account_ids(dest))
        await db.sever_streams()
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["601", "post-cut", "2"])
        await _wait_for(lambda: 601 in _account_ids(dest), timeout=20)
        n600 = sum(1 for e in _row_events(dest)
                   if isinstance(e, InsertEvent) and e.row.values[0] == 600)
        assert n600 == 1, "duplicate delivery after reconnect"
        await pipeline.shutdown_and_wait()




class TestBaselineConfig5:
    async def test_multi_table_filters_to_lake(self, tmp_path):
        """BASELINE.json config 5: multi-table parallel sync with PG15
        row/column publication filters into the lake destination."""
        from etl_tpu.destinations.lake import LakeConfig, LakeDestination

        db = make_db()
        db.create_publication(
            "pub", [ACCOUNTS, ORDERS],
            column_filters={ACCOUNTS: ["id", "balance"]},
            # PG15 row filter: only non-negative balances replicate
            row_filters={ACCOUNTS: lambda r: r[2] is not None
                         and not r[2].startswith("-")})
        dest = LakeDestination(LakeConfig(str(tmp_path)))
        pipeline, store, _ = make_pipeline(db, destination=dest,
                                           max_table_sync_workers=2)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["9", "filtered-name", "77"])
            tx.insert(ACCOUNTS, ["10", "negative", "-5"])  # row-filtered out
            tx.insert(ORDERS, ["11", "1.25"])
        await _wait_for(lambda: _lake_has(dest, ACCOUNTS, 9)
                        and _lake_has(dest, ORDERS, 11, key="oid"))
        acc = dest.read_current(ACCOUNTS)
        # column filter applied end to end: only id + balance columns
        assert set(acc.column_names) == {"id", "balance"}
        # row filter: copy drops id=2 (balance -5) and CDC drops id=10
        assert {r["id"] for r in acc.to_pylist()} == {1, 3, 9}
        orders = dest.read_current(ORDERS).to_pylist()
        assert {r["oid"] for r in orders} == {10, 11}
        # numeric survives exactly as text through the lake
        assert [r["amount"] for r in orders if r["oid"] == 11] == ["1.25"]
        await pipeline.shutdown_and_wait()


def _lake_has(dest, tid, key_value, key="id"):
    try:
        return any(r[key] == key_value
                   for r in dest.read_current(tid).to_pylist())
    except Exception:
        return False


class TestBackpressure:
    async def test_pressure_pauses_intake_then_recovers(self):
        """Memory pressure must pause WAL intake (no events land) and the
        hysteresis resume must deliver everything afterwards — VERDICT r1
        item 3: the memory defense wired into the data path."""
        from etl_tpu.config import MemoryBackpressureConfig

        db = make_db()
        pipeline, store, dest = make_pipeline(
            db, backpressure=MemoryBackpressureConfig(
                refresh_interval_ms=10))
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)

        # drive the monitor with a fake RSS: pressure on
        fake_rss = [10**9]
        m = pipeline.memory_monitor
        m.limit_bytes = 10**6
        m._rss_reader = lambda: fake_rss[0]
        for _ in range(100):
            if m.pressure:
                break
            await asyncio.sleep(0.01)
        assert m.pressure

        async with db.transaction() as tx:
            for i in range(50):
                tx.insert(ACCOUNTS, [str(1000 + i), "bulk", str(i)])
        await asyncio.sleep(0.3)
        assert len(_row_events(dest)) == 0, \
            "events delivered while intake should be paused"

        fake_rss[0] = 0  # below resume ratio → hysteresis releases
        await _wait_for(lambda: len(_row_events(dest)) >= 50)
        vals = {e.row.values[0] for e in _row_events(dest)}
        assert vals == {1000 + i for i in range(50)}
        await pipeline.shutdown_and_wait()

    async def test_budget_shrinks_batch_threshold(self):
        """With many active streams the per-stream budget drops below the
        static max_size_bytes (batch_budget.rs:72-96)."""
        from etl_tpu.config import MemoryBackpressureConfig
        from etl_tpu.runtime.backpressure import BatchBudgetController

        ctl = BatchBudgetController(
            MemoryBackpressureConfig(memory_ratio=0.2), max_bytes=8 << 20,
            limit_bytes=100 << 20)
        leases = [ctl.register_stream() for _ in range(10)]
        try:
            # 100MiB × 0.2 / 10 = 2MiB < 8MiB cap
            assert leases[0].ideal_batch_bytes() == 2 << 20
        finally:
            for l in leases:
                l.release()


class TestSchemaCleanupTask:
    async def test_old_versions_pruned_in_background(self):
        """The background cleanup prunes schema versions below the durable
        LSN (reference hourly task apply.rs:123,423-631; VERDICT r1 item 9:
        prune_schema_versions previously had no caller)."""
        from etl_tpu.models.schema import ColumnSchema as CS, TableSchema as TS
        from etl_tpu.postgres.codec.event import (DDL_MESSAGE_PREFIX,
                                                  encode_schema_change)

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        pipeline, store, dest = make_pipeline(
            db, schema_cleanup_interval_s=0.15)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        old = db.tables[ACCOUNTS].schema
        new_schema = TS(ACCOUNTS, old.name, old.columns
                        + (CS("extra", Oid.TEXT),))
        db.tables[ACCOUNTS].schema = new_schema  # the ALTER itself
        async with db.transaction() as tx:
            tx.logical_message(DDL_MESSAGE_PREFIX,
                               encode_schema_change(ACCOUNTS, new_schema))
            tx.insert(ACCOUNTS, ["70", "after-ddl", "1", "x"])
        await _wait_for(lambda: 70 in _account_ids(dest))
        assert len(await store.get_schema_versions(ACCOUNTS)) == 2
        # a later commit pushes durable past the DDL; cleanup then prunes
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["71", "later", "2", "y"])
        await _wait_for(lambda: 71 in _account_ids(dest))

        async def pruned():
            return len(await store.get_schema_versions(ACCOUNTS)) == 1
        for _ in range(100):
            if await pruned():
                break
            await asyncio.sleep(0.05)
        assert await pruned(), "old schema version was not pruned"
        versions = await store.get_schema_versions(ACCOUNTS)
        sch = await store.get_table_schema(ACCOUNTS, at_snapshot=versions[0])
        assert len(sch.table_schema.columns) == 4  # the NEW schema survives
        await pipeline.shutdown_and_wait()


class TestObservabilityLoop:
    async def test_lag_gauges_and_egress_recorded(self):
        """All four lag gauges get set (two by status updates, two by the
        out-of-band sampler) and durable acks record egress bytes —
        VERDICT r1 item 8: these were defined but never set/called."""
        from etl_tpu.telemetry.metrics import (
            ETL_APPLY_LOOP_EFFECTIVE_FLUSH_LAG_BYTES,
            ETL_APPLY_LOOP_END_TO_END_LAG_BYTES,
            ETL_APPLY_LOOP_FLUSH_LAG_BYTES,
            ETL_APPLY_LOOP_RECEIVED_LAG_BYTES,
            ETL_PROCESSED_BYTES_TOTAL, LABEL_DESTINATION, LABEL_PIPELINE_ID,
            registry)

        labels = {LABEL_PIPELINE_ID: "1",
                  LABEL_DESTINATION: "MemoryDestination"}
        egress_before = registry.get_counter(ETL_PROCESSED_BYTES_TOTAL,
                                             labels)
        db = make_db()
        pipeline, store, dest = make_pipeline(db, lag_sample_interval_s=0.05)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["80", "egress", "1"])
        await _wait_for(lambda: 80 in _account_ids(dest))
        # copy egress (table_copy) + CDC egress (streaming) both recorded
        await _wait_for(lambda: registry.get_counter(
            ETL_PROCESSED_BYTES_TOTAL, labels) > egress_before)
        # sampler gauges appear within a few ticks
        await _wait_for(lambda: registry.get_gauge(
            ETL_APPLY_LOOP_END_TO_END_LAG_BYTES) is not None)
        assert registry.get_gauge(
            ETL_APPLY_LOOP_EFFECTIVE_FLUSH_LAG_BYTES) is not None
        assert registry.get_gauge(ETL_APPLY_LOOP_FLUSH_LAG_BYTES) is not None
        assert registry.get_gauge(
            ETL_APPLY_LOOP_RECEIVED_LAG_BYTES) is not None
        await pipeline.shutdown_and_wait()


class TestSourceMigrations:
    async def test_trigger_installed_and_alter_flows_through_wal(self):
        """Pipeline start installs the DDL event trigger (source
        migrations); a plain ALTER TABLE then emits the supabase_etl_ddl
        message through the WAL — the INSTALLED path, not a hand-crafted
        logical message (VERDICT r1 item 5)."""
        from etl_tpu.models import SchemaChangeEvent
        from etl_tpu.models.schema import ColumnSchema as CS, TableSchema as TS

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        assert not db.ddl_trigger_installed
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        assert db.ddl_trigger_installed, "source migrations did not run"
        assert db.applied_migrations, "migration name not recorded"
        await wait_ready(store, ACCOUNTS)

        old = db.tables[ACCOUNTS].schema
        new_schema = TS(ACCOUNTS, old.name, old.columns
                        + (CS("added", Oid.TEXT),))
        async with db.transaction() as tx:
            tx.alter_table(ACCOUNTS, new_schema)
            tx.insert(ACCOUNTS, ["90", "post-ddl", "1", "v"])
        await _wait_for(lambda: 90 in _account_ids(dest))
        ev = next(e for e in dest.events if isinstance(e, SchemaChangeEvent))
        assert [c.name for c in ev.new_schema.table_schema.columns][-1] == \
            "added"
        assert len(await store.get_schema_versions(ACCOUNTS)) == 2
        await pipeline.shutdown_and_wait()

    async def test_migrations_idempotent_across_restarts(self):
        db = make_db()
        p1, store, dest = make_pipeline(db)
        await p1.start()
        await wait_ready(store, ACCOUNTS)
        await p1.shutdown_and_wait()
        n = len(db.applied_migrations)
        p2, _, _ = make_pipeline(db, store=store, destination=dest)
        await p2.start()
        assert len(db.applied_migrations) == n, "migrations re-applied"
        await p2.shutdown_and_wait()

    async def test_skippable_via_config(self):
        db = make_db()
        pipeline, store, dest = make_pipeline(db,
                                              run_source_migrations=False)
        await pipeline.start()
        assert not db.ddl_trigger_installed
        await wait_ready(store, ACCOUNTS)
        await pipeline.shutdown_and_wait()


class TestReadReplica:
    async def test_standby_skips_migrations_but_replicates(self):
        """Against a standby: migrations are skipped (DDL is impossible
        there; they replicate from the primary) and the pipeline still
        copies + streams (reference pipeline_read_replica.rs)."""
        db = make_db()
        db.is_standby = True
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        assert not db.ddl_trigger_installed
        assert db.applied_migrations == []
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["95", "standby", "2"])
        await _wait_for(lambda: 95 in _account_ids(dest))
        await pipeline.shutdown_and_wait()

    async def test_standby_trigger_presence_via_primary(self):
        """If the PRIMARY installed the trigger (replicated to the
        standby), DDL messages still flow when decoding on the standby."""
        from etl_tpu.models import SchemaChangeEvent
        from etl_tpu.models.schema import ColumnSchema as CS, TableSchema as TS

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        db.ddl_trigger_installed = True  # replicated from primary
        db.is_standby = True
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        old = db.tables[ACCOUNTS].schema
        async with db.transaction() as tx:
            tx.alter_table(ACCOUNTS, TS(ACCOUNTS, old.name, old.columns
                                        + (CS("x", Oid.TEXT),)))
        await _wait_for(lambda: any(isinstance(e, SchemaChangeEvent)
                                    for e in dest.events))
        await pipeline.shutdown_and_wait()


    async def test_slots_live_on_replica_not_primary(self):
        """Reference pipeline_read_replica.rs:294-297: in read-replica
        mode ETL's logical slots are created on the REPLICA; the primary
        owns none. CDC still flows: primary writes replay to the standby
        and stream from there."""
        primary = make_db()
        replica = primary.make_replica()
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        assert replica.slots, "logical slots must exist on the replica"
        assert not primary.slots, "primary must own no logical slots"
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["96", "from-primary", "1"])
        await _wait_for(lambda: 96 in _account_ids(dest))
        await pipeline.shutdown_and_wait()

    async def test_stream_lags_until_standby_replays(self):
        """The replica's walsender only serves WAL the standby has
        REPLAYED: a primary commit is invisible to the pipeline until
        replay catches up (wait_for_read_replica_replay semantics)."""
        primary = make_db()
        replica = primary.make_replica()
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        replica.auto_replay = False
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["97", "lagged", "2"])
        await asyncio.sleep(0.3)
        assert 97 not in _account_ids(dest), \
            "un-replayed WAL must not reach the pipeline"
        await replica.replay()
        await _wait_for(lambda: 97 in _account_ids(dest))
        await pipeline.shutdown_and_wait()

    async def test_slot_creation_waits_for_standby_snapshot(self):
        """PG16 logical slot creation on a standby blocks until the
        primary logs a standby snapshot; the reference drives this with
        wait_with_standby_snapshots (pipeline_read_replica.rs:141-159)."""
        primary = make_db()
        replica = primary.make_replica(snapshot_gate=True)
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await asyncio.sleep(0.3)
        assert not replica.slots, \
            "slot creation must block until the standby snapshot"
        await primary.log_standby_snapshot()
        await wait_ready(store, ACCOUNTS)
        assert replica.slots
        await pipeline.shutdown_and_wait()

    async def test_standby_rejects_writes(self):
        primary = make_db()
        replica = primary.make_replica()
        with pytest.raises(AssertionError, match="standby"):
            replica.transaction()

    async def test_promotion_mid_stream_continues_cdc(self):
        """pg_promote() while the pipeline streams from the replica:
        logical slots survive promotion (PG16+), so CDC continues from
        the promoted node's own WAL with no re-copy and no duplicates —
        the pre-promotion event set is delivered exactly once."""
        primary = make_db()
        replica = primary.make_replica()
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["80", "pre-promotion", "1"])
        await _wait_for(lambda: 80 in _account_ids(dest))
        await replica.promote()
        # the promoted node now accepts writes directly
        async with replica.transaction() as tx:
            tx.insert(ACCOUNTS, ["81", "post-promotion", "2"])
        await _wait_for(lambda: 81 in _account_ids(dest))
        assert replica.slots, "slots must survive promotion"
        ids = [e.row.values[0] for e in _row_events(dest)
               if isinstance(e, InsertEvent)]
        assert ids.count(80) == 1 and ids.count(81) == 1, ids
        await pipeline.shutdown_and_wait()

    async def test_promotion_detaches_from_old_primary(self):
        """After promotion the old primary's writes must NOT reach the
        pipeline — the promoted node no longer replays (a split-brain
        leak would double-apply on failback)."""
        primary = make_db()
        replica = primary.make_replica()
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await replica.promote()
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["82", "orphaned", "1"])
        await asyncio.sleep(0.3)
        assert 82 not in _account_ids(dest), \
            "old-primary WAL must not leak into a promoted replica"
        await pipeline.shutdown_and_wait()

    async def test_disconnect_during_stream_from_standby_no_dupes(self):
        """Severing the replica's walsender connections mid-stream
        (NetworkChaos partition analogue) must recover exactly-once:
        the apply worker reconnects from durable progress and the
        destination sees each committed row once."""
        primary = make_db()
        replica = primary.make_replica()
        pipeline, store, dest = make_pipeline(replica)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["83", "before-cut", "1"])
        await _wait_for(lambda: 83 in _account_ids(dest))
        await replica.sever_streams()
        async with primary.transaction() as tx:
            tx.insert(ACCOUNTS, ["84", "after-cut", "2"])
        await _wait_for(lambda: 84 in _account_ids(dest), timeout=15)
        ids = [e.row.values[0] for e in _row_events(dest)
               if isinstance(e, InsertEvent)]
        assert ids.count(83) == 1 and ids.count(84) == 1, ids
        await pipeline.shutdown_and_wait()

    async def test_slot_invalidation_on_standby_recreate_and_resync(self):
        """A replica-owned slot invalidated by the standby (hot-standby
        feedback lapse / max_slot_wal_keep_size) with
        recreate_and_resync: tables reset, destination tables dropped
        and recopied from the replica — same policy as on a primary
        (apply_worker.rs Error/Recreate semantics)."""
        from etl_tpu.config import InvalidatedSlotBehavior
        from etl_tpu.postgres.slots import apply_slot_name

        primary = make_db()
        replica = primary.make_replica()
        store = NotifyingStore()
        dest = MemoryDestination()
        pipeline, _, _ = make_pipeline(replica, store=store,
                                       destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await pipeline.shutdown_and_wait()
        replica.invalidate_slot(apply_slot_name(1))
        pipeline2, _, _ = make_pipeline(
            replica, store=store, destination=dest,
            invalidated_slot_behavior=(
                InvalidatedSlotBehavior.RECREATE_AND_RESYNC))
        reset_seen = store.notify_on(ACCOUNTS, TableStateType.INIT)
        await pipeline2.start()
        await asyncio.wait_for(reset_seen, 20)  # table reset for resync
        await wait_ready(store, ACCOUNTS, timeout=20)
        assert ACCOUNTS in dest.dropped_tables
        rows = {tuple(r.values) for r in dest.table_rows[ACCOUNTS]}
        assert rows == {(1, "alice", 100), (2, "bob", -5), (3, None, 0)}
        await pipeline2.shutdown_and_wait()

    async def test_idle_keepalive_advances_slot_past_unpublished_wal(self):
        """Reference pipeline_read_replica.rs:313: with only UNPUBLISHED /
        keepalive WAL flowing, the slot's confirmed_flush must advance to
        the received position (effective flush LSN, apply.rs:891-912) —
        otherwise an idle pipeline pins the replica's WAL retention —
        while durable ETL progress stays at the commit-boundary floor."""
        from etl_tpu.postgres.slots import apply_slot_name

        db = make_db()
        db.is_standby = True
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        slot_name = apply_slot_name(1)
        durable_before = await store.get_durable_progress(slot_name)
        # WAL advances with nothing published: physical-only records; the
        # stream sees keepalives carrying the new position, no frames
        db.next_lsn(4096)
        target = db.current_lsn
        slot = db.slots[slot_name]
        await _wait_for(lambda: slot.confirmed_flush >= target)
        # idle-only advances are NOT persisted as durable progress
        assert await store.get_durable_progress(slot_name) == durable_before
        await pipeline.shutdown_and_wait()

    async def test_empty_commit_window_advances_durable_progress(self):
        """TPU engine: commits are not assembler events, so a committed
        transaction whose owned-row set is EMPTY (here: rows for a table
        outside the publication's owned set) must still clear the commit
        boundary and advance durable progress — a regression here pins
        the slot's confirmed_flush and _is_idle() forever."""
        from etl_tpu.postgres.slots import apply_slot_name

        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        slot_name = apply_slot_name(1)
        # an EMPTY transaction: Begin + Commit, zero row messages
        tx = db.transaction()
        await tx.commit()
        target = db.current_lsn
        slot = db.slots[slot_name]
        # the commit boundary must become durable (persisted progress,
        # not just an idle-keepalive advance) and the slot must follow
        await _wait_for(lambda: slot.confirmed_flush >= target)
        for _ in range(200):
            durable = await store.get_durable_progress(slot_name)
            if durable is not None and durable >= target:
                break
            await asyncio.sleep(0.02)
        assert durable is not None and durable >= target, (durable, target)
        await pipeline.shutdown_and_wait()

    async def test_open_transaction_blocks_idle_flush_advance(self):
        """Safety inverse: while a transaction is OPEN mid-stream, status
        updates must keep reporting the durable floor — advancing to the
        received LSN would let the server discard WAL that is not yet
        durably applied (apply.rs is_idle, :885-889)."""
        from etl_tpu.postgres.codec import pgoutput as pg
        from etl_tpu.postgres.slots import apply_slot_name

        db = make_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        await wait_ready(store, ORDERS)
        slot_name = apply_slot_name(1)
        # hand-feed a BEGIN with no COMMIT: transaction stays open
        commit_at = int(db.current_lsn) + 64 * 8
        await db.append_wal(pg.encode_begin(commit_at, 1_700_000_000_000_000,
                                            777))
        await asyncio.sleep(0.3)  # several keepalive periods
        db.next_lsn(4096)
        target = db.current_lsn
        await asyncio.sleep(0.3)
        slot = db.slots[slot_name]
        assert slot.confirmed_flush < target, \
            "open transaction must pin the reported flush LSN"
        await pipeline.shutdown_and_wait()


PART_ROOT = 17000
PART_L1 = 17001
PART_L2 = 17002


def make_partitioned_db(n1=150, n2=70):
    db = FakeDatabase()
    parent = TableSchema(
        PART_ROOT, TableName("public", "events_part"),
        (ColumnSchema("id", Oid.INT4, nullable=False, primary_key_ordinal=1),
         ColumnSchema("region", Oid.TEXT)))
    db.create_partitioned_table(parent, {
        PART_L1: ("events_part_a",
                  [[str(i), "us"] for i in range(1, n1 + 1)]),
        PART_L2: ("events_part_b",
                  [[str(1000 + i), "eu"] for i in range(1, n2 + 1)]),
    })
    db.create_publication("pub", [PART_ROOT])
    return db


class TestPartitionedTables:
    async def test_copy_resolves_leaves_and_cdc_maps_to_root(self):
        """A published partitioned root: initial copy resolves and copies
        every leaf (per-leaf CTID planning, reference copy.rs:457-547);
        leaf row changes stream under the ROOT's relid
        (publish_via_partition_root), so the destination sees one table
        (reference pipeline_with_partitioned_table.rs)."""
        db = make_partitioned_db()
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, PART_ROOT)
        rows = {r.values[0] for r in dest.table_rows[PART_ROOT]}
        assert len(rows) == 220  # both leaves copied
        assert 1 in rows and 1001 in rows
        assert PART_L1 not in dest.table_rows  # no per-leaf tables

        # CDC into a leaf arrives under the root
        async with db.transaction() as tx:
            tx.insert(PART_L1, ["500", "us"])
            tx.insert(PART_L2, ["1500", "eu"])
        await _wait_for(lambda: sum(
            1 for e in _row_events(dest)
            if isinstance(e, InsertEvent) and e.schema.id == PART_ROOT) >= 2)
        evs = [e for e in _row_events(dest) if isinstance(e, InsertEvent)]
        assert {e.row.values[0] for e in evs} == {500, 1500}
        assert all(e.schema.id == PART_ROOT for e in evs)
        await pipeline.shutdown_and_wait()


class TestRowFiltersOnCopy:
    async def test_row_filter_applies_to_snapshot_copy(self):
        """PG15 publication row filters must gate the initial COPY, not
        just CDC (VERDICT r1 item 7: the real-source copy previously
        ignored them). The fake carries the SQL text the wire client
        appends to its COPY (transaction.rs:868)."""
        db = make_db()
        db.create_publication(
            "pub", [ACCOUNTS],
            row_filters={ACCOUNTS: ("balance >= 0",
                                    lambda r: r[2] is not None
                                    and int(r[2]) >= 0)})
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        rows = {tuple(r.values) for r in dest.table_rows[ACCOUNTS]}
        # bob (-5) excluded by the filter at copy time
        assert rows == {(1, "alice", 100), (3, None, 0)}
        await pipeline.shutdown_and_wait()


class TestHugeTransaction:
    async def test_bulk_transaction_splits_batches_durable_at_commit(self):
        """A single transaction far above max_size_bytes must flow through
        multiple mid-transaction flushes (carried commit accounting) with
        durable progress advancing ONLY at the commit boundary — the
        memory-defense path for bulk UPDATEs (apply.rs:1932-1945)."""
        from etl_tpu.postgres.slots import apply_slot_name

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        inner = MemoryDestination()
        dest = FaultInjectingDestination(inner)  # counts write calls
        store = NotifyingStore()
        config = PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_size_bytes=8 * 1024, max_fill_ms=30,
                              batch_engine=BatchEngine.TPU))
        pipeline = Pipeline(config=config, store=store, destination=dest,
                            source_factory=lambda: FakeSource(db))
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        key = apply_slot_name(1)
        progress_before = await store.get_durable_progress(key) or Lsn(0)

        n = 3000  # ~100KB of payloads >> 8KB batch cap
        async with db.transaction() as tx:
            for i in range(n):
                tx.insert(ACCOUNTS, [str(5000 + i), "bulk" * 4, str(i)])
        await _wait_for(lambda: sum(
            1 for e in _row_events(inner)
            if isinstance(e, InsertEvent)) >= n, timeout=30)
        # the transaction split across multiple writes (with an instant
        # destination the loop drains the backlog into the next batch
        # while one write is in flight, so exactly-2 is the floor;
        # slower destinations + the memory monitor bound the buildup)
        assert dest.write_events_calls >= 2
        ids = [e.row.values[0] for e in _row_events(inner)
               if isinstance(e, InsertEvent)]
        assert len(ids) == n and len(set(ids)) == n  # exactly once
        # durable progress moved past the tx commit (destination delivery
        # precedes the progress write, so wait on the store)
        await _wait_for_progress(store, key, progress_before)
        await pipeline.shutdown_and_wait()


class TestToastThroughPipeline:
    async def test_unchanged_toast_preserved_in_lake(self, tmp_path):
        """Full pipeline: an UPDATE whose TOASTed column is unchanged (no
        old image, default replica identity) must NOT null the stored
        value at the lake — the column-wise PATCH path end to end
        (ADVICE r1 high, pipeline-level coverage)."""
        from etl_tpu.destinations.lake import LakeConfig, LakeDestination
        from etl_tpu.postgres.fake import TOAST_UNCHANGED_VALUE

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = LakeDestination(LakeConfig(warehouse_path=str(tmp_path)))
        store = NotifyingStore()
        pipeline, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        big = "toasted-" + "x" * 500
        async with db.transaction() as tx:
            tx.insert(ACCOUNTS, ["70", big, "5"])
        async with db.transaction() as tx:
            # balance changes; the big TOASTed name is unchanged → 'u' kind
            tx.update(ACCOUNTS, ["70", None, None],
                      ["70", TOAST_UNCHANGED_VALUE, "6"])

        async def settled():
            recs = {r["id"]: r for r in dest.read_current(ACCOUNTS).to_pylist()}
            return recs.get(70, {}).get("balance") == 6 and recs

        for _ in range(300):
            recs = await settled() or {}
            if recs:
                break
            await asyncio.sleep(0.02)
        assert recs, "update never landed"
        assert recs[70]["name"] == big, "unchanged TOAST column was lost"
        await pipeline.shutdown_and_wait()

    async def test_toast_sentinel_reaches_destination_intact(self):
        """The TOAST sentinel must REACH the destination (never be
        silently nulled upstream) — destinations then decide: patch
        (lake) or typed error (full-row upserters)."""
        from etl_tpu.models.cell import TOAST_UNCHANGED
        from etl_tpu.postgres.fake import TOAST_UNCHANGED_VALUE

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        pipeline, store, dest = make_pipeline(db)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            tx.update(ACCOUNTS, ["1", None, None],
                      ["1", TOAST_UNCHANGED_VALUE, "200"])
        await _wait_for(lambda: any(
            isinstance(e, UpdateEvent) and e.row.values[2] == 200
            for e in dest.events))
        ev = next(e for e in dest.events
                  if isinstance(e, UpdateEvent) and e.row.values[2] == 200)
        assert ev.row.values[1] is TOAST_UNCHANGED
        await pipeline.shutdown_and_wait()

    async def test_identity_changing_toast_errors_typed_at_lake(
            self, tmp_path):
        """An identity-CHANGING update with an unchanged-TOAST column is
        unreconstructable even for the patching lake — the worker must
        surface the typed replica-identity error, never null the value
        (reference bigquery_update_new_row stance)."""
        from etl_tpu.destinations.lake import LakeConfig, LakeDestination
        from etl_tpu.postgres.fake import TOAST_UNCHANGED_VALUE

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = LakeDestination(LakeConfig(warehouse_path=str(tmp_path)))
        store = NotifyingStore()
        pipeline, _, _ = make_pipeline(db, store=store, destination=dest)
        await pipeline.start()
        await wait_ready(store, ACCOUNTS)
        async with db.transaction() as tx:
            # PK 1 → 90 with an unchanged TOASTed name: 'K' old tuple,
            # no old image for the name → cannot be patched
            tx.update(ACCOUNTS, ["1", None, None],
                      ["90", TOAST_UNCHANGED_VALUE, "7"])
        # the apply worker fails permanently with the typed error
        # (MANUAL directive) — pipeline.wait surfaces it
        from etl_tpu.models.errors import ErrorKind, EtlError

        try:
            with pytest.raises(EtlError) as ei:
                await asyncio.wait_for(pipeline.wait(), timeout=20)
            assert ErrorKind.SOURCE_REPLICA_IDENTITY in ei.value.kinds()
        finally:
            await pipeline.shutdown()


class TestRestartMidTransaction:
    async def test_restart_during_split_transaction_no_dupes_in_lake(
            self, tmp_path):
        """Shutdown lands between mid-transaction flushes of a huge
        transaction; restart re-streams from the last durable COMMIT
        (progress never advances mid-tx). At-least-once re-delivery with
        shifted batch boundaries must still collapse to a correct
        _current view in the lake (identity+sequence collapse makes
        duplicate upserts idempotent)."""
        from etl_tpu.destinations.lake import LakeConfig, LakeDestination

        db = make_db()
        db.create_publication("pub", [ACCOUNTS])
        dest = LakeDestination(LakeConfig(warehouse_path=str(tmp_path)))
        store = NotifyingStore()
        config = PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_size_bytes=4 * 1024, max_fill_ms=20,
                              batch_engine=BatchEngine.TPU))
        p1 = Pipeline(config=config, store=store, destination=dest,
                      source_factory=lambda: FakeSource(db))
        await p1.start()
        await wait_ready(store, ACCOUNTS)

        n = 1200
        async with db.transaction() as tx:
            for i in range(n):
                tx.insert(ACCOUNTS, [str(7000 + i), "r" * 30, str(i)])
        # shut down QUICKLY — likely mid-delivery of the split transaction
        await asyncio.sleep(0.05)
        await p1.shutdown_and_wait()

        p2 = Pipeline(config=config, store=store, destination=dest,
                      source_factory=lambda: FakeSource(db))
        await p2.start()

        async def complete():
            recs = dest.read_current(ACCOUNTS).to_pylist()
            ids = {r["id"] for r in recs}
            return ids >= {7000 + i for i in range(n)} and recs

        recs = None
        for _ in range(600):
            recs = await complete()
            if recs:
                break
            await asyncio.sleep(0.05)
        assert recs, "rows missing after restart"
        by_id = {}
        for r in recs:
            by_id.setdefault(r["id"], []).append(r)
        dupes = {k: v for k, v in by_id.items() if len(v) > 1}
        assert not dupes, f"duplicate identities in _current: {list(dupes)[:5]}"
        await p2.shutdown_and_wait()
