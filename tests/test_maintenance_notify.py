"""Maintenance binary + error webhook tests."""

import asyncio
import json
import logging

import pytest

from etl_tpu.destinations.lake import LakeConfig, LakeDestination
from etl_tpu.maintenance import run_maintenance
from etl_tpu.telemetry.notify import WebhookErrorNotifier
from etl_tpu.telemetry.tracing import set_error_hook
from etl_tpu.testing.fake_http import RecordingHttpServer
from tests.test_destinations import TID, batch, ins, make_schema


class TestMaintenance:
    async def test_compact_and_vacuum(self, tmp_path):
        d = LakeDestination(LakeConfig(str(tmp_path), compact_min_files=99))
        await d.startup()
        await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
        for i in range(3):
            await d.write_events([ins(0, [10 + i, "x", None],
                                      lsn=0x100 + 16 * i)])
        # truncate bumps the generation, leaving old-generation files
        from etl_tpu.models import Lsn, TruncateEvent

        await d.write_events([TruncateEvent(Lsn(1), Lsn(1), 0, 0,
                                            (make_schema(),))])
        await d.write_events([ins(0, [50, "post", None], lsn=0x500)])
        await d.shutdown()

        out = await run_maintenance(str(tmp_path), vacuum=True,
                                    api_url=None, pipeline_id=None,
                                    tenant_id=None)
        assert out["tables"] == 1
        assert out["vacuumed_files"] >= 4  # old generation cleaned
        reader = LakeDestination(LakeConfig(str(tmp_path)))
        await reader.startup()
        assert [r["id"] for r in reader.read_current(TID).to_pylist()] == [50]
        await reader.shutdown()

    async def test_pause_resume_via_api(self, tmp_path):
        server = RecordingHttpServer()
        await server.start()
        # maintenance polls /status until the pipeline is fully stopped
        # before touching the lake (pause-coordination race fix)
        server.responders.append(
            lambda r: (200, {"state": "stopped"})
            if r.path.endswith("/status") else None)
        try:
            d = LakeDestination(LakeConfig(str(tmp_path)))
            await d.startup()
            await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
            await d.shutdown()
            await run_maintenance(str(tmp_path), vacuum=False,
                                  api_url=server.url(), pipeline_id=7,
                                  tenant_id="acme")
            paths = server.paths()
            assert paths[0] == "POST /v1/pipelines/7/stop"
            assert paths[-1] == "POST /v1/pipelines/7/start"
        finally:
            await server.stop()

    async def test_api_key_sent_as_bearer(self, tmp_path):
        """A secured control plane rejects unauthenticated /v1 calls with
        401; coordination must carry the bearer token on every call
        (ADVICE r2)."""
        server = RecordingHttpServer()
        await server.start()
        server.responders.append(
            lambda r: (200, {"state": "stopped"})
            if r.path.endswith("/status") else None)
        try:
            d = LakeDestination(LakeConfig(str(tmp_path)))
            await d.startup()
            await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
            await d.shutdown()
            await run_maintenance(str(tmp_path), vacuum=False,
                                  api_url=server.url(), pipeline_id=7,
                                  tenant_id="acme", api_key="sekrit")
            assert server.requests
            for req in server.requests:
                assert req.headers.get("Authorization") == "Bearer sekrit"
        finally:
            await server.stop()


class TestWebhookNotifier:
    async def test_error_posts_webhook(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            n = WebhookErrorNotifier(server.url() + "/hook", pipeline_id=3,
                                     min_interval_s=0)
            n.install()
            logging.getLogger("etl_tpu.test").error("boom %s", "now")
            for _ in range(100):
                if server.requests:
                    break
                await asyncio.sleep(0.02)
            assert server.requests, "webhook never fired"
            doc = server.requests[0].json
            assert doc["pipeline_id"] == 3
            assert doc["message"] == "boom now"
            await n.close()
        finally:
            set_error_hook(lambda r: None)
            await server.stop()

    async def test_rate_limited(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            n = WebhookErrorNotifier(server.url(), min_interval_s=60)
            n.install()
            for _ in range(5):
                logging.getLogger("etl_tpu.test").error("burst")
            await asyncio.sleep(0.2)
            assert len(server.requests) == 1  # only the first within window
            await n.close()
        finally:
            set_error_hook(lambda r: None)
            await server.stop()


class TestMaintenancePausePoll:
    async def test_aborts_if_never_stopped(self, tmp_path):
        """If the pipeline never reaches 'stopped', maintenance must abort
        rather than compact under a live writer."""
        server = RecordingHttpServer()
        await server.start()
        server.responders.append(
            lambda r: (200, {"state": "stopping"})
            if r.path.endswith("/status") else None)
        try:
            with pytest.raises(RuntimeError, match="did not reach 'stopped'"):
                await run_maintenance(str(tmp_path), vacuum=False,
                                      api_url=server.url(), pipeline_id=7,
                                      tenant_id="acme", stop_timeout_s=0.3)
            # the abort must still resume the (successfully paused)
            # pipeline — otherwise replication stays down on timeout
            assert server.paths()[-1] == "POST /v1/pipelines/7/start"
        finally:
            await server.stop()


class TestMaintenancePolicyAndHistory:
    async def test_policy_skips_small_tables_and_records_history(
            self, tmp_path):
        d = LakeDestination(LakeConfig(str(tmp_path), compact_min_files=100))
        await d.startup()
        # one table with 3 CDC files, one with 1
        await d.write_events([ins(0, [1, "a", None])])
        await d.write_events([ins(1, [2, "b", None])])
        await d.write_events([ins(2, [3, "c", None])])
        await d.shutdown()
        out = await run_maintenance(str(tmp_path), vacuum=True,
                                    api_url=None, pipeline_id=None,
                                    tenant_id=None, min_cdc_files=2)
        assert out["compacted_files"] >= 3
        assert out["skipped_by_policy"] == 0
        hist = out["history"]
        assert hist and hist[0]["operation"] in ("vacuum", "compact")
        assert all(h["outcome"] in ("ok", "skipped") for h in hist)
        # run again: now a single base file → policy skips compaction
        out2 = await run_maintenance(str(tmp_path), vacuum=False,
                                     api_url=None, pipeline_id=None,
                                     tenant_id=None, min_cdc_files=2)
        assert out2["compacted_files"] == 0
        assert out2["skipped_by_policy"] == 1


class TestMaintenanceCoordination:
    """External-maintenance coordination through the catalog store
    (reference etl-maintenance coordination.rs: operation requests,
    pause lease with max-pause, per-operation cooldowns, history)."""

    def make_parts(self, tmp_path, **policy_kw):
        from etl_tpu.maintenance_coordination import (
            CatalogMaintenanceStore, MaintenanceController,
            MaintenancePolicy, ReplicatorMaintenanceAgent)

        lake = LakeDestination(LakeConfig(str(tmp_path),
                                          compact_min_files=99))
        policy = MaintenancePolicy(**policy_kw)
        store = CatalogMaintenanceStore(str(tmp_path), 1)
        pauses = []
        agent = ReplicatorMaintenanceAgent(
            store, policy,
            pause=lambda: pauses.append("pause"),
            resume=lambda: pauses.append("resume"))
        ctrl = MaintenanceController(store, lake, policy)
        return lake, store, agent, ctrl, pauses

    async def seed(self, lake, n_cdc=3):
        await lake.startup()
        await lake.write_table_rows(make_schema(),
                                    batch([[1, "a", None]]))
        for i in range(n_cdc):
            await lake.write_events([ins(0, [10 + i, "x", None],
                                         lsn=0x100 + 16 * i)])

    async def test_request_pause_execute_history_cycle(self, tmp_path):
        lake, store, agent, ctrl, pauses = self.make_parts(
            tmp_path, merge_min_cdc_files=2, request_cooldown_seconds=0.0)
        await self.seed(lake)
        # replicator samples → posts a merge request
        state = agent.tick()
        assert state.request_operations.merge_adjacent_files
        assert not state.request_operations.inline_flush
        # controller takes the lease and runs; a background agent tick
        # honors the pause so the controller sees replicator_paused
        async def keep_ticking():
            for _ in range(100):
                agent.tick()
                await asyncio.sleep(0.01)

        tick_task = asyncio.ensure_future(keep_ticking())
        report = await ctrl.run_once(wait_for_pause_s=2.0)
        tick_task.cancel()
        assert report["replicator_paused"] is True
        assert report["operations"]["merge_adjacent_files"] >= 2
        assert pauses[0] == "pause"
        # lease cleared → next tick resumes the replicator
        agent.tick()
        assert pauses[-1] == "resume"
        st = store.load()
        assert st.pause_run_id is None
        assert st.last_completed_at is not None
        assert "merge_adjacent_files" in st.last_successful
        # request consumed
        assert not st.request_operations.merge_adjacent_files
        store.close()
        await lake.shutdown()

    async def test_operation_cooldown_skips_repeat_runs(self, tmp_path):
        lake, store, agent, ctrl, _ = self.make_parts(
            tmp_path, merge_min_cdc_files=2,
            request_cooldown_seconds=3600.0)
        await self.seed(lake)
        agent.tick()
        report = await ctrl.run_once(wait_for_pause_s=0.0)
        assert "merge_adjacent_files" in report["operations"]
        # more CDC files arrive; the request re-posts but the operation
        # is cooling down → the controller skips it
        await lake.write_events([ins(0, [90, "y", None], lsn=0x900)])
        await lake.write_events([ins(0, [91, "z", None], lsn=0x910)])
        # force a fresh request despite the request cooldown window
        def reset_request(st):
            st.request_at = None
            st.request_operations.merge_adjacent_files = True

        store.mutate(reset_request)
        report2 = await ctrl.run_once(wait_for_pause_s=0.0)
        assert report2.get("skipped", "").startswith("no operations")
        store.close()
        await lake.shutdown()

    async def test_pause_lease_expiry_self_resumes(self, tmp_path):
        """If the controller dies mid-run the replicator must resume on
        lease expiry (max_pause), not stay paused forever."""
        import time as _t

        lake, store, agent, ctrl, pauses = self.make_parts(
            tmp_path, max_pause_seconds=1000.0)
        await self.seed(lake, n_cdc=0)
        now = _t.time()

        def dead_controller(st):
            st.pause_run_id = "dead"
            st.pause_requested_at = now - 2000.0  # lease long expired
            st.pause_max_pause_s = 1000.0

        store.mutate(dead_controller)
        agent.tick()
        assert agent.paused is False
        assert pauses == []  # expired lease never pauses
        # a LIVE lease pauses...
        def live_controller(st):
            st.pause_run_id = "live"
            st.pause_requested_at = _t.time()

        store.mutate(live_controller)
        agent.tick()
        assert agent.paused is True
        store.close()
        await lake.shutdown()

    async def test_inline_flush_requested_by_bytes_threshold(self, tmp_path):
        from etl_tpu.maintenance_coordination import (
            CatalogMaintenanceStore, MaintenanceController,
            MaintenancePolicy, ReplicatorMaintenanceAgent)

        lake = LakeDestination(LakeConfig(
            str(tmp_path), compact_min_files=99, inline_max_bytes=1 << 20,
            inline_flush_bytes=1 << 30))
        await lake.startup()
        await lake.write_table_rows(make_schema(), batch([[1, "a", None]]))
        for i in range(3):
            await lake.write_events([ins(0, [20 + i, "inline", None],
                                         lsn=0x200 + 16 * i)])
        assert lake.pending_inline_bytes(TID) > 0
        policy = MaintenancePolicy(inline_flush_min_inlined_bytes=1,
                                   request_cooldown_seconds=0.0)
        store = CatalogMaintenanceStore(str(tmp_path), 1)
        agent = ReplicatorMaintenanceAgent(store, policy)
        ctrl = MaintenanceController(store, lake, policy)
        st = agent.tick()
        assert st.request_operations.inline_flush
        report = await ctrl.run_once(wait_for_pause_s=0.0)
        assert report["operations"]["inline_flush"] == 3
        assert lake.pending_inline_bytes(TID) == 0
        store.close()
        await lake.shutdown()

    async def test_monitor_external_pause_composes_with_memory(self):
        from etl_tpu.config.pipeline import MemoryBackpressureConfig
        from etl_tpu.runtime.backpressure import MemoryMonitor

        rss = {"v": 0}
        mon = MemoryMonitor(MemoryBackpressureConfig(),
                            limit_bytes=100, rss_reader=lambda: rss["v"])
        mon.set_external_pause(True)
        assert mon.pressure is True
        # memory pressure rises while externally paused
        rss["v"] = 100
        mon.sample_once()
        assert mon.pressure is True
        # external pause lifts but memory still high → stays paused
        mon.set_external_pause(False)
        assert mon.pressure is True
        rss["v"] = 0
        mon.sample_once()
        assert mon.pressure is False

    async def test_two_controllers_cannot_both_take_the_lease(self, tmp_path):
        lake, store, agent, ctrl, _ = self.make_parts(
            tmp_path, merge_min_cdc_files=2, request_cooldown_seconds=0.0)
        await self.seed(lake)
        agent.tick()
        from etl_tpu.maintenance_coordination import MaintenanceController

        ctrl2 = MaintenanceController(store, lake, ctrl.policy)
        r1, r2 = await asyncio.gather(
            ctrl.run_once(wait_for_pause_s=0.0),
            ctrl2.run_once(wait_for_pause_s=0.0))
        ran = [r for r in (r1, r2) if "operations" in r]
        skipped = [r for r in (r1, r2) if "skipped" in r]
        assert len(ran) == 1 and len(skipped) == 1
        assert skipped[0]["skipped"].startswith("run already active") or \
            skipped[0]["skipped"].startswith("no operations")
        store.close()
        await lake.shutdown()

    async def test_operator_vacuum_runs_without_request(self, tmp_path):
        """--vacuum maps to cleanup_old_files_enabled: operator-driven,
        selected even though no replicator ever requests it."""
        from etl_tpu.models import Lsn, TruncateEvent

        lake, store, _, _, _ = self.make_parts(tmp_path)
        await self.seed(lake)
        # truncate supersedes the old generation → vacuumable files
        await lake.write_events([TruncateEvent(Lsn(0x800), Lsn(0x800), 0,
                                               0, (make_schema(),))])
        from etl_tpu.maintenance_coordination import (MaintenanceController,
                                                      MaintenancePolicy)

        ctrl = MaintenanceController(
            store, lake,
            MaintenancePolicy(cleanup_old_files_enabled=True))
        report = await ctrl.run_once(wait_for_pause_s=0.0)
        assert report["operations"]["cleanup_old_files"] >= 1
        st = store.load()
        assert "cleanup_old_files" in st.last_successful
        store.close()
        await lake.shutdown()

    async def test_stale_request_cleared_when_need_vanished(self, tmp_path):
        """A posted merge request whose CDC files were since compacted
        away must be consumed without pausing the pipeline."""
        lake, store, agent, ctrl, _ = self.make_parts(
            tmp_path, merge_min_cdc_files=2, request_cooldown_seconds=0.0)
        await self.seed(lake)
        agent.tick()  # posts merge request
        await lake.compact(TID)  # need vanishes out-of-band
        report = await ctrl.run_once(wait_for_pause_s=0.0)
        assert report["skipped"].startswith("no operations")
        st = store.load()
        assert not st.request_operations.merge_adjacent_files
        assert st.pause_run_id is None  # never paused
        store.close()
        await lake.shutdown()

    async def test_agent_tick_runs_on_worker_thread(self, tmp_path):
        """The production agent ticks via asyncio.to_thread while the
        pipeline's lake connection lives on the loop thread — sampling
        must ride the store's own thread-safe connection (reviewed
        failure: sqlite ProgrammingError made coordination silently
        dead)."""
        lake, store, agent, _, _ = self.make_parts(
            tmp_path, merge_min_cdc_files=2, request_cooldown_seconds=0.0)
        await self.seed(lake)
        state = await asyncio.to_thread(agent.tick)
        assert state.request_operations.merge_adjacent_files
        store.close()
        await lake.shutdown()
