"""Maintenance binary + error webhook tests."""

import asyncio
import json
import logging

import pytest

from etl_tpu.destinations.lake import LakeConfig, LakeDestination
from etl_tpu.maintenance import run_maintenance
from etl_tpu.telemetry.notify import WebhookErrorNotifier
from etl_tpu.telemetry.tracing import set_error_hook
from etl_tpu.testing.fake_http import RecordingHttpServer
from tests.test_destinations import TID, batch, ins, make_schema


class TestMaintenance:
    async def test_compact_and_vacuum(self, tmp_path):
        d = LakeDestination(LakeConfig(str(tmp_path), compact_min_files=99))
        await d.startup()
        await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
        for i in range(3):
            await d.write_events([ins(0, [10 + i, "x", None],
                                      lsn=0x100 + 16 * i)])
        # truncate bumps the generation, leaving old-generation files
        from etl_tpu.models import Lsn, TruncateEvent

        await d.write_events([TruncateEvent(Lsn(1), Lsn(1), 0, 0,
                                            (make_schema(),))])
        await d.write_events([ins(0, [50, "post", None], lsn=0x500)])
        await d.shutdown()

        out = await run_maintenance(str(tmp_path), vacuum=True,
                                    api_url=None, pipeline_id=None,
                                    tenant_id=None)
        assert out["tables"] == 1
        assert out["vacuumed_files"] >= 4  # old generation cleaned
        reader = LakeDestination(LakeConfig(str(tmp_path)))
        await reader.startup()
        assert [r["id"] for r in reader.read_current(TID).to_pylist()] == [50]
        await reader.shutdown()

    async def test_pause_resume_via_api(self, tmp_path):
        server = RecordingHttpServer()
        await server.start()
        # maintenance polls /status until the pipeline is fully stopped
        # before touching the lake (pause-coordination race fix)
        server.responders.append(
            lambda r: (200, {"state": "stopped"})
            if r.path.endswith("/status") else None)
        try:
            d = LakeDestination(LakeConfig(str(tmp_path)))
            await d.startup()
            await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
            await d.shutdown()
            await run_maintenance(str(tmp_path), vacuum=False,
                                  api_url=server.url(), pipeline_id=7,
                                  tenant_id="acme")
            paths = server.paths()
            assert paths[0] == "POST /v1/pipelines/7/stop"
            assert paths[-1] == "POST /v1/pipelines/7/start"
        finally:
            await server.stop()

    async def test_api_key_sent_as_bearer(self, tmp_path):
        """A secured control plane rejects unauthenticated /v1 calls with
        401; coordination must carry the bearer token on every call
        (ADVICE r2)."""
        server = RecordingHttpServer()
        await server.start()
        server.responders.append(
            lambda r: (200, {"state": "stopped"})
            if r.path.endswith("/status") else None)
        try:
            d = LakeDestination(LakeConfig(str(tmp_path)))
            await d.startup()
            await d.write_table_rows(make_schema(), batch([[1, "a", None]]))
            await d.shutdown()
            await run_maintenance(str(tmp_path), vacuum=False,
                                  api_url=server.url(), pipeline_id=7,
                                  tenant_id="acme", api_key="sekrit")
            assert server.requests
            for req in server.requests:
                assert req.headers.get("Authorization") == "Bearer sekrit"
        finally:
            await server.stop()


class TestWebhookNotifier:
    async def test_error_posts_webhook(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            n = WebhookErrorNotifier(server.url() + "/hook", pipeline_id=3,
                                     min_interval_s=0)
            n.install()
            logging.getLogger("etl_tpu.test").error("boom %s", "now")
            for _ in range(100):
                if server.requests:
                    break
                await asyncio.sleep(0.02)
            assert server.requests, "webhook never fired"
            doc = server.requests[0].json
            assert doc["pipeline_id"] == 3
            assert doc["message"] == "boom now"
            await n.close()
        finally:
            set_error_hook(lambda r: None)
            await server.stop()

    async def test_rate_limited(self):
        server = RecordingHttpServer()
        await server.start()
        try:
            n = WebhookErrorNotifier(server.url(), min_interval_s=60)
            n.install()
            for _ in range(5):
                logging.getLogger("etl_tpu.test").error("burst")
            await asyncio.sleep(0.2)
            assert len(server.requests) == 1  # only the first within window
            await n.close()
        finally:
            set_error_hook(lambda r: None)
            await server.stop()


class TestMaintenancePausePoll:
    async def test_aborts_if_never_stopped(self, tmp_path):
        """If the pipeline never reaches 'stopped', maintenance must abort
        rather than compact under a live writer."""
        server = RecordingHttpServer()
        await server.start()
        server.responders.append(
            lambda r: (200, {"state": "stopping"})
            if r.path.endswith("/status") else None)
        try:
            with pytest.raises(RuntimeError, match="did not reach 'stopped'"):
                await run_maintenance(str(tmp_path), vacuum=False,
                                      api_url=server.url(), pipeline_id=7,
                                      tenant_id="acme", stop_timeout_s=0.3)
            # the abort must still resume the (successfully paused)
            # pipeline — otherwise replication stays down on timeout
            assert server.paths()[-1] == "POST /v1/pipelines/7/start"
        finally:
            await server.stop()


class TestMaintenancePolicyAndHistory:
    async def test_policy_skips_small_tables_and_records_history(
            self, tmp_path):
        d = LakeDestination(LakeConfig(str(tmp_path), compact_min_files=100))
        await d.startup()
        # one table with 3 CDC files, one with 1
        await d.write_events([ins(0, [1, "a", None])])
        await d.write_events([ins(1, [2, "b", None])])
        await d.write_events([ins(2, [3, "c", None])])
        await d.shutdown()
        out = await run_maintenance(str(tmp_path), vacuum=True,
                                    api_url=None, pipeline_id=None,
                                    tenant_id=None, min_cdc_files=2)
        assert out["compacted_files"] >= 3
        assert out["skipped_by_policy"] == 0
        hist = out["history"]
        assert hist and hist[0]["operation"] in ("vacuum", "compact")
        assert all(h["outcome"] in ("ok", "skipped") for h in hist)
        # run again: now a single base file → policy skips compaction
        out2 = await run_maintenance(str(tmp_path), vacuum=False,
                                     api_url=None, pipeline_id=None,
                                     tenant_id=None, min_cdc_files=2)
        assert out2["compacted_files"] == 0
        assert out2["skipped_by_policy"] == 1
