# etl-lint fixture: clean shard-scoped reads — everything goes through
# the shard view's filtered read (owned_table_states), a single-table
# lookup, or a read carrying an explicit filter argument; an unfiltered
# full-list read OUTSIDE any @shard_scoped function is also fine (the
# unsharded runtime owns the whole publication by definition).
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import shard_scoped


@shard_scoped
async def respawn_owned_sync_workers(scoped_store, pool):
    states = await scoped_store.owned_table_states()
    for tid in states:
        await pool.ensure_worker(tid)


@shard_scoped
async def check_one_table(scoped_store, tid):
    return await scoped_store.get_table_state(tid)


@shard_scoped
async def filtered_read(store, shard_map, shard):
    # an explicit filter argument makes the read shard-aware
    return await store.get_table_states(shard=shard)


async def unsharded_refresh(store):
    return await store.get_table_states()
