# etl-lint fixture: unfiltered full-table-list store reads inside
# @shard_scoped functions (etl_tpu/sharding) — against a SHARED store
# `get_table_states()` returns EVERY shard's tables, and acting on the
# full list re-copies / re-owns / purges tables a sibling pod owns.
# Nested defs and lambdas inherit the frame flag.
# expect: cross-shard-table-access=4
from etl_tpu.analysis.annotations import shard_scoped


@shard_scoped
async def respawn_sync_workers(store, pool):
    states = await store.get_table_states()  # flagged: every shard's tables
    for tid in states:
        await pool.ensure_worker(tid)


@shard_scoped
async def purge_departed(store, published):
    for tid in set(await store.get_table_states()) - published:  # flagged
        await store.purge_table(tid)


@shard_scoped
def make_state_reader(store):
    async def read_all():
        return await store.get_table_states()  # nested def: flagged

    return read_all


@shard_scoped
def gauge_provider(store):
    return lambda: store.get_table_states()  # lambda inherits: flagged
