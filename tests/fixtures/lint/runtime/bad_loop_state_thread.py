# expect: loop-state-from-thread=1
"""Worker-thread code scheduling onto the event loop through a
non-thread-safe surface: asyncio documents `call_soon` (and friends)
as loop-affine; the crossing must be `call_soon_threadsafe`."""

import threading


class Notifier:
    def __init__(self, loop):
        self._loop = loop
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self):
        self._loop.call_soon(self._wake)  # corrupts loop internals

    def _wake(self):
        pass
