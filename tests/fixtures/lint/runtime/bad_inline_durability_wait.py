# etl-lint fixture: bare `await ack.wait_durable()` inside a
# @flush_path function (runtime/ack_window.py owns durability waits):
# an inline wait re-serializes the pipeline to one ack round-trip per
# batch — the exact ceiling the bounded write window removes. Nested
# defs and lambdas inherit the frame flag (the flush submit closures).
# expect: inline-durability-wait=3
from etl_tpu.analysis.annotations import flush_path


@flush_path
async def flush_one_batch(destination, events):
    ack = await destination.write_event_batches(events)
    await ack.wait_durable()  # flagged: the window owns this wait
    return len(events)


@flush_path
async def copy_chunk_barrier(destination, schema, batch):
    ack = await destination.write_table_batch(schema, batch)

    async def barrier():
        # nested def inherits the flush-path flag: flagged
        await ack.wait_durable()

    await barrier()


@flush_path
def make_waiter(ack):
    return lambda: ack.wait_durable()  # lambda inherits: flagged
