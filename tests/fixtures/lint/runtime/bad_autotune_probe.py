# etl-lint fixture: the pre-fix engine.py:340 pattern the round-5
# advisor caught — the jit-compiling autotune probe (and other device
# sync points) running synchronously inside the asyncio apply loop at
# first-decoder construction. Regression guard for device-sync-in-async.
# expect: device-sync-in-async=3
import numpy as np

from etl_tpu.ops import autotune


class Sealer:
    async def seal_run(self, device_value):
        # resolve_device_min_rows -> measure(): jit compile + 2x8 MiB
        # device round trips, all on the event loop
        rows = autotune.resolve_device_min_rows(4, 36.0, 16384)
        host = np.asarray(device_value)
        device_value.block_until_ready()
        return rows, host
