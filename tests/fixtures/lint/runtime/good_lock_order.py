# Consistent global order (state before flush, everywhere) — including
# through a call: the interprocedural pass sees the same order on both
# paths and stays quiet.
import asyncio

STATE_LOCK = asyncio.Lock()
FLUSH_LOCK = asyncio.Lock()


async def apply_path(events):
    async with STATE_LOCK:
        async with FLUSH_LOCK:
            return len(events)


async def shutdown_path():
    async with STATE_LOCK:
        return await _drain()


async def _drain():
    async with FLUSH_LOCK:
        return True
