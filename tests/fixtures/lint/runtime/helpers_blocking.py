# Clean in isolation: a sync module-level helper may sleep — the bug is
# CALLING it from the event loop (bad_transitive_blocking.py's entry).
# The lexical rule can't see through the call; the interprocedural pass
# anchors its finding in the CALLER's file, so this one expects zero.
import time


def do_backoff(attempt: int) -> None:
    time.sleep(0.1 * attempt)


def fetch_config(path: str) -> str:
    with open(path) as f:
        return f.read()
