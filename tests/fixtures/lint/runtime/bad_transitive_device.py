# expect: device-sync-in-async=1
# Cross-DIRECTORY chain: an event-loop coroutine in runtime/ reaches a
# definite device sync (jax.device_get) through an ops/ helper. The
# lexical rule only sees the helper call.
from ..ops.helpers_device import fetch_all


async def drain_results(pending):
    return fetch_all(pending)
