# expect: lock-held-across-await=3
# Foreign awaitables under a held lock: every other waiter queues behind
# an await that has nothing to do with the locked resource. The sync
# threading.Lock case is worse — the mutex blocks the whole loop.
import asyncio
import threading

RETRY_GATE = asyncio.Lock()


class BatchWriter:
    def __init__(self, queue):
        self._lock = asyncio.Lock()
        self._mu = threading.Lock()
        self._queue = queue

    async def flush_with_sleep(self):
        async with self._lock:
            await asyncio.sleep(0.5)  # backoff while serialized

    async def sync_mutex_across_await(self, destination):
        with self._mu:
            await destination.flush()


async def module_lock_foreign_wait(destination):
    async with RETRY_GATE:
        await destination.flush()
