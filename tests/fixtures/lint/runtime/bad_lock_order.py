# expect: lock-order-inversion=1
# state_lock -> flush_lock on the apply path, flush_lock -> state_lock
# on the shutdown path: two tasks interleaving these deadlock. One
# finding per unordered pair, carrying both witness chains.
import asyncio

STATE_LOCK = asyncio.Lock()
FLUSH_LOCK = asyncio.Lock()


async def apply_path(events):
    async with STATE_LOCK:
        async with FLUSH_LOCK:
            return len(events)


async def shutdown_path():
    async with FLUSH_LOCK:
        async with STATE_LOCK:
            return True
