# expect: unbounded-await=3
"""Rule 8 positives: bare parking awaits with no timeout and no
shutdown race — a dead producer wedges the worker silently."""

import asyncio


async def consume(queue: asyncio.Queue):
    # a producer that crashed never puts again: this await never returns
    item = await queue.get()
    return item


async def wait_for_flush(flushed: asyncio.Event):
    await flushed.wait()


class Worker:
    def __init__(self):
        self.done_event = asyncio.Event()

    async def join(self):
        # attribute-chain receiver: still a bare park
        await self.done_event.wait()
