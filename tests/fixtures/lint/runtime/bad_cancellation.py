# etl-lint fixture: handlers that eat CancelledError, and a broad
# runtime/ except that never re-raises.
# expect: cancellation-swallow=2
import asyncio


async def swallows_cancel(task):
    try:
        await task
    except asyncio.CancelledError:
        return None


async def hides_failures(op):
    try:
        return await op()
    except Exception:
        return None
