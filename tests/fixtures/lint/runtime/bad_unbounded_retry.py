# expect: unbounded-retry=8
"""Positive fixture: `while True` retry loops that swallow exceptions and
spin again with no backoff — the connect storm shape."""

import asyncio


async def connect_storm(source):
    while True:
        try:
            return await source.connect()
        except ConnectionError:
            pass  # spins at CPU speed against a down server


def sync_variant(op):
    while True:
        try:
            return op()
        except OSError:
            continue


def db_hammer(cursor, sql):
    # a bare `.execute` is a DB call, NOT RetryPolicy.execute backoff
    while True:
        try:
            return cursor.execute(sql)
        except OSError:
            continue


def outer_backoff_does_not_absolve_inner_spin(op):
    import time

    # the OUTER loop's sleep paces only the outer region; the inner
    # while-True hammers op() at CPU speed and is reported on its own
    while True:
        time.sleep(60)
        while True:
            try:
                return op()
            except OSError:
                continue


def break_only_exits_inner_for(op, items):
    # the break leaves the for loop, not the retry loop — still a spin
    while True:
        try:
            return op()
        except OSError:
            for _ in items:
                break


def handler_def_never_raises_here(op):
    # the raise lives in a def the handler merely DEFINES — it does not
    # exit the retry loop
    while True:
        try:
            return op()
        except OSError:
            def cb():
                raise


def break_in_handler_of_inner_loop_try(op, conns):
    # the try sits inside the for: the handler's break exits the FOR,
    # and the retry loop spins on
    while True:
        for conn in conns:
            try:
                return op(conn)
            except OSError:
                break


def nested_sleep_does_not_pace(op):
    # the sleep lives in a nested def the loop never calls — it must not
    # suppress the finding
    while True:
        def later():
            import time

            time.sleep(1)

        try:
            return op()
        except OSError:
            continue
