# etl-lint fixture: task handles discarded at statement level, or born
# inside a callback lambda whose return value every caller throws away —
# the loop keeps only a weak ref, so GC may cancel them mid-flight.
# expect: orphaned-task=3
import asyncio
import signal


async def fire_and_forget(coro, loop):
    asyncio.create_task(coro)
    loop.create_task(coro)


def install_handler(loop, shutdown):
    loop.add_signal_handler(
        signal.SIGTERM, lambda: asyncio.ensure_future(shutdown()))
