# The sanctioned own-resource idiom (docs/CONCURRENCY.md): a lock may be
# held across awaits ON THE RESOURCE IT SERIALIZES — the owner's own
# connection/channel, including locals derived from self and wait_for
# wrappers. Mirrors PostgresStore._txn and the snowflake per-table locks.
import asyncio


class Store:
    def __init__(self, conn):
        self._lock = asyncio.Lock()
        self._conn = conn

    async def execute(self, sql):
        async with self._lock:
            return await self._conn.execute(sql)

    async def txn(self, statements):
        async with self._lock:
            handle = self._conn.cursor()
            for sql in statements:
                await handle.execute(sql)
            return await asyncio.wait_for(self._conn.commit(), 30)

    async def outside_the_lock(self, destination):
        async with self._lock:
            sql = self._render()
            await self._conn.execute(sql)
        await destination.flush()  # foreign await AFTER release: fine

    def _render(self):
        return "SELECT 1"
