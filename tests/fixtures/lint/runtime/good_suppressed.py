# etl-lint fixture: an inline `# etl-lint: ignore[...]` on the finding
# line suppresses exactly that rule.
# (no expectations: zero findings)
import time


async def reviewed_and_blessed():
    time.sleep(0.001)  # etl-lint: ignore[blocking-call-in-async] — 1ms calibration spin, measured harmless
