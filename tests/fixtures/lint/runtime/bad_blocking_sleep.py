# etl-lint fixture: blocking calls lexically inside async defs in a
# runtime/ path. Parsed by the analyzer, never imported.
# expect: blocking-call-in-async=4
import subprocess
import time


async def stalls_the_loop(path):
    time.sleep(0.5)
    subprocess.run(["pg_dump", path])
    with open(path) as f:
        return f.read()


async def executor_typo(loop):
    # classic mistake: the CALL runs eagerly on the loop, the executor
    # gets its (None) result — must be flagged, not exempted
    await loop.run_in_executor(None, time.sleep(5))
