# expect: unsynchronized-shared-mutation=2
"""The historical race shapes the concurrency tier exists to catch:
a worker thread and the event loop both rebinding shared attributes
with no common thread lock (the PR 12 retired-shard gauge leak and the
PR 13 stranded-lease accounting both matched this pattern — found by
chaos sampling then; found statically now)."""

import asyncio
import threading


class ProgressBoard:
    """Worker publishes, loop resets — no lock anywhere."""

    def __init__(self):
        self.applied_lsn = 0
        self.outstanding = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            self.applied_lsn = self.applied_lsn + 1  # worker-domain write
            self.outstanding = self.outstanding - 1  # worker-domain write

    async def reset(self):
        self.applied_lsn = 0  # loop-domain write: races _run
        self.outstanding = 0  # loop-domain write: races _run
        await asyncio.sleep(0)
