# etl-lint fixture: the approved shapes for everything the bad snippets
# do wrong — all six rules must stay quiet here.
# (no expectations: zero findings)
import asyncio

import numpy as np


async def sleeps_right():
    await asyncio.sleep(0.5)


async def fetch_off_loop(loop, pending):
    # device sync routed through the executor: the nested sync def is
    # exactly how blocking work legally leaves the event loop
    def fetch():
        return np.asarray(pending)

    return await loop.run_in_executor(None, fetch)


async def keeps_the_handle(coro):
    task = asyncio.create_task(coro)
    return await task


async def awaits_local():
    await sleeps_right()
    await asyncio.gather(sleeps_right())


async def reraises_cancel(task):
    try:
        await task
    except asyncio.CancelledError:
        raise


async def cancel_then_drain(task):
    # the canonical shutdown idiom: the swallow IS the point — awaiting
    # a task we just cancelled raises its CancelledError into us; the
    # rule recognizes the shape, no suppression needed
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass
