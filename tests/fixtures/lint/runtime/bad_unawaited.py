# etl-lint fixture: locally-defined async defs called without
# await/gather/create_task — the coroutine object is built and dropped.
# expect: unawaited-coroutine=2
async def flush_progress():
    pass


def sync_caller():
    flush_progress()


class Worker:
    async def stop(self):
        pass

    def shutdown(self):
        self.stop()
