# expect: blocking-call-in-async=3
# The lexical rule misses every one of these:
#  - do_backoff wraps time.sleep one file away (chain finding #1);
#  - fetch_config wraps open() (chain finding #2);
#  - `snooze` is time.sleep behind an import alias (depth-0 alias
#    resolution, finding #3) — the hole annotations.py used to document.
from time import sleep as snooze

from .helpers_blocking import do_backoff, fetch_config


async def pump_with_helper_sleep():
    do_backoff(3)


async def load_with_helper_open():
    return fetch_config("/etc/etl.conf")


async def aliased_sleep():
    snooze(1)
