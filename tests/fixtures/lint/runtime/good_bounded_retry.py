# expect: unbounded-retry=0
"""Negative fixture: retry loops that back off, exit, or re-raise are
not unbounded."""

import asyncio


async def with_backoff(source, policy):
    attempt = 0
    while True:
        try:
            return await source.connect()
        except ConnectionError:
            await asyncio.sleep(policy.delay(attempt))
            attempt += 1


async def reraises(source):
    while True:
        try:
            return await source.connect()
        except ConnectionError:
            raise


async def exits(source):
    while True:
        try:
            return await source.connect()
        except ConnectionError:
            break


async def bounded_loop(source):
    # not `while True`: the loop condition bounds it
    attempts = 0
    while attempts < 5:
        try:
            return await source.connect()
        except ConnectionError:
            attempts += 1


async def narrow_catch(queue):
    # narrow, non-error control-flow exceptions are not retry swallows
    while True:
        try:
            return queue.get_nowait()
        except LookupError:
            await waiters_changed(queue)


async def waiters_changed(queue):
    return queue


async def policy_execute_is_backoff(policy, op):
    # RetryPolicy.execute owns the backoff schedule itself
    while True:
        try:
            return await policy.execute(op)
        except ConnectionError:
            continue


def nested_callback_swallow_is_not_the_loop(q, handler):
    # the swallowing handler lives in a nested def (a different
    # activation): the loop itself blocks on q.get() and never spins
    while True:
        item = q.get()

        def cb():
            try:
                handler(item)
            except OSError:
                pass

        cb()


async def raise_after_nested_def(source, wrap):
    # the raise EXITS the loop even though a nested def precedes it in
    # the same compound statement (walk-pruning regression)
    while True:
        try:
            return await source.connect()
        except ConnectionError:
            if wrap:
                def _note():
                    return "failed"
                raise
