"""The sanctioned loop crossings from a worker thread:
`call_soon_threadsafe` and resolving a concurrent.futures future the
loop awaits (wrapped by a @handoff seam)."""

import threading

from etl_tpu.analysis.annotations import handoff


class Notifier:
    def __init__(self, loop):
        self._loop = loop
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self):
        self._loop.call_soon_threadsafe(self._wake)

    def _wake(self):
        pass


class ResultPublisher:
    def __init__(self, loop, future):
        self._loop = loop
        self._future = future
        threading.Thread(target=self._run, daemon=True).start()

    @handoff
    def _run(self):
        # future resolution is the handoff edge; the loop side awaits it
        self._loop.call_soon(self._future.set_result, 1)
