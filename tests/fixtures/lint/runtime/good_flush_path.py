# etl-lint fixture: clean @flush_path dispatch — acks route through the
# bounded ack window (which owns the durability waits); an inline wait
# OUTSIDE any marked function (a destination's own internals, a test
# barrier) is fine.
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import flush_path


@flush_path
async def dispatch_flush(window, destination, events, commit_end):
    async def submit():
        return await destination.write_event_batches(events)

    window.dispatch(submit, commit_end_lsn=commit_end,
                    n_events=len(events))


@flush_path
async def copy_chunk(window, destination, schema, batch):
    await window.add(await destination.write_table_batch(schema, batch))


async def test_barrier(ack):
    # unmarked code may wait inline (tests, destination internals)
    await ack.wait_durable()
