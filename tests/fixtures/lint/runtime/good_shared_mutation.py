"""Every guard the concurrency tier recognizes, in one clean file:
lock-held writes, Condition-handoff writes, init-before-spawn, and an
explicit @handoff ownership-transfer seam."""

import asyncio
import threading

from etl_tpu.analysis.annotations import handoff


class LockedBoard:
    """Writes from both domains hold the SAME threading.Lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.applied_lsn = 0  # init-before-spawn: no thread exists yet
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            with self._lock:
                self.applied_lsn = self.applied_lsn + 1

    async def reset(self):
        with self._lock:
            self.applied_lsn = 0


class CondQueue:
    """Condition-handoff mediated: the Condition IS the mutex."""

    def __init__(self):
        self._cond = threading.Condition()
        self.item = None
        threading.Thread(target=self._consume, daemon=True).start()

    def _consume(self):
        with self._cond:
            while self.item is None:
                self._cond.wait()
            self.item = None

    async def publish(self, item):
        with self._cond:
            self.item = item
            self._cond.notify()


class FutureHandoff:
    """Ownership transfer through a declared @handoff seam: the result
    is published via a future the other domain awaits, so the write
    needs no lock — the future resolution is the happens-before edge."""

    def __init__(self):
        self.result = None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    @handoff
    def _run(self):
        self.result = 42  # published before the future resolves

    async def consume(self):
        await asyncio.sleep(0)
        return self.result
