"""Rule 8 negatives: every parking await is bounded, raced against
shutdown, or not a parking shape at all."""

import asyncio


async def or_shutdown(shutdown, aw):
    return await asyncio.wait_for(aw, 30.0)


async def consume_bounded(queue: asyncio.Queue):
    # timeout-bounded: the wrapper call is what gets awaited
    return await asyncio.wait_for(queue.get(), timeout=5.0)


async def consume_raced(shutdown, queue: asyncio.Queue):
    # shutdown-raced: same structural exemption
    return await or_shutdown(shutdown, queue.get())


async def wait_shutdown(shutdown_signal):
    # the shutdown signal IS the escape hatch the rule demands
    await shutdown_signal.wait()


async def select_tasks(tasks):
    # asyncio.wait takes arguments: not the zero-arg parking shape
    done, _ = await asyncio.wait(tasks, timeout=1.0)
    return done


class Pipeline:
    async def wait(self):
        await asyncio.sleep(0)

    async def shutdown_and_wait(self):
        # a method on the worker itself (self receiver), not an event
        await self.wait()


def sync_get(q):
    # not awaited: thread-queue pops are the InFlightWindow's business
    return q.get()
