# etl-lint fixture: blocking I/O and device traffic inside the
# autoscaling control loop's decision path (@control_loop,
# etl_tpu/autoscale) — the signal→policy→decision computation must be a
# pure function of (SignalFrame history, config); a blocking call ties
# decision latency to an external service, a device call ties
# shard-count control to accelerator health. Nested defs and lambdas
# inherit the frame flag.
# expect: control-loop-blocking-io=6
import time

import jax
import requests

from etl_tpu.analysis.annotations import control_loop


@control_loop
def evaluate_with_settle(history, current_k):
    time.sleep(0.5)  # blocking settle inside the decision: flagged
    return current_k + 1


@control_loop
def capacity_from_device(counter_dev):
    # the decision must read HOST state (sampled frames), never the chip
    return float(jax.device_get(counter_dev))  # flagged


@control_loop
def decide_from_dashboard(url, current_k):
    doc = requests.get(url).json()  # network I/O in the decision: flagged
    return max(current_k, doc["target"])


@control_loop
def decide_from_file(path, current_k):
    with open(path) as f:  # filesystem read in the decision: flagged
        return int(f.read())


@control_loop
def make_capacity_estimator(pending):
    def estimate():
        pending.block_until_ready()  # nested def inherits: flagged
        return 1.0

    return estimate


@control_loop
def make_backlog_reader(counter_dev):
    return lambda: jax.device_get(counter_dev)  # lambda inherits: flagged
