# etl-lint fixture: clean @control_loop decision path — pure arithmetic
# over already-sampled signal frames; blocking I/O OUTSIDE any marked
# function (the collector's sampling, the controller's actuation) is
# fine, and so is math/sorting inside the marked path.
# (no expectations: zero findings)
import math

from etl_tpu.analysis.annotations import control_loop


@control_loop
def rate_model_target(backlog_bytes, capacity_bytes_per_s, drain_slo_s):
    if backlog_bytes <= 0:
        return 0
    return math.ceil(backlog_bytes / (capacity_bytes_per_s * drain_slo_s))


@control_loop
def pick_laggiest_shard(frames):
    latest = frames[-1]
    return max(latest.shards, key=lambda s: s.lag_bytes)


def collector_sample(path):
    # sampling is NOT the decision path: file/registry reads belong here
    with open(path) as f:
        return f.read()
