# etl-lint fixture: blocking I/O and device traffic inside the fleet
# reconciler's decision path (@control_loop, etl_tpu/fleet) — the
# observe→diff→converge computation must be a pure function of
# (desired spec, observed shard map); a blocking call ties every
# pipeline's convergence to one external service, a device call ties
# fleet control to accelerator health. Nested defs and lambdas inherit
# the frame flag.
# expect: control-loop-blocking-io=6
import time

import jax
import requests

from etl_tpu.analysis.annotations import control_loop


@control_loop
def diff_with_settle(targets, observed):
    time.sleep(0.2)  # blocking settle inside the diff: flagged
    return [pid for pid in targets if pid not in observed]


@control_loop
def observed_k_from_device(counter_dev):
    # the diff must consume HOST state (the observe() snapshot),
    # never read the chip
    return int(jax.device_get(counter_dev))  # flagged


@control_loop
def targets_from_dashboard(url):
    doc = requests.get(url).json()  # network I/O in the diff: flagged
    return doc["targets"]


@control_loop
def spec_from_file(path):
    with open(path) as f:  # filesystem read in the diff: flagged
        return f.read()


@control_loop
def make_backlog_scorer(pending):
    def score():
        pending.block_until_ready()  # nested def inherits: flagged
        return 0.0

    return score


@control_loop
def make_shard_counter(counter_dev):
    return lambda: jax.device_get(counter_dev)  # lambda inherits: flagged
