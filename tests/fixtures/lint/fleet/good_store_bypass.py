"""The persist-then-actuate discipline: every multi-process store
mutation routes through ONE @handoff-marked seam, so a restarted
coordinator always resumes from a consistent journal."""

from etl_tpu.analysis.annotations import domain, handoff


class JournaledPusher:
    def __init__(self, store):
        self.store = store

    @handoff
    async def _save_spec(self, spec: dict) -> None:
        await self.store.update_fleet_spec(spec)

    @domain("coordinator")
    async def push(self, spec: dict) -> None:
        await self._save_spec(spec)
