# expect: coordinator-store-bypass=1
"""Coordinator-domain code mutating a multi-process StateStore surface
directly: a crash between this write and the actuation it implies
leaves the fleet and the journal disagreeing."""

from etl_tpu.analysis.annotations import domain


class SpecPusher:
    def __init__(self, store):
        self.store = store

    @domain("coordinator")
    async def push(self, spec: dict) -> None:
        await self.store.update_fleet_spec(spec)  # no persist seam
