# etl-lint fixture: clean fleet-reconciler decision path — placement
# and diff under @control_loop are pure arithmetic over the desired
# spec and an already-observed shard map; the reconciler's observe()
# (runtime listing, store reads) and actuation live OUTSIDE the marked
# path, where I/O belongs.
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import control_loop


@control_loop
def clamp_tenant_budget(pipelines, max_shards):
    # every pipeline keeps >= 1 shard; surplus dealt in id order
    targets = {p.pipeline_id: 1 for p in pipelines}
    budget = max_shards - len(targets)
    for p in sorted(pipelines, key=lambda q: q.pipeline_id):
        want = p.shard_count - 1
        grant = min(want, budget)
        targets[p.pipeline_id] += grant
        budget -= grant
    return targets


@control_loop
def diff_shard_map(targets, observed):
    deletes = sorted(pid for pid in observed if pid not in targets)
    creates = sorted(pid for pid in targets if pid not in observed)
    resizes = sorted(pid for pid, k in targets.items()
                     if pid in observed and observed[pid] != k)
    return deletes, creates, resizes


def observe_fleet(path):
    # sampling is NOT the decision path: file/store reads belong here
    with open(path) as f:
        return f.read()
