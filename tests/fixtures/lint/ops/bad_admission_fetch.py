# etl-lint fixture: blocking device traffic inside the batch-admission
# scheduler's grant path (@admission_path) — a fetch (device_get /
# block_until_ready / asarray) OR an upload (device_put: the
# @dispatch_stage sanction does NOT extend here) under the scheduler
# lock head-of-line-blocks every tenant's admission. The inline lag
# provider (a nested def/lambda) inherits the frame flag.
# expect: admission-blocking-fetch=5
import jax
import numpy as np

from etl_tpu.analysis.annotations import admission_path


@admission_path
def weight_from_device_counter(tenant, counter_dev):
    lag = jax.device_get(counter_dev)  # fetch under the lock: flagged
    return 1.0 + float(np.asarray(counter_dev)) + lag  # asarray: flagged


@admission_path
def grant_after_sync(tenant, pending):
    pending.block_until_ready()  # sync in the grant path: flagged
    return tenant


@admission_path
def admit_with_upload(tenant, weights, dev):
    # even an UPLOAD blocks every waiter behind this tenant's transfer
    return jax.device_put(weights, dev)  # flagged


@admission_path
def make_lag_provider(counter_dev):
    def lag_bytes():
        return float(jax.device_get(counter_dev))  # nested def: flagged

    return lag_bytes
