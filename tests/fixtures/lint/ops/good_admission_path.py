# etl-lint fixture: a clean admission grant path — weights and picks
# read HOST state only (LSN deltas, wall clock, plain counters); device
# traffic stays in the dispatch/fetch stages, so the rule stays quiet.
# A device fetch OUTSIDE any @admission_path function is also fine (it
# belongs to the consumer's fetch stage, rule 6's territory — and this
# one is not @hot_loop either).
# (no expectations: zero findings)
import time

import numpy as np

from etl_tpu.analysis.annotations import admission_path


@admission_path
def weight_from_lag(tenant, lag_scale):
    lag = max(0.0, float(tenant.received_lsn - tenant.durable_lsn))
    return 1.0 + lag / lag_scale


@admission_path
def pick_min_pass(waiters, starvation_s):
    now = time.monotonic()
    starved = [t for t in waiters if now - t.wait_since >= starvation_s]
    if starved:
        return min(starved, key=lambda t: t.wait_since)
    return min(waiters, key=lambda t: t.virtual_pass)


def fetch_at_consumer(pending):
    return np.asarray(pending.result())
