# etl-lint fixture: the decode pipeline's dispatch stage — a @hot_loop
# function where host→device UPLOADS (jax.device_put of a packed arena)
# are sanctioned by @dispatch_stage; the rule must stay quiet.
# (no expectations: zero findings)
import jax

from etl_tpu.analysis.annotations import dispatch_stage, hot_loop


@dispatch_stage
@hot_loop
def dispatch_packed(fn, bmat, lengths, dev):
    bmat = jax.device_put(bmat, dev)  # committed upload: rides the pipeline
    lengths = jax.device_put(lengths, dev)
    return fn(bmat, lengths)
