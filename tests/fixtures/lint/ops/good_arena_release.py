# The safe shapes: release in finally (covers exception paths), the
# context-manager form, release in except-with-reraise plus fall-through,
# and explicit ownership transfer (the lease ESCAPES via a call/return —
# the pipeline hands it to the fetch stage, which releases it there).


def finally_release(pool, decoder, staged):
    lease = pool.lease()
    try:
        return decoder.pack(staged, arena=lease)
    finally:
        lease.release()


def with_release(pool, decoder, staged):
    with pool.lease() as lease:
        return decoder.pack(staged, arena=lease)


def except_release_and_fallthrough(pool, decoder, staged):
    lease = pool.lease()
    try:
        packed = decoder.pack(staged, arena=lease)
    except BaseException:
        lease.release()
        raise
    lease.release()
    return packed


def ownership_transfer(pool, decoder, staged, handle):
    lease = pool.lease()
    packed = decoder.pack(staged, arena=lease)
    handle.set_result((packed, lease))
