# expect: donated-buffer-use=2
# Reading a buffer after passing it in a donate_argnums position: the
# device owns the allocation now (XLA reuses it for scratch/output on
# TPU/GPU); the host read sees poisoned memory.
import jax

_DECODE = jax.jit(lambda b, w: b, donate_argnums=(0,))


def module_level_donate(bmat, widths):
    out = _DECODE(bmat, widths)
    checksum = bmat.sum()  # bmat was donated
    return out, checksum


def local_donate(kernel, bmat, lengths):
    fn = jax.jit(kernel, donate_argnums=(0, 1))
    out = fn(bmat, lengths)
    return out, lengths[0]  # lengths was donated
