# etl-lint fixture: @dispatch_stage sanctions UPLOADS only — fetch-side
# transfers (asarray / device_get / block_until_ready) inside the
# dispatch stage still serialize the pipeline and must be flagged, and a
# device_put in a plain @hot_loop function (no @dispatch_stage) is still
# a finding.
# expect: hot-loop-host-transfer=3
import jax
import numpy as np

from etl_tpu.analysis.annotations import dispatch_stage, hot_loop


@dispatch_stage
@hot_loop
def dispatch_then_fetch(fn, bmat, lengths, dev):
    out = fn(jax.device_put(bmat, dev), lengths)  # upload: sanctioned
    out.block_until_ready()  # fetch-side sync: flagged
    return np.asarray(out)  # fetch: flagged


@hot_loop
def upload_outside_dispatch_stage(bmat, dev):
    return jax.device_put(bmat, dev)  # no @dispatch_stage: flagged
