# etl-lint fixture: host transfers inside a @hot_loop function — each
# one serializes the hot path against the device link.
# expect: hot-loop-host-transfer=2
import numpy as np

from etl_tpu.analysis.annotations import hot_loop


@hot_loop
def dispatch_and_fetch(packed):
    packed.block_until_ready()
    return np.asarray(packed)
