# expect: arena-lease-leak=2
# Two leak shapes the CFG pass proves path-sensitively:
#  - conditional release: the not-taken branch reaches EXIT holding it;
#  - fall-through release with raising work in between and no finally:
#    an exception escapes holding the lease.


def conditional_release(pool, staged, ok):
    lease = pool.lease()
    buf = lease.take((staged.n_rows, 8), "uint8")
    if ok:
        lease.release()
    return buf


def release_after_raising_work(pool, decoder, staged):
    lease = pool.lease()
    packed = decoder.pack(staged, arena=lease)
    lease.release()
    return packed
