# expect: hot-loop-host-transfer=2
# The decorator is import-ALIASED — the lexical rule (which matches the
# terminal decorator name in-module) still sees `hl`, but the resolver
# follows the import to analysis.annotations.hot_loop. Both hot
# functions reach jax.device_get through helpers one file away.
from etl_tpu.analysis.annotations import hot_loop as hl

from .helpers_device import fetch_all


@hl
def dispatch_row(batch):
    return fetch_all(batch.pending)


@hl
def dispatch_nested(batch):
    def drain():
        return fetch_all(batch.pending)

    return drain()
