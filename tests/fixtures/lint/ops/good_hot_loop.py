# etl-lint fixture: dispatch-only @hot_loop function, with the fetch in
# an undecorated consumer — the hot-loop rule must stay quiet.
# (no expectations: zero findings)
import numpy as np

from etl_tpu.analysis.annotations import hot_loop


@hot_loop
def dispatch_only(fn, staged):
    return fn(staged)  # hands back the device future


def consumer_fetch(pending):
    return np.asarray(pending)  # not @hot_loop: fetch belongs here
