# Clean in isolation: sync helpers around device transfers are legal —
# the bug is reaching them from a @hot_loop function or the event loop
# (bad_transitive_hot.py / bad_transitive_device.py).
import jax


def fetch_all(values):
    return [jax.device_get(v) for v in values]


def force_upload(arr, dev):
    return jax.device_put(arr, dev)
