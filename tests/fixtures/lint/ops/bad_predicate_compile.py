# etl-lint fixture: publication row-filter compilation inside @hot_loop
# functions — binding re-resolves columns/literals and re-traces the
# fused device program PER BATCH instead of once at decoder construction.
# expect: hot-loop-row-materialization=2
from etl_tpu.analysis.annotations import hot_loop
from etl_tpu.ops.predicate import compile_row_filter, parse_row_filter


@hot_loop
def decode_batch(schema, staged, sql):
    pred = compile_row_filter(parse_row_filter(sql), schema)
    return pred.host_keep(staged)
