# etl-lint fixture: row filter compiled ONCE at decoder construction;
# the @hot_loop batch path only EVALUATES the compiled form — the rule
# must stay quiet.
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import hot_loop
from etl_tpu.ops.predicate import compile_row_filter


class Decoder:
    def __init__(self, schema, row_filter):
        # construction-time compile: the sanctioned place
        self._pred = compile_row_filter(row_filter, schema)

    @hot_loop
    def decode_batch(self, staged):
        return self._pred.host_keep(staged)
