# Safe donation patterns: read BEFORE the donating call, rebind the name
# after (fresh buffer), non-donated positions stay readable, and the
# donated result itself is the output.
import jax

_DECODE = jax.jit(lambda b, w: b, donate_argnums=(0,))


def read_before_dispatch(bmat, widths):
    checksum = bmat.sum()
    out = _DECODE(bmat, widths)
    return out, checksum


def rebind_after_dispatch(bmat, widths, fresh):
    out = _DECODE(bmat, widths)
    bmat = fresh()
    return out, bmat.sum()


def non_donated_positions(bmat, widths):
    out = _DECODE(bmat, widths)
    return out, widths[0]  # position 1 is not donated
