# etl-lint fixture: blocking-call-in-async is scoped to runtime/,
# postgres/, api/ — the same call in destinations/ is out of scope —
# and a broad containment handler shielded by an earlier
# CancelledError re-raise is not a cancellation swallow.
# (no expectations: zero findings)
import asyncio
import time


async def out_of_scope_retry_backoff():
    time.sleep(0.1)


async def contained_panic(task):
    try:
        await task
    except asyncio.CancelledError:
        raise
    except BaseException:
        # shielded: the handler above re-raises cancellation, so this
        # broad containment never sees CancelledError (the runtime/
        # broad-except check still applies there — not here)
        return None
