# etl-lint fixture: row materialization inside @hot_loop batch-encode
# entry points — the columnar egress path rebuilding per-row Python
# objects (TableRow construction, batch expansion, row transposes).
# expect: hot-loop-row-materialization=4
from etl_tpu.analysis.annotations import hot_loop
from etl_tpu.destinations.base import expand_batch_events
from etl_tpu.models.table_row import ColumnarBatch, TableRow


@hot_loop
def encode_batch_via_rows(schema, batch, labels, seqs):
    rows = batch.to_rows()  # per-row boxing on the hot path
    rebuilt = ColumnarBatch.from_rows(schema, rows)  # and back again
    out = []
    for i, row in enumerate(rows):
        out.append(TableRow(list(row.values)))  # a third copy per row
    return rebuilt, out


@hot_loop
def write_batches_by_expansion(events):
    return expand_batch_events(events)  # the row path wearing a batch hat
