# etl-lint fixture: @transactional_commit entry points that land CDC
# data without ever consulting the commit-range parameter — the write
# happens but its WAL coordinate range is never recorded alongside it,
# so crash recovery cannot rebuild the sink's high-water mark: a silent
# downgrade to at-least-once behind a transactional marker. Nested
# write closures (the retried attempt() shape) belong to the marked
# function's body and are in scope too.
# expect: uncoordinated-transactional-write=3
from etl_tpu.analysis.annotations import transactional_commit


class ForgetfulDestination:
    @transactional_commit
    async def write_event_batches_committed(self, events, commit):
        # flagged: forwards to the plain path, commit never touched
        return await self.write_event_batches(events)

    @transactional_commit
    async def write_committed_retried(self, events, commit):
        async def attempt():
            # flagged: the closure writes, the marked frame never
            # derives a token / marker from `commit`
            return await self.inner.write_events(events)

        return await attempt()


@transactional_commit
async def route_committed(sink, events, commit):
    # flagged: free-function seam, coordinates dropped on the floor
    return await sink.write_table_batch(None, events)
