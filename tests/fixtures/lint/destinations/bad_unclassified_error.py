# etl-lint fixture: broad `except Exception` on destination write paths
# that re-raises WITHOUT wrapping in EtlError/ErrorKind — the
# unclassified failure reaches the worker retry layer bare, where the
# poison-isolation trigger (models.errors.POISON_KINDS) can never fire.
# Nested `attempt()` closures inside a write_* function are in scope
# too, as is any @flush_path function.
# expect: unclassified-destination-error=3
from etl_tpu.analysis.annotations import flush_path


class LeakyDestination:
    async def write_events(self, events):
        try:
            await self._post(events)
        except Exception:
            raise  # flagged: bare re-raise, nothing classified

    async def write_table_rows(self, schema, batch):
        async def attempt():
            try:
                return await self._post(batch)
            except Exception as e:
                raise RuntimeError(f"write failed: {e}")  # flagged:
                # re-raised as another unclassified exception

        return await attempt()

    async def _post(self, payload):
        return payload


@flush_path
async def dispatch_unclassified(destination, events):
    try:
        return await destination.write_event_batches(events)
    except Exception as e:
        raise ValueError(str(e))  # flagged: @flush_path frame
