# etl-lint fixture: coordinated @transactional_commit entry points —
# every committed write derives its dedup token / commit marker from
# the commit-range parameter (or consults it to choose a deliberate
# pass-through, the offset-token sink shape), so rule 20 stays quiet.
from etl_tpu.analysis.annotations import transactional_commit


class CoordinatedDestination:
    @transactional_commit
    async def write_event_batches_committed(self, events, commit):
        # token-armed write: data + coordinates land together
        self._arm_dedup(commit.token())
        try:
            return await self.write_event_batches(events)
        finally:
            self._disarm_dedup()

    @transactional_commit
    async def offset_token_committed(self, events, commit):
        if not commit.replay:
            # the plain path's offset tokens already ARE the
            # coordinates — consulting `commit` is the decision
            return await self.write_event_batches(events)
        return await self._replay_write(events, commit.token())
