# etl-lint fixture: clean destination write paths — every broad handler
# that re-raises wraps through the shared classifiers or a typed
# EtlError; handlers that never re-raise, narrow handlers, and broad
# handlers OUTSIDE write paths are out of this rule's scope.
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import flush_path
from etl_tpu.destinations.util import classify_write_exception
from etl_tpu.models.errors import ErrorKind, EtlError


class ClassifiedDestination:
    async def write_events(self, events):
        try:
            return await self._post(events)
        except Exception as e:
            raise classify_write_exception("fixture", e)  # wrapped: ok

    async def write_table_rows(self, schema, batch):
        try:
            return await self._post(batch)
        except Exception as e:
            raise EtlError(ErrorKind.DESTINATION_FAILED, repr(e))  # ok

    async def write_event_batches(self, events):
        try:
            return await self._post(events)
        except ValueError:
            raise EtlError(ErrorKind.DESTINATION_REJECTED, "bad value")
        # narrow handler: out of scope even if it re-raised bare

    async def startup(self):
        try:
            await self._post(None)
        except Exception:
            raise  # not a write path, not @flush_path: out of scope

    async def _post(self, payload):
        return payload


@flush_path
async def dispatch_classified(destination, events):
    try:
        return await destination.write_event_batches(events)
    except Exception as e:
        raise classify_write_exception("fixture", e)
