# etl-lint fixture: the sanctioned shapes around rule 13 — a @hot_loop
# encoder that stays columnar, and row materialization in UNDECORATED
# fallback/compat functions (the shim lives outside the hot path).
# (no expectations: zero findings)
from etl_tpu.analysis.annotations import hot_loop
from etl_tpu.destinations.base import expand_batch_events
from etl_tpu.models.table_row import ColumnarBatch


@hot_loop
def encode_batch_columnar(schema, batch, labels, seqs):
    cells = [c.data for c in batch.columns]  # column storage, no rows
    return cells, labels, seqs


def legacy_row_fallback(schema, events, rows):
    # not @hot_loop: the compatibility shim expands and transposes freely
    expanded = expand_batch_events(events)
    return ColumnarBatch.from_rows(schema, rows), expanded
