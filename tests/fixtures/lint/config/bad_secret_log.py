# expect: secret-in-log=4
"""Secret-typed values reaching exported surfaces: a Secret config
field %-formatted into a log line, a bare secret-named local in an
f-string handed to a logger, an `.expose()` unwrap concatenated into an
exception message, and a secret attribute as a metric label value."""

import logging

log = logging.getLogger("etl_tpu.config")


def log_connection(config, password):
    log.info("connecting with password=%s", config.password)
    log.debug(f"dsn built for {password}")


def fail_auth(secret):
    raise ValueError("bad credentials: " + secret.expose())


def emit_metric(registry, config):
    registry.counter_inc("etl_auth_failures_total",
                         labels={"key": config.api_key})
