"""Secrets handled correctly around exported surfaces: presence/shape
logged instead of values, secret read OUTSIDE the sink expression,
exception messages and metric labels built from non-secret fields."""

import logging

log = logging.getLogger("etl_tpu.config")


def log_connection(config):
    has_password = config.password is not None
    log.info("connecting as %s (password=%s)", config.username,
             "[set]" if has_password else "[unset]")


def fail_auth(config):
    raise ValueError(f"auth failed for user {config.username}")


def emit_metric(registry, config):
    registry.counter_inc("etl_auth_failures_total",
                         labels={"destination": config.name})
