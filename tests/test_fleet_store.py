"""Fleet control-plane persistence: the desired-state spec document and
the per-pipeline actuation journals on every StateStore dialect —
memory, sqlite (file-backed restart), and Postgres over the
from-scratch wire client against the socket-level fake server — plus
the version-regression refusals, the STORE_FLEET_COMMIT failpoint's
crash-consistency (refused write mutates nothing), and the
ShardScopedStore read-forward / write-refuse split."""

import pytest

from etl_tpu.config import PgConnectionConfig
from etl_tpu.fleet import (ActuationJournal, FleetSpec, PipelineSpec,
                           TenantQuota, VERB_CREATE)
from etl_tpu.models.errors import ErrorKind, EtlError
from etl_tpu.store.memory import MemoryStore
from etl_tpu.store.sql import PostgresStore, SqliteStore


def sample_spec(version: int = 1) -> FleetSpec:
    return FleetSpec(
        spec_version=version,
        pipelines=(PipelineSpec(pipeline_id=1, tenant_id="acme",
                                shard_count=2, profile="insert_heavy"),
                   PipelineSpec(pipeline_id=2, tenant_id="globex",
                                shard_count=4, profile="tiny_txs",
                                destination="clickhouse",
                                config={"flush_ms": 50})),
        quotas={"acme": TenantQuota(max_shards=3, slo_weight=2.0)})


def sample_journal() -> dict:
    j = ActuationJournal()
    j.open(spec_version=1, verb=VERB_CREATE, from_k=0, to_k=2)
    return j.to_json()


class FleetStoreEnv:
    """One dialect's stores over shared backing storage: a second
    `make()` models a coordinator-process restart."""

    def __init__(self, dialect: str, tmp_path):
        self.dialect = dialect
        self.tmp_path = tmp_path
        self._server = None
        self._stores = []

    async def make(self, pipeline_id: int = 1):
        if self.dialect == "memory":
            # memory has no cross-process story; restarts reuse it
            if self._stores:
                return self._stores[0]
            s = MemoryStore()
        elif self.dialect == "sqlite":
            s = SqliteStore(self.tmp_path / "fleet.db", pipeline_id)
            await s.connect()
        else:
            if self._server is None:
                from etl_tpu.postgres.fake import FakeDatabase
                from etl_tpu.testing.fake_pg_server import FakePgServer

                self._server = FakePgServer(FakeDatabase())
                await self._server.start()
            s = PostgresStore(
                PgConnectionConfig(host="127.0.0.1",
                                   port=self._server.port,
                                   name="postgres", username="etl"),
                pipeline_id)
            await s.connect()
        self._stores.append(s)
        return s

    async def cleanup(self):
        for s in self._stores:
            try:
                await s.close()
            except Exception:
                pass
        if self._server is not None:
            await self._server.stop()


DIALECTS = ["memory", "sqlite", "postgres"]


@pytest.mark.parametrize("dialect", DIALECTS)
class TestFleetStoreDialects:
    async def test_spec_round_trips_across_restart(self, dialect, tmp_path):
        env = FleetStoreEnv(dialect, tmp_path)
        try:
            s1 = await env.make()
            assert await s1.get_fleet_spec() is None
            assert FleetSpec.from_json(await s1.get_fleet_spec()) \
                == FleetSpec()
            spec = sample_spec()
            await s1.update_fleet_spec(spec.to_json())

            s2 = await env.make()
            back = FleetSpec.from_json(await s2.get_fleet_spec())
            assert back == spec
            assert back.quotas["acme"].slo_weight == 2.0
            assert back.by_id()[2].config == {"flush_ms": 50}
        finally:
            await env.cleanup()

    async def test_spec_version_regression_refused(self, dialect, tmp_path):
        env = FleetStoreEnv(dialect, tmp_path)
        try:
            s = await env.make()
            await s.update_fleet_spec(sample_spec(version=3).to_json())
            with pytest.raises(EtlError) as e:
                await s.update_fleet_spec(sample_spec(version=2).to_json())
            assert e.value.kind is ErrorKind.PROGRESS_REGRESSION
            # the stored document is untouched by the refused write
            kept = FleetSpec.from_json(await s.get_fleet_spec())
            assert kept.spec_version == 3
            # same-version rewrite is an idempotent retry, not a
            # regression — a coordinator may repeat a write it cannot
            # prove landed
            await s.update_fleet_spec(sample_spec(version=3).to_json())
        finally:
            await env.cleanup()

    async def test_journal_round_trip_and_id_regression(self, dialect,
                                                        tmp_path):
        env = FleetStoreEnv(dialect, tmp_path)
        try:
            s1 = await env.make()
            assert await s1.get_fleet_journal(7) is None
            assert await s1.get_fleet_journals() == {}
            await s1.update_fleet_journal(7, sample_journal())
            await s1.update_fleet_journal(9, sample_journal())

            s2 = await env.make()
            back = ActuationJournal.from_json(await s2.get_fleet_journal(7))
            assert back.pending() is not None
            assert back.pending().verb == VERB_CREATE
            assert set((await s2.get_fleet_journals()).keys()) == {7, 9}
            # next_id moving backwards = a stale coordinator's write
            with pytest.raises(EtlError) as e:
                await s2.update_fleet_journal(7, {"next_id": 1,
                                                  "entries": []})
            assert e.value.kind is ErrorKind.PROGRESS_REGRESSION
        finally:
            await env.cleanup()


class TestFleetCommitFailpoint:
    async def test_refused_spec_write_mutates_nothing(self):
        from etl_tpu.chaos import failpoints

        store = MemoryStore()

        def boom():
            raise EtlError(ErrorKind.STATE_STORE_FAILED, "chaos")

        failpoints.arm(failpoints.STORE_FLEET_COMMIT, boom)
        try:
            with pytest.raises(EtlError):
                await store.update_fleet_spec(sample_spec().to_json())
            assert await store.get_fleet_spec() is None
        finally:
            failpoints.disarm_all()

    async def test_refused_journal_write_mutates_nothing(self):
        from etl_tpu.chaos import failpoints

        store = MemoryStore()
        await store.update_fleet_journal(3, sample_journal())

        def boom():
            raise EtlError(ErrorKind.STATE_STORE_FAILED, "chaos")

        failpoints.arm(failpoints.STORE_FLEET_COMMIT, boom)
        try:
            with pytest.raises(EtlError):
                await store.update_fleet_journal(3, {"next_id": 5,
                                                     "entries": []})
        finally:
            failpoints.disarm_all()
        # the journal the coordinator reads back is the pre-crash one
        kept = ActuationJournal.from_json(await store.get_fleet_journal(3))
        assert kept.next_id == sample_journal()["next_id"]

    async def test_site_is_registered_for_chaos_runs(self):
        from etl_tpu.chaos import failpoints

        assert failpoints.STORE_FLEET_COMMIT in failpoints.CHAOS_SITES
        assert failpoints.STORE_FLEET_COMMIT in failpoints.ASYNC_STALL_SITES


class TestShardScopedFleetSurface:
    async def test_reads_forward_and_writes_refuse(self):
        from etl_tpu.sharding.runtime import ShardIdentity, ShardScopedStore

        store = MemoryStore()
        scoped = ShardScopedStore(store, ShardIdentity(1, 0, 2, 0))
        await store.update_fleet_spec(sample_spec().to_json())
        await store.update_fleet_journal(1, sample_journal())

        # a pod may inspect the fleet's desired state...
        spec = FleetSpec.from_json(await scoped.get_fleet_spec())
        assert spec.spec_version == 1
        assert (await scoped.get_fleet_journal(1))["next_id"] == 2
        assert set((await scoped.get_fleet_journals()).keys()) == {1}

        # ...but only the coordinator, on the RAW store, may move it
        with pytest.raises(EtlError) as e:
            await scoped.update_fleet_spec(sample_spec(version=2).to_json())
        assert e.value.kind is ErrorKind.SHARD_NOT_OWNED
        with pytest.raises(EtlError) as e:
            await scoped.update_fleet_journal(1, sample_journal())
        assert e.value.kind is ErrorKind.SHARD_NOT_OWNED
