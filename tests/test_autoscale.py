"""etl-autoscale (ISSUE 13): policy properties (monotone response,
hysteresis no-flap, cooldown enforcement, max-step), signal
serialization + seeded-timeline determinism, the decision journal's
persistence (memory + sqlite) and resume idempotence, controller
actuation/overlap/resume/abort against stub coordinators, admission SLO
weights, the orchestrator scale seam, the replay CLI's deterministic
trace, the bench reaction-time gate, and the two chaos scenarios in
tier-1."""

from __future__ import annotations

import asyncio
import json
import random

import pytest

from etl_tpu.autoscale import (ACTION_DOWN, ACTION_HOLD, ACTION_UP,
                               AutoscaleController, AutoscaleJournal,
                               AutoscalePolicy, AutoscalePolicyConfig,
                               DecisionRecord, RegistrySignalSource,
                               STATUS_APPLIED, STATUS_PENDING,
                               ShardSignals, SignalFrame, SignalTimeline,
                               seeded_surge_timeline)
from etl_tpu.autoscale.controller import STATUS_ABORTED
from etl_tpu.autoscale.policy import simulate
from etl_tpu.models.errors import ErrorKind, EtlError
from etl_tpu.sharding import ShardAssignment
from etl_tpu.sharding.shardmap import STATUS_REBALANCING, STATUS_STEADY
from etl_tpu.store import MemoryStore


def frame(tick: int, lags, durables=None, *, pressure=False,
          healthy=True, at_s=None) -> SignalFrame:
    durables = durables or [0] * len(lags)
    return SignalFrame(
        tick=tick, at_s=float(tick if at_s is None else at_s),
        shards=tuple(
            ShardSignals(shard=s, lag_bytes=lag, durable_lsn=dur,
                         memory_pressure=pressure, healthy=healthy)
            for s, (lag, dur) in enumerate(zip(lags, durables))))


def steady_history(ticks: int, lag_per_shard: int, shards: int = 2,
                   drain_rate: int = 1000) -> list:
    """`ticks` frames at a constant backlog with a constant observed
    drain rate — fixed capacity evidence for the rate-model tests."""
    return [frame(t, [lag_per_shard] * shards,
                  [t * drain_rate] * shards) for t in range(ticks)]


CFG = AutoscalePolicyConfig(
    min_shards=1, max_shards=8, drain_slo_s=10.0,
    up_backlog_bytes=100_000, down_backlog_bytes=10_000,
    up_ticks=2, down_ticks=2, cooldown_ticks=4,
    capacity_floor_bytes_per_s=1000.0)


class TestPolicyProperties:
    def test_config_validation(self):
        with pytest.raises(EtlError):
            AutoscalePolicyConfig(min_shards=0).validate()
        with pytest.raises(EtlError):
            AutoscalePolicyConfig(max_shards=1, min_shards=2).validate()
        with pytest.raises(EtlError):  # inverted hysteresis bands
            AutoscalePolicyConfig(up_backlog_bytes=10,
                                  down_backlog_bytes=20).validate()
        with pytest.raises(EtlError):
            AutoscalePolicyConfig(drain_slo_s=0).validate()

    def test_monotone_response(self):
        """More backlog never lowers the target: raw_target is monotone
        in backlog at fixed capacity, and the applied decision never
        moves DOWN while a larger backlog would have moved it UP."""
        policy = AutoscalePolicy(CFG)
        targets = []
        decisions = []
        for backlog in range(0, 2_000_000, 50_000):
            targets.append(policy.raw_target(backlog, 1000.0))
            hist = steady_history(4, backlog // 2)
            decisions.append(policy.evaluate(hist, 2, None))
        assert targets == sorted(targets)
        # decision monotonicity: the applied target as a function of
        # backlog is non-decreasing too (hold=2, up=3; never down at
        # high backlog after an up at lower backlog)
        applied = [d.target_k for d in decisions]
        for a, b in zip(applied, applied[1:]):
            assert b >= a or b >= 2, (applied,)

    def test_hysteresis_dead_zone_never_flaps(self):
        """A noisy signal oscillating INSIDE the band gap decides
        nothing, ever — the dead zone is the no-flap guarantee."""
        rng = random.Random(13)
        frames = [frame(t, [rng.randrange(
            CFG.down_backlog_bytes // 2 + 1, CFG.up_backlog_bytes // 2)
            for _ in range(2)]) for t in range(50)]
        decisions = simulate(frames, AutoscalePolicy(CFG), 2)
        assert all(d.action == ACTION_HOLD for d in decisions)

    def test_noisy_band_edge_never_flaps(self):
        """Seeded noise oscillating ACROSS the up band edge every other
        tick never scales up: the sustained-votes threshold (up_ticks=2
        consecutive frames) filters single-frame spikes."""
        policy = AutoscalePolicy(CFG)
        frames = []
        for t in range(60):
            over = t % 2 == 0
            per_shard = (CFG.up_backlog_bytes // 2 + 5_000) if over \
                else (CFG.up_backlog_bytes // 2 - 5_000)
            frames.append(frame(t, [per_shard, per_shard]))
        decisions = simulate(frames, policy, 2)
        assert all(d.action == ACTION_HOLD for d in decisions)

    def test_sustained_surge_scales_up_max_step(self):
        policy = AutoscalePolicy(CFG)
        frames = [frame(t, [500_000, 500_000]) for t in range(4)]
        d = policy.evaluate(frames, 2, None)
        assert d.action == ACTION_UP
        assert d.target_k == 3  # K -> K+1, never a jump
        assert d.raw_target_k > 3  # the rate model wanted more

    def test_cooldown_enforced(self):
        """After an applied decision, no further decision until
        cooldown_ticks evaluations pass — even with the votes there."""
        policy = AutoscalePolicy(CFG)
        frames = [frame(t, [500_000, 500_000]) for t in range(12)]
        history = []
        last = None
        decided_at = []
        k = 2
        for f in frames:
            history.append(f)
            d = policy.evaluate(history, k, last)
            if d.action != ACTION_HOLD:
                decided_at.append(d.tick)
                k = d.target_k
                last = d.tick
        assert decided_at, "surge never decided"
        for a, b in zip(decided_at, decided_at[1:]):
            assert b - a >= CFG.cooldown_ticks
        # and the holds in between say why
        d = policy.evaluate(frames[:decided_at[0] + 2], 3, decided_at[0])
        assert d.action == ACTION_HOLD and "cooldown" in d.reason

    def test_scale_down_needs_quiet_and_rate_model_agreement(self):
        policy = AutoscalePolicy(CFG)
        quiet = [frame(t, [100, 100], [t * 1000] * 2) for t in range(6)]
        d = policy.evaluate(quiet, 3, None)
        assert d.action == ACTION_DOWN and d.target_k == 2

    def test_min_max_clamps(self):
        policy = AutoscalePolicy(CFG)
        quiet = [frame(t, [0, 0]) for t in range(6)]
        assert policy.evaluate(quiet, CFG.min_shards,
                               None).action == ACTION_HOLD
        surge = [frame(t, [10**7] * 8) for t in range(6)]
        assert policy.evaluate(surge, CFG.max_shards,
                               None).action == ACTION_HOLD

    def test_unhealthy_shard_holds(self):
        policy = AutoscalePolicy(CFG)
        surge = [frame(t, [500_000, 500_000], healthy=(t < 5))
                 for t in range(6)]
        d = policy.evaluate(surge, 2, None)
        assert d.action == ACTION_HOLD and "unhealthy" in d.reason

    def test_memory_pressure_vetoes_scale_down(self):
        policy = AutoscalePolicy(CFG)
        quiet = [frame(t, [100, 100], pressure=True) for t in range(6)]
        d = policy.evaluate(quiet, 3, None)
        assert d.action == ACTION_HOLD and "pressure" in d.reason

    def test_capacity_estimate_from_drain_rates(self):
        """Median of the best per-shard durable-advance rates; floored
        when there is no evidence."""
        policy = AutoscalePolicy(CFG)
        hist = [frame(t, [0, 0], [t * 5000, t * 3000]) for t in range(5)]
        cap = policy.estimate_capacity(hist)
        assert cap == 5000.0  # median of {5000, 3000} -> upper-mid
        assert policy.estimate_capacity([hist[0]]) \
            == CFG.capacity_floor_bytes_per_s
        idle = [frame(t, [0, 0], [7, 7]) for t in range(5)]
        assert policy.estimate_capacity(idle) \
            == CFG.capacity_floor_bytes_per_s

    def test_empty_history_is_typed_error(self):
        with pytest.raises(EtlError):
            AutoscalePolicy(CFG).evaluate([], 2, None)


class TestSignals:
    def test_frame_json_round_trip(self):
        f = frame(3, [100, 200], [10, 20], pressure=True)
        back = SignalFrame.from_json(json.loads(json.dumps(f.to_json())))
        assert back == f
        assert back.aggregate_backlog_bytes == 300
        assert back.any_memory_pressure and back.all_healthy

    def test_timeline_round_trip_and_tick_regression(self):
        tl = SignalTimeline(max_frames=8)
        tl.record(frame(0, [1]))
        tl.record(frame(1, [2]))
        back = SignalTimeline.from_json(tl.to_json())
        assert [f.tick for f in back.frames] == [0, 1]
        with pytest.raises(EtlError):
            back.record(frame(1, [3]))

    def test_timeline_bound(self):
        tl = SignalTimeline(max_frames=3)
        for t in range(10):
            tl.record(frame(t, [t]))
        assert [f.tick for f in tl.frames] == [7, 8, 9]

    def test_seeded_timeline_deterministic_and_seed_sensitive(self):
        a = seeded_surge_timeline(7).to_json()
        b = seeded_surge_timeline(7).to_json()
        c = seeded_surge_timeline(8).to_json()
        assert a == b
        assert a != c

    def test_registry_source_reads_published_gauges(self):
        from etl_tpu.telemetry.metrics import (ETL_SHARD_DELIVERED_EVENTS,
                                               ETL_SLOT_LAG_BYTES,
                                               registry)

        registry.gauge_set(ETL_SLOT_LAG_BYTES, 12_345,
                           {"shard": "0"})
        registry.gauge_set(ETL_SLOT_LAG_BYTES, 54_321,
                           {"shard": "1"})
        registry.gauge_set(ETL_SHARD_DELIVERED_EVENTS, 99, {"shard": "0"})
        src = RegistrySignalSource(2)
        f = asyncio.run(src.sample(0.0))
        assert f.shards[0].lag_bytes == 12_345
        assert f.shards[1].lag_bytes == 54_321
        assert f.shards[0].delivered_events == 99
        assert f.aggregate_backlog_bytes == 12_345 + 54_321

    def test_registry_source_tracks_live_shard_count(self):
        """On an autoscaled fleet the collector must follow the CURRENT
        K: a pinned count would keep sampling a retired shard's
        never-cleared lag gauge after a scale-down (inflating backlog
        forever) and miss new shards after a scale-up."""
        from etl_tpu.telemetry.metrics import ETL_SLOT_LAG_BYTES, registry

        for s in range(3):
            registry.gauge_set(ETL_SLOT_LAG_BYTES, 1_000 * (s + 1),
                               {"shard": str(s)})
        holder = {"k": 3}
        src = RegistrySignalSource(lambda: holder["k"])
        assert asyncio.run(src.sample(0.0)).shard_count == 3
        holder["k"] = 2  # scale-down: shard 2's stale gauge must drop out
        f = asyncio.run(src.sample(1.0))
        assert f.shard_count == 2
        assert f.aggregate_backlog_bytes == 1_000 + 2_000


class TestJournal:
    def test_round_trip_and_pending(self):
        j = AutoscaleJournal()
        rec = j.open_decision(
            _decision(ACTION_UP, 2, 3, tick=5), epoch_before=0)
        assert j.pending() == rec and rec.decision_id == 1
        back = AutoscaleJournal.from_json(j.to_json())
        assert back.pending() == rec and back.next_id == 2
        back.settle(rec.decision_id, STATUS_APPLIED)
        assert back.pending() is None
        assert back.last_applied_tick() == 5

    def test_entry_bound(self):
        j = AutoscaleJournal(max_entries=4)
        for i in range(10):
            rec = j.open_decision(
                _decision(ACTION_UP, 2, 3, tick=i), epoch_before=0)
            j.settle(rec.decision_id, STATUS_APPLIED)
        assert len(j.entries) == 4
        assert j.next_id == 11  # ids survive the bound

    async def _store_round_trip(self, store):
        assert await store.get_autoscale_journal() is None
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3, tick=1), 0)
        await store.update_autoscale_journal(j.to_json())
        back = AutoscaleJournal.from_json(
            await store.get_autoscale_journal())
        assert back.pending() is not None and back.pending().to_k == 3
        # id regression refused (a stale controller must not rewind)
        with pytest.raises(EtlError) as e:
            await store.update_autoscale_journal({"next_id": 0,
                                                  "entries": []})
        assert e.value.kind is ErrorKind.PROGRESS_REGRESSION

    async def test_memory_store_persistence(self):
        await self._store_round_trip(MemoryStore())

    async def test_sqlite_store_persistence(self, tmp_path):
        from etl_tpu.store.sql import SqliteStore

        store = SqliteStore(tmp_path / "as.db", 1)
        await store.connect()
        try:
            await self._store_round_trip(store)
            # restart: a SECOND store over the same file reads through
            other = SqliteStore(tmp_path / "as.db", 1)
            await other.connect()
            try:
                back = AutoscaleJournal.from_json(
                    await other.get_autoscale_journal())
                assert back.pending() is not None
            finally:
                await other.close()
        finally:
            await store.close()

    async def test_shard_scoped_store_refuses_journal_writes(self):
        from etl_tpu.sharding.runtime import ShardIdentity, ShardScopedStore

        store = MemoryStore()
        scoped = ShardScopedStore(store, ShardIdentity(1, 0, 2, 0))
        await store.update_autoscale_journal({"next_id": 2, "entries": []})
        assert (await scoped.get_autoscale_journal())["next_id"] == 2
        with pytest.raises(EtlError) as e:
            await scoped.update_autoscale_journal({"next_id": 3,
                                                   "entries": []})
        assert e.value.kind is ErrorKind.SHARD_NOT_OWNED

    async def test_journal_commit_failpoint(self):
        from etl_tpu.chaos import failpoints
        from etl_tpu.models.errors import ErrorKind as EK

        store = MemoryStore()

        def boom():
            raise EtlError(EK.STATE_STORE_FAILED, "chaos")

        failpoints.arm(failpoints.STORE_AUTOSCALE_COMMIT, boom)
        try:
            with pytest.raises(EtlError):
                await store.update_autoscale_journal({"next_id": 1,
                                                      "entries": []})
            assert await store.get_autoscale_journal() is None
        finally:
            failpoints.disarm_all()


def _decision(action, from_k, to_k, tick=0):
    from etl_tpu.autoscale.policy import Decision

    return Decision(tick=tick, action=action, current_k=from_k,
                    target_k=to_k, raw_target_k=to_k,
                    backlog_bytes=0, capacity_bytes_per_s=1.0,
                    reason="test")


class _StubCollector:
    def __init__(self, frames):
        self.frames = list(frames)
        self.i = 0

    async def sample(self, at_s: float) -> SignalFrame:
        f = self.frames[min(self.i, len(self.frames) - 1)]
        self.i += 1
        return f


class _StubResult:
    def __init__(self, from_k, to_k, epoch):
        self.old_epoch = epoch
        self.new_epoch = epoch + 1
        self.old_shard_count = from_k
        self.new_shard_count = to_k
        self.fence_lsn = 100
        self.moved = {}
        self.duration_s = 0.0


class _StubCoordinator:
    """ShardCoordinator-shaped stub tracking the persisted assignment in
    a MemoryStore like the real one does."""

    def __init__(self, store, k=2, epoch=0):
        self.store = store
        self.calls: list[str] = []
        self._seed = ShardAssignment(epoch=epoch, shard_count=k)

    async def current(self, bootstrap_shard_count: int = 1):
        a = await self.store.get_shard_assignment()
        if a is None:
            a = self._seed
            await self.store.update_shard_assignment(a)
        return a

    async def add_shard(self):
        a = await self.current()
        self.calls.append("add")
        new = ShardAssignment(epoch=a.epoch + 1,
                              shard_count=a.shard_count + 1)
        await self.store.update_shard_assignment(new)
        return _StubResult(a.shard_count, new.shard_count, a.epoch)

    async def remove_shard(self):
        a = await self.current()
        self.calls.append("remove")
        new = ShardAssignment(epoch=a.epoch + 1,
                              shard_count=a.shard_count - 1)
        await self.store.update_shard_assignment(new)
        return _StubResult(a.shard_count, new.shard_count, a.epoch)

    async def abort_rebalance(self):
        a = await self.current()
        self.calls.append("abort")
        await self.store.update_shard_assignment(ShardAssignment(
            epoch=a.epoch, shard_count=a.shard_count,
            status=STATUS_STEADY))


def _controller(store, coordinator, frames, **kw):
    return AutoscaleController(
        store=store, pipeline_id=1, collector=_StubCollector(frames),
        coordinator=coordinator, policy=AutoscalePolicy(CFG), **kw)


class TestController:
    async def test_tick_applies_scale_up_and_journals(self):
        store = MemoryStore()
        coord = _StubCoordinator(store)
        rolls = []

        async def on_scale(from_k, to_k, result):
            rolls.append((from_k, to_k, result.new_epoch))

        surge = [frame(t, [500_000, 500_000]) for t in range(4)]
        c = _controller(store, coord, surge, scale_listener=on_scale)
        holds = [await c.tick(0.0)]  # first vote: hold
        d = await c.tick(1.0)  # second vote: actuates
        assert holds[0].action == ACTION_HOLD
        assert d.action == ACTION_UP and d.target_k == 3
        assert coord.calls == ["add"]
        assert rolls == [(2, 3, 1)]
        j = AutoscaleJournal.from_json(await store.get_autoscale_journal())
        assert j.pending() is None
        assert [ (r.action, r.status) for r in j.entries ] \
            == [(ACTION_UP, STATUS_APPLIED)]

    async def test_overlap_refused_while_pending(self):
        store = MemoryStore()
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3), 0)
        await store.update_autoscale_journal(j.to_json())
        surge = [frame(t, [500_000, 500_000]) for t in range(4)]
        c = _controller(store, coord, surge)
        for t in range(2):
            d = await c.tick(float(t))
            assert d.action == ACTION_HOLD
        assert "in_flight" in d.reason
        assert coord.calls == []

    async def test_overlap_refused_while_rebalancing(self):
        store = MemoryStore()
        await store.update_shard_assignment(ShardAssignment(
            epoch=0, shard_count=2, status=STATUS_REBALANCING,
            fence_lsn=5, next_shard_count=3))
        coord = _StubCoordinator(store)
        surge = [frame(t, [500_000, 500_000]) for t in range(4)]
        c = _controller(store, coord, surge)
        await c.tick(0.0)
        d = await c.tick(1.0)
        assert d.action == ACTION_HOLD and "in_flight" in d.reason

    async def test_resume_redrives_pending_transition(self):
        store = MemoryStore()
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3), 0)
        await store.update_autoscale_journal(j.to_json())
        c = _controller(store, coord, [frame(0, [0, 0])])
        settled = await c.resume()
        assert settled.status == STATUS_APPLIED
        assert coord.calls == ["add"]
        assert (await coord.current()).shard_count == 3
        # idempotent: nothing pending anymore
        assert await c.resume() is None
        assert coord.calls == ["add"]

    async def test_resume_after_flip_is_noop_beyond_journal(self):
        """Crash between epoch flip and journal mark: re-running the
        persisted decision must NOT re-actuate — it only settles the
        journal (and replays the idempotent fleet roll)."""
        store = MemoryStore()
        await store.update_shard_assignment(
            ShardAssignment(epoch=1, shard_count=3))
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3), 0)
        await store.update_autoscale_journal(j.to_json())
        rolls = []

        async def on_scale(from_k, to_k, result):
            rolls.append((from_k, to_k))

        c = _controller(store, coord, [frame(0, [0, 0])],
                        scale_listener=on_scale)
        settled = await c.resume()
        assert settled.status == STATUS_APPLIED
        assert coord.calls == []  # no topology action
        assert rolls == [(2, 3)]  # the roll re-applies idempotently

    async def test_restart_does_not_inherit_foreign_tick_cooldown(self):
        """The journal's decision ticks belong to the process that wrote
        them. A successor whose collector counts from 0 again must NOT
        read a persisted tick-700 decision as a (negative-age) permanent
        cooldown — the cooldown re-anchors at the restart and expires
        normally."""
        store = MemoryStore()
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        rec = j.open_decision(_decision(ACTION_UP, 2, 3, tick=700), 0)
        j.settle(rec.decision_id, STATUS_APPLIED)
        await store.update_autoscale_journal(j.to_json())
        surge = [frame(t, [500_000, 500_000]) for t in range(12)]
        c = _controller(store, coord, surge)
        actions = []
        for t in range(CFG.cooldown_ticks + CFG.up_ticks + 1):
            d = await c.tick(float(t))
            actions.append(d.action)
        # held through the re-anchored cooldown, then decided — never
        # stuck until the fresh counter overtakes 700
        assert ACTION_UP in actions, actions
        assert actions.index(ACTION_UP) >= CFG.cooldown_ticks - 1

    async def test_resume_abort_after_flip_settles_applied(self):
        """An epoch flip is not abortable: abort=True on a decision
        whose flip already happened must settle it APPLIED and roll the
        fleet — marking it aborted would strand a flipped assignment
        with an un-rolled fleet (moved tables owned by nobody)."""
        store = MemoryStore()
        await store.update_shard_assignment(
            ShardAssignment(epoch=1, shard_count=3))
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3), 0)
        await store.update_autoscale_journal(j.to_json())
        rolls = []

        async def on_scale(from_k, to_k, result):
            rolls.append((from_k, to_k))

        c = _controller(store, coord, [frame(0, [0, 0])],
                        scale_listener=on_scale)
        settled = await c.resume(abort=True)
        assert settled.status == STATUS_APPLIED
        assert coord.calls == []  # neither abort nor re-actuation
        assert rolls == [(2, 3)]

    async def test_resume_abort_rolls_back(self):
        store = MemoryStore()
        await store.update_shard_assignment(ShardAssignment(
            epoch=0, shard_count=2, status=STATUS_REBALANCING,
            fence_lsn=5, next_shard_count=3))
        coord = _StubCoordinator(store)
        j = AutoscaleJournal()
        j.open_decision(_decision(ACTION_UP, 2, 3), 0)
        await store.update_autoscale_journal(j.to_json())
        c = _controller(store, coord, [frame(0, [0, 0])])
        settled = await c.resume(abort=True)
        assert settled.status == STATUS_ABORTED
        assert coord.calls == ["abort"]
        back = AutoscaleJournal.from_json(
            await store.get_autoscale_journal())
        assert back.pending() is None

    async def test_actuation_failure_leaves_pending_entry(self):
        store = MemoryStore()

        class FailingCoordinator(_StubCoordinator):
            async def add_shard(self):
                raise EtlError(ErrorKind.TIMEOUT, "quiesce timed out")

        coord = FailingCoordinator(store)
        surge = [frame(t, [500_000, 500_000]) for t in range(4)]
        c = _controller(store, coord, surge)
        await c.tick(0.0)
        with pytest.raises(EtlError):
            await c.tick(1.0)
        j = AutoscaleJournal.from_json(await store.get_autoscale_journal())
        assert j.pending() is not None  # a successor resumes or aborts

    def test_slo_weights_feed_admission(self):
        from etl_tpu.ops.pipeline import AdmissionScheduler

        sched = AdmissionScheduler(2)
        store = MemoryStore()
        c = AutoscaleController(
            store=store, pipeline_id=1,
            collector=_StubCollector([frame(0, [0, 0])]),
            coordinator=_StubCoordinator(store),
            slo_weights={"cdc": 4.0, "copy": 0.5})
        c.apply_slo_weights(sched)
        t_cdc = sched.register("cdc-0")
        t_copy = sched.register("copy-16384-1")
        t_other = sched.register("other")
        assert sched._weight(t_cdc) == 4.0  # prefix match, no lag reader
        assert sched._weight(t_copy) == 0.5
        assert sched._weight(t_other) == 1.0
        # exact beats prefix; clamped into [1/max, max]
        sched.set_slo_weight("cdc-0", 1000.0)
        assert sched._weight(t_cdc) == sched._max_weight
        for t in (t_cdc, t_copy, t_other):
            t.close()

    def test_slo_weight_composes_with_lag(self):
        from etl_tpu.ops.pipeline import AdmissionScheduler

        sched = AdmissionScheduler(2, lag_scale_bytes=1024,
                                   max_weight=32.0)
        sched.set_slo_weight("gold", 2.0)
        gold = sched.register("gold", lag_bytes=lambda: 1024)
        plain = sched.register("plain", lag_bytes=lambda: 1024)
        assert sched._weight(gold) == pytest.approx(4.0)  # 2.0 x (1+1)
        assert sched._weight(plain) == pytest.approx(2.0)
        gold.close()
        plain.close()


class TestOrchestratorScaleSeam:
    async def test_scale_pipeline_reapplies_spec_with_new_k(self):
        from etl_tpu.api.orchestrator import Orchestrator, ReplicatorSpec

        class Recorder(Orchestrator):
            def __init__(self):
                self.started = []

            async def start_pipeline(self, spec):
                self.started.append(spec)

            async def stop_pipeline(self, pipeline_id):
                pass

            async def status(self, pipeline_id):
                raise NotImplementedError

        orch = Recorder()
        spec = ReplicatorSpec(pipeline_id=1, tenant_id="t",
                              config={"shard": 1, "shard_count": 2,
                                      "publication": "pub"})
        await orch.scale_pipeline(spec, 3)
        (started,) = orch.started
        assert started.shard is None and started.shard_count == 3
        assert started.config["shard_count"] == 3
        assert "shard" not in started.config  # stale pin stripped
        assert started.config["publication"] == "pub"
        with pytest.raises(EtlError):
            await orch.scale_pipeline(spec, 0)


class TestReplayCli:
    def test_synthetic_trace_is_deterministic(self, capsys):
        from etl_tpu.autoscale.__main__ import main

        args = ["--synthetic", "--seed", "7", "--holds",
                "--min-shards", "2", "--max-shards", "3",
                "--drain-slo-s", "2", "--up-backlog-bytes", "262144",
                "--down-backlog-bytes", "65536"]
        assert main(args) == 0
        first = capsys.readouterr().out
        assert main(args) == 0
        second = capsys.readouterr().out
        assert first == second
        lines = [json.loads(line) for line in first.splitlines()]
        summary = lines[-1]
        assert summary["summary"] and summary["frames"] == 40
        actions = [d["action"] for d in summary["decisions"]]
        assert "scale_up" in actions and "scale_down" in actions
        # every evaluation printed with --holds: one line per frame
        assert len(lines) == 40 + 1

    def test_replay_file_round_trip(self, tmp_path, capsys):
        from etl_tpu.autoscale.__main__ import main

        path = tmp_path / "signals.json"
        path.write_text(json.dumps(seeded_surge_timeline(9).to_json()))
        assert main(["--replay", str(path), "--min-shards", "2",
                     "--up-backlog-bytes", "262144",
                     "--down-backlog-bytes", "65536"]) == 0
        out = capsys.readouterr().out
        summary = json.loads(out.splitlines()[-1])
        assert summary["source"] == str(path)
        assert summary["start_k"] == 2

    def test_malformed_input_exits_2(self, tmp_path, capsys):
        from etl_tpu.autoscale.__main__ import main

        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert main(["--replay", str(bad)]) == 2
        capsys.readouterr()


class TestBenchGate:
    def test_reaction_time_gate_green(self):
        import importlib.util
        import sys
        from pathlib import Path

        path = Path(__file__).resolve().parents[1] / "bench.py"
        spec = importlib.util.spec_from_file_location("_bench_as", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_bench_as"] = mod
        spec.loader.exec_module(mod)
        out = mod.run_autoscale_bench(seed=7, reaction_ticks_max=3)
        assert out["ok"], out["failures"]
        assert out["reaction_ticks"] <= 3
        assert out["scale_down_tick"] - out["scale_up_tick"] \
            >= out["cooldown_ticks"]
        assert out["deterministic"]


class TestChaosScenarios:
    async def test_surge_drain_end_to_end(self):
        from etl_tpu.chaos.autoscale import run_autoscale_surge_drain
        from etl_tpu.telemetry.metrics import ETL_SLOT_LAG_BYTES, registry

        run = await run_autoscale_surge_drain(seed=7)
        assert run.ok, run.report.describe()
        assert [d["action"] for d in run.decision_trace] == (
            ["hold"] * 3 + ["scale_up"] + ["hold"] * 2 + ["scale_down"])
        assert run.k_track[-1] == 2 and 3 in run.k_track
        assert run.union_matches
        # satellite: the apply loops published the per-slot lag gauge on
        # their status cadence (the series the collector + operators read)
        assert registry.get_gauge(ETL_SLOT_LAG_BYTES,
                                  {"shard": "0"}) is not None

    async def test_controller_crash_resumes_via_journal(self):
        from etl_tpu.chaos.autoscale import run_autoscale_controller_crash

        run = await run_autoscale_controller_crash(seed=7)
        assert run.ok, run.report.describe()
        entries = run.journal.get("entries", [])
        assert [(e["action"], e["status"]) for e in entries] \
            == [("scale_up", "applied")]
        assert any(r.kind == "crash" for r in run.restarts)
