"""Pinned-snapshot route tests (reference: etl-api's insta snapshot
suites, crates/etl-api/tests — 9.2k LoC of pinned route responses).

Each case drives a route and compares the FULL response document
(status + body) against a snapshot committed under tests/snapshots/.
Any change to a response shape — field added, renamed, re-typed,
status changed — fails until the snapshot is re-pinned, making API
surface drift an explicit, reviewed event instead of an accident.

Re-pin intentionally with:  UPDATE_SNAPSHOTS=1 pytest tests/test_api_snapshots.py

The suite runs on BOTH storage backends (the autouse fixture in
test_api.py does not apply here; this module pins shape parity
explicitly): a response that differs between sqlite and Postgres is a
bug by definition, so both backends must match the same snapshot.
"""

import json
import os
from pathlib import Path

import pytest

from tests.test_api import H, StubOrchestrator, make_client

SNAP_DIR = Path(__file__).parent / "snapshots"


@pytest.fixture(params=["sqlite", "postgres"])
def api_backend(request):
    import tests.test_api as ta

    old = ta._BACKEND
    ta._BACKEND = request.param
    yield request.param
    ta._BACKEND = old


def assert_snapshot(name: str, doc) -> None:
    path = SNAP_DIR / f"{name}.json"
    rendered = json.dumps(doc, indent=2, sort_keys=True)
    if os.environ.get("UPDATE_SNAPSHOTS", "0") not in ("", "0", "false"):
        SNAP_DIR.mkdir(exist_ok=True)
        if path.exists():
            # re-pin runs parameterize over BOTH backends: the second
            # backend must MATCH what the first just wrote, not silently
            # overwrite it — a divergence is a bug, even mid-re-pin
            assert json.loads(path.read_text()) == doc, (
                f"backends disagree while re-pinning {path.name}:\n"
                f"{rendered}")
            return
        path.write_text(rendered + "\n")
        return
    assert path.exists(), \
        f"missing snapshot {path.name}; run with UPDATE_SNAPSHOTS=1"
    pinned = json.loads(path.read_text())
    assert doc == pinned, (
        f"response drifted from snapshot {path.name}\n"
        f"got:     {rendered}\n"
        f"pinned:  {json.dumps(pinned, indent=2, sort_keys=True)}")


async def snap(name, resp):
    text = await resp.text()
    body = json.loads(text) \
        if text and resp.content_type == "application/json" else text
    assert_snapshot(name, {"status": resp.status, "body": body})


class TestRouteSnapshots:
    async def test_full_surface(self, tmp_path, api_backend):
        client, _ = await make_client(tmp_path, StubOrchestrator())
        try:
            await snap("tenant_create", await client.post(
                "/v1/tenants", json={"id": "acme", "name": "Acme"}))
            await snap("tenant_conflict", await client.post(
                "/v1/tenants", json={"id": "acme", "name": "Acme"}))
            await snap("tenant_missing_header",
                       await client.get("/v1/sources"))

            await snap("source_create", await client.post(
                "/v1/sources", headers=H,
                json={"name": "prod", "config": {
                    "host": "db", "port": 5432, "name": "app",
                    "username": "etl", "password": "pw-1234567"}}))
            await snap("source_invalid_config", await client.post(
                "/v1/sources", headers=H,
                json={"name": "bad", "config": {"port": "nope"}}))
            await snap("source_get_masks_secrets",
                       await client.get("/v1/sources/1", headers=H))
            await snap("source_404",
                       await client.get("/v1/sources/99", headers=H))

            await snap("destination_create", await client.post(
                "/v1/destinations", headers=H,
                json={"name": "lake", "config": {
                    "type": "lake", "warehouse_path": "/tmp/wh"}}))

            await snap("pipeline_create", await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": 1, "destination_id": 1,
                      "publication_name": "pub"}))
            await snap("pipeline_get",
                       await client.get("/v1/pipelines/1", headers=H))
            await snap("pipeline_list",
                       await client.get("/v1/pipelines", headers=H))
            await snap("pipeline_missing_source", await client.post(
                "/v1/pipelines", headers=H,
                json={"source_id": 77, "destination_id": 1,
                      "publication_name": "pub"}))

            await snap("image_create", await client.post(
                "/v1/images", headers=H,
                json={"name": "repl:v1", "default": True}))
            await snap("image_list",
                       await client.get("/v1/images", headers=H))

            await snap("pipeline_start", await client.post(
                "/v1/pipelines/1/start", headers=H))
            await snap("pipeline_status",
                       await client.get("/v1/pipelines/1/status",
                                        headers=H))
            await snap("pipeline_version_pin", await client.post(
                "/v1/pipelines/1/version", headers=H,
                json={"image_id": 1}))
            await snap("image_delete_pinned", await client.delete(
                "/v1/images/1", headers=H))
            await snap("pipeline_version_unpin", await client.post(
                "/v1/pipelines/1/version", headers=H, json={}))
            await snap("pipeline_stop", await client.post(
                "/v1/pipelines/1/stop", headers=H))
            await snap("replication_status_no_store", await client.get(
                "/v1/pipelines/1/replication-status", headers=H))
            await snap("source_delete_in_use",
                       await client.delete("/v1/sources/1", headers=H))
            await snap("pipeline_delete",
                       await client.delete("/v1/pipelines/1", headers=H))
        finally:
            await client.close()

    async def test_openapi_document_pinned(self, tmp_path, api_backend):
        """The whole API surface, pinned: any route/schema addition or
        removal must re-pin this snapshot (surface drift is reviewed,
        not accidental)."""
        client, _ = await make_client(tmp_path, StubOrchestrator())
        try:
            await snap("openapi", await client.get("/openapi.json"))
        finally:
            await client.close()
